"""Benchmarks on the real device, mirroring the BASELINE.json configs.

1. **Scan battery** (BASELINE config 2 shape): fused single-pass analyzer
   scan over a 50M-row table — completeness, moments, min/max, HLL distinct,
   KLL quantile sketches.
2. **Column profiler** (BASELINE config 3 shape, the north-star metric):
   `ColumnProfilerRunner` full profile over a wide mixed-type table
   (numeric + string + categorical columns), reporting rows/sec/chip.

Each stage compares against a single-core pandas/numpy oracle computing the
same statistics on the same data (the stand-in for the reference's
Spark-local per-core throughput; the reference publishes no numbers,
BASELINE.md). After EVERY stage a parse-able partial-result JSON line goes
to stdout ("partial": true, with everything measured so far), so a timeout
in a late stage keeps the earlier numbers; the final complete line carries
"partial": false and the north-star profiler metric.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def write_stage_trace(stage: str) -> None:
    """Drain the flight-recorder ring into a per-stage Chrome trace
    artifact (DEEQU_TPU_TRACE_DIR, default ./bench-traces): every bench
    stage leaves its span tree behind, so a slow stage is explainable from
    the artifact without re-running under a profiler. Draining keeps each
    artifact scoped to its own stage."""
    import os

    try:
        from deequ_tpu.observability import export as obs_export
        from deequ_tpu.observability import recorder as obs_recorder
        from deequ_tpu.observability import trace as obs_trace

        if not obs_trace.enabled():
            return
        out_dir = os.environ.get("DEEQU_TPU_TRACE_DIR", "bench-traces")
        spans = obs_recorder().drain()
        if not spans:
            return
        path = obs_export.write_chrome_trace(
            os.path.join(out_dir, f"bench-{stage}.trace.json"), spans
        )
        log(f"[{stage}] trace artifact: {path} ({len(spans)} spans)")
    except Exception as exc:  # noqa: BLE001 - artifacts are advisory
        log(f"[{stage}] trace artifact failed: {exc}")


def monitor_phase_fields(mon) -> dict:
    """The per-stage observability fields the partial JSON records for every
    monitored stage (VERDICT r5 ask #1b): NEW program compiles this run
    (``compiles`` — a compile regression shows as a nonzero value on a warm
    stage), plus the state_fetch vs device_dispatch phase split the r6
    acceptance gate reads."""
    return {
        "compiles": mon.program_compiles,
        "state_fetch_s": round(mon.phase_seconds.get("state_fetch", 0.0), 3),
        "device_dispatch_s": round(
            mon.phase_seconds.get("device_dispatch", 0.0), 3
        ),
    }


# ---------------------------------------------------------------------------
# per-stage hard deadlines (VERDICT r5 weak #1: the driver's wall-clock kill
# must never erase completed stages' numbers — each stage now gets its own
# enforced budget and a graceful skip leaves the partial JSON intact)
# ---------------------------------------------------------------------------

STAGE_BUDGET_ENV = "DEEQU_TPU_BENCH_STAGE_BUDGET_S"


class StageDeadline(BaseException):
    """A stage blew its wall-clock budget (raised from SIGALRM).
    BaseException, so no stage-internal ``except Exception`` can swallow
    the deadline — the same reason KeyboardInterrupt sits outside
    Exception."""


def stage_budget_s() -> float:
    import os

    return float(os.environ.get(STAGE_BUDGET_ENV, "180"))


def subprocess_timeout_s() -> float:
    """Wall-clock cap for detached stage subprocesses (prewarm, grouping
    points): generous enough to absorb a cold XLA compile longer than one
    stage budget, bounded so a hung child can never wedge the bench."""
    return max(stage_budget_s() * 2, 300)


def run_stage_with_deadline(name: str, fn, *args, budget_s=None, **kwargs):
    """Run one stage under a HARD wall-clock deadline: SIGALRM interrupts
    the main thread mid-stage (numpy/pyarrow/XLA dispatch all return to the
    interpreter frequently enough for delivery), the stage is recorded as
    ``skipped_deadline`` and the bench moves on — a slow stage costs its
    own numbers, never the stages after it. ``budget_s`` overrides the
    default stage budget (the xla_prewarm stage exists to absorb a cold
    compile LONGER than one stage budget, so it runs under an enlarged
    deadline). Returns (result | None, status, seconds)."""
    import signal

    budget = stage_budget_s() if budget_s is None else float(budget_s)

    def on_alarm(signum, frame):
        raise StageDeadline(name)

    prior = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, budget)
    t0 = time.perf_counter()
    try:
        result = fn(*args, **kwargs)
        return result, "ok", time.perf_counter() - t0
    except StageDeadline:
        elapsed = time.perf_counter() - t0
        log(
            f"[{name}] exceeded its {budget:.0f}s stage budget after "
            f"{elapsed:.1f}s — skipped (partial JSON keeps earlier stages)"
        )
        return None, "skipped_deadline", elapsed
    except Exception as exc:
        # a failing stage (dead subprocess, missing native lib, env issue)
        # costs its own numbers, never the stages after it — the same
        # contract the deadline path keeps. SystemExit (parity mismatch)
        # and KeyboardInterrupt still abort the bench.
        elapsed = time.perf_counter() - t0
        log(f"[{name}] stage FAILED after {elapsed:.1f}s: {exc!r}")
        return None, "failed", elapsed
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, prior)


# ---------------------------------------------------------------------------
# stage 1: scan battery (BASELINE config 2)
# ---------------------------------------------------------------------------


def build_scan_data(rows: int):
    import pyarrow as pa

    rng = np.random.default_rng(42)
    cols = {}
    for i in range(4):
        vals = rng.normal(100 * i, 10, rows)
        nulls = rng.random(rows) < 0.05
        cols[f"x{i}"] = pa.array(vals, mask=nulls)
    cols["cat"] = pa.array(rng.integers(0, 100_000, rows))
    return pa.table(cols)


def scan_battery():
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLParameters,
        KLLSketch,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
        Sum,
    )

    analyzers = []
    for i in range(4):
        c = f"x{i}"
        analyzers += [
            Completeness(c), Mean(c), Sum(c), Minimum(c), Maximum(c),
            StandardDeviation(c),
        ]
    analyzers.append(ApproxCountDistinct("cat"))
    analyzers += [KLLSketch("x0", KLLParameters(2048, 0.64, 100)),
                  KLLSketch("x1", KLLParameters(2048, 0.64, 100))]
    return analyzers


def run_scan_stage(rows: int, batch_size: int) -> dict:
    import pyarrow as pa

    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import RunMonitor

    log(f"[scan] building {rows:,}-row table")
    table = build_scan_data(rows)
    data = Dataset.from_arrow(table)
    analyzers = scan_battery()

    warm = Dataset.from_arrow(table.slice(0, batch_size))
    AnalysisRunner.do_analysis_run(warm, analyzers, batch_size=batch_size)

    mon = RunMonitor()
    t0 = time.perf_counter()
    ctx = AnalysisRunner.do_analysis_run(
        data, analyzers, batch_size=batch_size, monitor=mon
    )
    elapsed = time.perf_counter() - t0
    assert mon.passes == 1
    scan_phases = monitor_phase_fields(mon)
    tpu_vals = {}
    for a, m in ctx.metric_map.items():
        if m.value.is_success and a.name in ("Completeness", "Mean", "Sum"):
            tpu_vals[f"{a.name}:{a.instance}"] = m.value.get()

    df = table.to_pandas()
    t0 = time.perf_counter()
    base_vals = {}
    for i in range(4):
        c = f"x{i}"
        s = df[c]
        base_vals[f"Completeness:{c}"] = s.notna().mean()
        base_vals[f"Mean:{c}"] = s.mean()
        base_vals[f"Sum:{c}"] = s.sum()
        s.min(); s.max(); s.std(ddof=0)
    df["cat"].nunique()
    np.nanquantile(df["x0"].to_numpy(), np.linspace(0.01, 1, 100))
    np.nanquantile(df["x1"].to_numpy(), np.linspace(0.01, 1, 100))
    base_s = time.perf_counter() - t0

    for k, v in base_vals.items():
        tv = tpu_vals[k]
        if abs(tv - v) > 1e-6 * max(1.0, abs(v)):
            log(f"PARITY MISMATCH {k}: tpu={tv} oracle={v}")
            sys.exit(1)
    rate = rows / elapsed
    phases = ", ".join(f"{k}={v:.2f}s" for k, v in sorted(mon.phase_seconds.items()))
    log(
        f"[scan] {rows:,} rows x {len(analyzers)} analyzers: {elapsed:.2f}s "
        f"({rate/1e6:.2f}M rows/s/chip), single-core pandas {base_s:.2f}s "
        f"-> {rate/(rows/base_s):.1f}x"
    )
    log(f"[scan] placement={mon.placement} phases: {phases}")
    return {
        "rows_per_sec": rate,
        "vs_single_core": rate / (rows / base_s),
        **scan_phases,
    }


# ---------------------------------------------------------------------------
# stage 2: column profiler on a wide mixed table (BASELINE config 3)
# ---------------------------------------------------------------------------

N_NUMERIC = 16
N_STRING = 4
N_CAT = 4


def build_wide_data(rows: int, n_numeric=N_NUMERIC, n_string=N_STRING, n_cat=N_CAT):
    import pyarrow as pa

    rng = np.random.default_rng(7)
    cols = {}
    for i in range(n_numeric):
        vals = rng.normal(10 * i, 1 + i, rows)
        if i % 3 == 0:
            cols[f"n{i}"] = pa.array(vals, mask=rng.random(rows) < 0.02)
        else:
            cols[f"n{i}"] = pa.array(vals)
    base = np.array([f"id_{i:07d}" for i in range(100_000)])
    for i in range(n_string):
        cols[f"s{i}"] = pa.array(base[rng.integers(0, len(base), rows)])
    for i in range(n_cat):
        card = 20 * (i + 1)
        cats = np.array([f"c{j}" for j in range(card)])
        cols[f"c{i}"] = pa.array(cats[rng.integers(0, card, rows)])
    return pa.table(cols)


def build_lineitem_data(rows: int):
    """TPC-H lineitem-shaped synthetic (BASELINE config 3): the 16 lineitem
    columns with realistic types/cardinalities — 4 int keys, 4 numeric
    measures, 2 flags, 3 dates (strings), ship instruction/mode categories,
    and a high-cardinality comment column (dictionary-encoded pool)."""
    import pyarrow as pa

    rng = np.random.default_rng(19)
    cols = {}
    cols["l_orderkey"] = pa.array(rng.integers(1, max(rows // 4, 2), rows))
    cols["l_partkey"] = pa.array(rng.integers(1, 200_001, rows))
    cols["l_suppkey"] = pa.array(rng.integers(1, 10_001, rows))
    cols["l_linenumber"] = pa.array(rng.integers(1, 8, rows))
    cols["l_quantity"] = pa.array(rng.integers(1, 51, rows).astype(np.float64))
    cols["l_extendedprice"] = pa.array(np.round(rng.uniform(900, 105_000, rows), 2))
    cols["l_discount"] = pa.array(np.round(rng.uniform(0, 0.10, rows), 2))
    cols["l_tax"] = pa.array(np.round(rng.uniform(0, 0.08, rows), 2))
    flags = np.array(["A", "N", "R"])
    cols["l_returnflag"] = pa.array(flags[rng.integers(0, 3, rows)])
    status = np.array(["F", "O"])
    cols["l_linestatus"] = pa.array(status[rng.integers(0, 2, rows)])
    day0 = np.datetime64("1992-01-01")
    for name in ("l_shipdate", "l_commitdate", "l_receiptdate"):
        days = rng.integers(0, 2526, rows)  # 1992-01-01 .. 1998-12-01
        dates = (day0 + days.astype("timedelta64[D]")).astype("datetime64[D]")
        dic = pa.array(np.unique(dates).astype(str))
        codes = pa.array(
            np.searchsorted(np.unique(days), days).astype(np.int32)
        )
        cols[name] = pa.DictionaryArray.from_arrays(codes, dic)
    instr = np.array(["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"])
    cols["l_shipinstruct"] = pa.array(instr[rng.integers(0, 4, rows)])
    modes = np.array(["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"])
    cols["l_shipmode"] = pa.array(modes[rng.integers(0, 7, rows)])
    pool = np.array(
        [f"comment text fragment number {i} about the order" for i in range(1_000_000)]
    )
    codes = pa.array(rng.integers(0, len(pool), rows).astype(np.int32))
    cols["l_comment"] = pa.DictionaryArray.from_arrays(codes, pa.array(pool))
    return pa.table(cols)


#: rows the single-core pandas oracle actually runs on; its RATE is what we
#: compare against (per-row cost of these stats is constant, and a smaller
#: working set flatters the baseline's caches, so the ratio is conservative)
ORACLE_ROWS_CAP = 10_000_000

#: memoized single-core oracle rates keyed by the row count they ran on:
#: the device_profile (config-3) stage and the host profile stage share one
#: measurement instead of paying the pandas pass twice
_ORACLE_RATE_MEMO: dict = {}


def lineitem_single_core_rate(table, oracle_rows: int) -> float:
    """Single-core pandas oracle rate (rows/s) over a lineitem-shaped
    table: the same WORK the profiler does per reference semantics —
    completeness, approx-distinct, the numeric battery incl. quantiles,
    value histograms for low-cardinality columns, and per-value regex type
    inference on string columns (`profiles/ColumnProfiler.scala:122-139`).
    Categorical (dictionary) columns classify their categories only — the
    same advantage our engine takes. Memoized per row count so the
    config-3 stage and the host profile stage measure it once."""
    cached = _ORACLE_RATE_MEMO.get(oracle_rows)
    if cached is not None:
        return cached
    import pandas as pd

    from deequ_tpu.runners.features import (
        _BOOLEAN_RE,
        _FRACTIONAL_RE,
        _INTEGRAL_RE,
    )

    def classify_series(s):
        if isinstance(s.dtype, pd.CategoricalDtype):
            cats = pd.Series(s.cat.categories.astype(object))
            cls = np.select(
                [
                    cats.str.fullmatch(_FRACTIONAL_RE),
                    cats.str.fullmatch(_INTEGRAL_RE),
                    cats.str.fullmatch(_BOOLEAN_RE),
                ],
                [1, 2, 3],
                default=4,
            )
            np.bincount(cls[s.cat.codes[s.cat.codes >= 0]], minlength=5)
            return
        sv = s.dropna()  # already str-typed; no re-stringification timed
        cls = np.select(
            [
                sv.str.fullmatch(_FRACTIONAL_RE),
                sv.str.fullmatch(_INTEGRAL_RE),
                sv.str.fullmatch(_BOOLEAN_RE),
            ],
            [1, 2, 3],
            default=4,
        )
        np.bincount(cls, minlength=5)

    df = table.slice(0, oracle_rows).to_pandas()
    t0 = time.perf_counter()
    for name in df.columns:
        s = df[name]
        s.notna().mean()
        nunique = s.nunique()
        if s.dtype.kind in "if":
            s.mean(); s.min(); s.max(); s.std(ddof=0); s.sum()
            np.nanquantile(
                s.to_numpy(dtype=np.float64), np.linspace(0.01, 1, 100)
            )
        elif s.dtype == object or isinstance(s.dtype, pd.CategoricalDtype):
            classify_series(s)
        if nunique <= 120:
            s.value_counts()
    base_rate = oracle_rows / (time.perf_counter() - t0)
    _ORACLE_RATE_MEMO[oracle_rows] = base_rate
    return base_rate


def run_profile_stage(rows: int) -> dict:
    from deequ_tpu.data import Dataset
    from deequ_tpu.profiles import ColumnProfilerRunner
    from deequ_tpu.runners.engine import RunMonitor

    log(f"[profile] building {rows:,}-row TPC-H-lineitem-shaped table (16 cols)")
    table = build_lineitem_data(rows)
    data = Dataset.from_arrow(table)

    # warmup on a slice: compile every program shape the profile needs
    warm = Dataset.from_arrow(table.slice(0, 1 << 18))
    ColumnProfilerRunner.on_data(warm).run()

    mon = RunMonitor()
    t0 = time.perf_counter()
    profiles = ColumnProfilerRunner.on_data(data).with_monitor(mon).run()
    elapsed = time.perf_counter() - t0
    rate = rows / elapsed
    phases = ", ".join(f"{k}={v:.2f}s" for k, v in sorted(mon.phase_seconds.items()))
    log(f"[profile] passes={mon.passes} placement={mon.placement} phases: {phases}")

    # full-data numeric parity guard (cheap numpy reductions)
    for name in ("l_quantity", "l_extendedprice", "l_discount", "l_tax"):
        arr = table[name].to_numpy()
        p = profiles.profiles[name]
        for got, want in (
            (p.mean, arr.mean()), (p.minimum, arr.min()), (p.maximum, arr.max()),
            (p.std_dev, arr.std()), (p.sum, arr.sum()),
        ):
            if abs(got - want) > 1e-6 * max(1.0, abs(want)):
                log(f"PARITY MISMATCH {name}: got={got} want={want}")
                sys.exit(1)

    # single-core pandas oracle on a capped subsample; compare RATES (see
    # lineitem_single_core_rate for the oracle's work definition — shared,
    # memoized, with the config-3 device_profile stage)
    oracle_rows = min(rows, ORACLE_ROWS_CAP)
    base_rate = lineitem_single_core_rate(table, oracle_rows)

    complete = len(profiles.profiles)
    vs_single = rate / base_rate
    log(
        f"[profile] {rows:,} rows x 16 cols ({complete} profiled): "
        f"{elapsed:.2f}s ({rate/1e6:.2f}M rows/s/chip); single-core pandas "
        f"{base_rate/1e6:.2f}M rows/s on {oracle_rows:,} rows -> {vs_single:.1f}x "
        f"single-core, {vs_single/64:.2f}x a hypothetical perfectly-linear "
        f"64-core baseline"
    )
    return {
        "rows_per_sec": rate,
        "vs_single_core": vs_single,
        "vs_64core_linear": vs_single / 64,
        **monitor_phase_fields(mon),
    }


# ---------------------------------------------------------------------------
# stage 2b: DEVICE-RESIDENT fused scan + sketch merge (VERDICT r3 ask #1:
# quantify the TPU itself — batches live in device memory, no tunnel/feed in
# the timed path, so the number is the chip's, not the link's)
# ---------------------------------------------------------------------------


def run_device_resident_stage(
    rows_per_batch: int = 1 << 20, n_batches: int = 2, target_seconds: float = 5.0
) -> dict:
    """Chip-side throughput of the PRODUCTION program: chained donated
    dispatches of the fused packed-carry update over device-resident
    feature batches.

    TIMING METHODOLOGY: on relayed/tunnel device transports,
    ``jax.block_until_ready`` can return before execution finishes (the
    ready-flag round-trips before the work drains), which silently inflated
    earlier rounds' numbers ~8x. Every timed region here therefore ends
    with a FULL host fetch (``np.asarray``) of the final states — the fetch
    forces real completion, and its own cost is amortized over the whole
    chain of dispatches."""
    import jax

    from deequ_tpu.data import Dataset
    from deequ_tpu.runners.engine import ScanEngine

    import jax.numpy as jnp
    from jax import random as jrandom

    analyzers = scan_battery()
    engine = ScanEngine(analyzers, placement="device")
    # ONE tiny real batch establishes the exact feature keys/dtypes the
    # fused program consumes; the full-size batches are then generated ON
    # DEVICE (same shapes/dtypes/distributions), so the stage quantifies
    # chip compute without paying 30-110s of tunnel feed for data whose
    # values the timing does not depend on (streaming-stage parity checks
    # cover correctness)
    tiny_rows = 1 << 10
    table = build_scan_data(tiny_rows)
    for batch in Dataset.from_arrow(table).batches(
        tiny_rows, columns=engine.required_columns()
    ):
        break
    template = engine._prepare(batch)

    t_feed0 = time.perf_counter()

    @jax.jit
    def gen_batch(key):
        out = {}
        for name in sorted(template):
            t = template[name]
            key, sub = jrandom.split(key)
            shape = (rows_per_batch,) + tuple(t.shape[1:])
            if t.dtype == jnp.bool_:
                out[name] = jrandom.uniform(sub, shape) > 0.05
            elif jnp.issubdtype(t.dtype, jnp.floating):
                out[name] = jrandom.normal(sub, shape).astype(t.dtype)
            else:
                info = jnp.iinfo(t.dtype)
                out[name] = jrandom.randint(
                    sub, shape, 0, min(info.max, 1 << 15), dtype=jnp.int32
                ).astype(t.dtype)
        return out

    feature_sets = [gen_batch(jrandom.PRNGKey(b)) for b in range(n_batches)]
    feed_bytes = sum(v.nbytes for v in feature_sets[0].values()) * n_batches
    for features in feature_sets:
        jax.block_until_ready(features)
    feed_s = time.perf_counter() - t_feed0

    program = engine._update

    def fetch(carry):
        return jax.tree_util.tree_map(np.asarray, carry)

    def chain(n_dispatches):
        carry = program.init_carry()
        t0 = time.perf_counter()
        for i in range(n_dispatches):
            carry = program(carry, feature_sets[i % n_batches])
        fetch(carry)
        return time.perf_counter() - t0

    chain(n_batches)  # warm/compile both feature-set shapes
    # two chain lengths; the SLOPE is the per-batch cost with the fixed
    # fetch round-trip (hundreds of ms on a congested tunnel) cancelled
    # out. RTT jitter can rival the compute of a short chain, so the delta
    # is kept >= 64 batches and the median of three slopes is reported.
    k1 = max(8, n_batches)
    t1 = chain(k1)
    k2 = k1 + max(64, int(target_seconds / max(t1 / k1, 1e-4)))
    slopes = []
    rows = 0
    for _ in range(3):
        ta, tb = chain(k1), chain(k2)
        slopes.append((tb - ta) / (k2 - k1))
        rows += rows_per_batch * (k1 + k2)
    per_batch = sorted(slopes)[1]
    if per_batch <= 0:  # jitter beat the delta; quote the conservative bound
        per_batch = tb / k2
    rate = rows_per_batch / per_batch
    bytes_per_row = feed_bytes / (rows_per_batch * n_batches)
    achieved_gbps = rate * bytes_per_row / 1e9
    log(
        f"[device-scan] {rows:,} device-resident rows x {len(analyzers)} "
        f"analyzers ({k1}+{k2} chained dispatches, fetch-forced sync, "
        f"RTT-cancelling slope {per_batch*1e3:.1f}ms/batch) -> "
        f"{rate/1e6:.1f}M rows/s/chip "
        f"({bytes_per_row:.0f} B/row touched, {achieved_gbps:.1f} GB/s achieved; "
        f"on-device generation of {feed_bytes/1e6:.0f}MB took {feed_s:.1f}s)"
    )
    return {
        "rows_per_sec": rate,
        "bytes_per_row": bytes_per_row,
        "achieved_gbps": achieved_gbps,
    }


def run_mesh_scaling_stage(rows: int = 2_000_000) -> dict:
    """ROADMAP item 2's acceptance artifact: 1→2→4→8-device sharded-scan
    throughput plus a chaos point that kills one shard mid-stage and
    records the recovery wall-time (salvage + re-shard + replay vs the
    clean run at the same mesh size). Runs in a DETACHED subprocess so the
    stage can force a multi-device platform (8 virtual CPU devices when no
    accelerator mesh exists) without re-configuring this process's jax.
    On CPU the absolute points model nothing (virtual devices share the
    same cores) — what transfers is the SHAPE and the measured recovery
    cost; a TPU host runs the same stage over its real mesh."""
    import json as _json
    import os
    import subprocess

    import jax

    env = dict(os.environ)
    if jax.default_backend() == "cpu":
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mesh_scaling_bench", "--stage-json",
         str(rows)],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=subprocess_timeout_s(),
        env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"mesh_scaling subprocess rc={proc.returncode}: "
            f"{proc.stderr[-500:]}"
        )
    result = _json.loads(proc.stdout.strip().splitlines()[-1])
    result["stage_seconds"] = time.perf_counter() - t0
    chaos = result.get("chaos") or {}
    log(
        "[mesh_scaling] points "
        + " ".join(
            f"{k}dev {v / 1e6:.2f}M rows/s"
            for k, v in sorted(result["points"].items(), key=lambda kv: int(kv[0]))
        )
        + (
            f"; chaos recovery {chaos['recovery_s']:.2f}s "
            f"(losses {chaos['shard_losses']}, reshards "
            f"{chaos['mesh_reshards']}, parity "
            f"{'ok' if chaos['parity_ok'] else 'MISMATCH'})"
            if chaos else "; chaos drill skipped (single device)"
        )
    )
    return result


def run_xla_prewarm_stage() -> dict:
    """Pre-warm the persistent XLA compilation cache from a DETACHED
    staging process (ROADMAP item 1): a subprocess runs the 1-batch
    production-shaped device profile, compiling the ~8 signature-bundled
    programs into the shared on-disk cache (config.py sets
    jax_compilation_cache_dir), so the measured device_profile stage's
    compile probe DESERIALIZES instead of compiling — the r05 failure mode
    (1140s of XLA compile inside the measured stage) cannot recur. The
    subprocess's own wall time is reported as this stage's cost."""
    import os
    import subprocess

    script = (
        "import bench; "
        "from deequ_tpu.data import Dataset; "
        "from deequ_tpu.profiles import ColumnProfilerRunner; "
        "t = bench.build_lineitem_data(1 << 20); "
        "ColumnProfilerRunner.on_data(Dataset.from_arrow(t))"
        ".with_placement('device').with_batch_size(1 << 20).run(); "
        "print('prewarm done')"
    )
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True,
            timeout=subprocess_timeout_s(),
        )
    except subprocess.TimeoutExpired:
        # a blown prewarm costs its own stage, never the measured ones:
        # the cache is simply (partially) cold for device_profile
        elapsed = time.perf_counter() - t0
        log(f"[xla-prewarm] staging subprocess timed out after {elapsed:.1f}s")
        return {"seconds": elapsed, "ok": False}
    elapsed = time.perf_counter() - t0
    ok = proc.returncode == 0
    log(
        f"[xla-prewarm] detached staging process "
        f"{'populated the persistent XLA cache' if ok else 'FAILED (rc=%d)' % proc.returncode} "
        f"in {elapsed:.1f}s"
    )
    if not ok:
        log(f"[xla-prewarm] stderr tail: {proc.stderr[-500:]}")
    return {"seconds": elapsed, "ok": ok}


def run_device_profile_stage(target_rows: int | None = None) -> dict:
    """DEVICE-PLACEMENT full column profile at config-3 (lineitem) shape:
    the REAL ColumnProfilerRunner over REAL data with `placement="device"`
    and the engine's device feature cache enabled, so the timed (second)
    run reads every feature batch from HBM — no tunnel feed in the timed
    path. Unlike the synthetic [device-scan] stage this produces real
    metrics, which are parity-checked below; timing is plain wall clock of
    the whole run, whose own state fetches force device completion (the
    block_until_ready trap does not apply to full host fetches).

    Row count adapts to the probed feed bandwidth so the one-time staging
    run fits DEEQU_TPU_BENCH_STAGE_BUDGET_S (default 180s)."""
    import os

    from deequ_tpu.data import Dataset
    from deequ_tpu.profiles import ColumnProfilerRunner
    from deequ_tpu.runners.engine import (
        RunMonitor,
        clear_device_feature_cache,
        probe_feed_bandwidth,
    )

    bytes_per_row = 150.0  # pass-1 features at lineitem shape
    compile_probe_s = 0.0
    if target_rows is None:
        budget_s = stage_budget_s()
        bw = probe_feed_bandwidth()
        # MEASURED 1-batch compile probe (VERDICT r5 weak #1b): run the
        # device-placed profile once over a single production-shaped batch
        # and charge the measured time — dominated by XLA compile — against
        # the stage budget. The old model budgeted feed bytes only and the
        # staging run blew a 180s budget by 6x of pure compile. The probe
        # doubles as the warmup: the staging run below reuses its programs.
        probe_table = build_lineitem_data(1 << 20)
        t0 = time.perf_counter()
        (
            ColumnProfilerRunner.on_data(Dataset.from_arrow(probe_table))
            .with_placement("device")
            .with_batch_size(1 << 20)
            .run()
        )
        compile_probe_s = time.perf_counter() - t0
        del probe_table
        feed_budget_s = max(budget_s - compile_probe_s, 0.1 * budget_s)
        target_rows = int(bw * 1e6 * feed_budget_s / bytes_per_row)
        log(
            f"[device-profile] compile probe: {compile_probe_s:.1f}s for 1 "
            f"batch (budget {budget_s:.0f}s -> {feed_budget_s:.0f}s left "
            f"for feed at {bw:.0f} MB/s)"
        )
    rows = max(2 << 20, min(target_rows, 32 << 20))
    rows = (rows >> 20) << 20  # whole 1M-row batches
    log(f"[device-profile] building {rows:,}-row lineitem table (16 cols)")
    table = build_lineitem_data(rows)
    data = Dataset.from_arrow(table)

    prior = os.environ.get("DEEQU_TPU_DEVICE_FEATURE_CACHE")
    os.environ["DEEQU_TPU_DEVICE_FEATURE_CACHE"] = "8"
    try:
        stage_mon = RunMonitor()
        t0 = time.perf_counter()
        runner = (
            ColumnProfilerRunner.on_data(data)
            .with_placement("device")
            .with_batch_size(1 << 20)
            .with_monitor(stage_mon)
        )
        profiles = runner.run()  # stages features into HBM + compiles
        stage_s = time.perf_counter() - t0

        mon = RunMonitor()
        t0 = time.perf_counter()
        profiles = (
            ColumnProfilerRunner.on_data(data)
            .with_placement("device")
            .with_batch_size(1 << 20)
            .with_monitor(mon)
            .run()
        )
        elapsed = time.perf_counter() - t0
    finally:
        clear_device_feature_cache()
        if prior is None:
            os.environ.pop("DEEQU_TPU_DEVICE_FEATURE_CACHE", None)
        else:
            os.environ["DEEQU_TPU_DEVICE_FEATURE_CACHE"] = prior

    # parity: real metrics from the device run vs full-data numpy oracles
    for name in ("l_quantity", "l_extendedprice", "l_discount", "l_tax"):
        arr = table[name].to_numpy()
        p = profiles.profiles[name]
        for got, want in (
            (p.mean, arr.mean()), (p.minimum, arr.min()), (p.maximum, arr.max()),
            (p.std_dev, arr.std()), (p.sum, arr.sum()),
        ):
            if abs(got - want) > 1e-6 * max(1.0, abs(want)):
                log(f"PARITY MISMATCH {name}: got={got} want={want}")
                sys.exit(1)
    flags = profiles.profiles["l_returnflag"].histogram
    import pyarrow.compute as pc

    vc = pc.value_counts(table["l_returnflag"])
    want_counts = {
        str(v["values"]): int(v["counts"]) for v in vc.to_pylist()
    }
    got_counts = {k: v.absolute for k, v in flags.values.items()}
    if got_counts != want_counts:
        log(f"PARITY MISMATCH l_returnflag histogram: {got_counts} != {want_counts}")
        sys.exit(1)

    rate = rows / elapsed
    # the NORTH-STAR ratio must exist the moment config-3 completes (a
    # later-stage timeout then can never erase it from the partial JSON):
    # a small-capped oracle here (cache-flattered, so the ratio is
    # conservative); the full profile stage re-measures at its larger cap
    # and overwrites with the canonical number when it completes
    oracle_rows = min(rows, 2 << 20)
    vs_single = rate / lineitem_single_core_rate(table, oracle_rows)
    phases = ", ".join(f"{k}={v:.2f}s" for k, v in sorted(mon.phase_seconds.items()))
    fetch_s = mon.phase_seconds.get("state_fetch", 0.0)
    dispatch_s = mon.phase_seconds.get("device_dispatch", 0.0)
    log(
        f"[device-profile] {rows:,} rows x 16 cols, placement=device, warm "
        f"feature cache: {elapsed:.2f}s -> {rate/1e6:.1f}M rows/s/chip "
        f"({vs_single:.1f}x single-core on a {oracle_rows:,}-row oracle; "
        f"passes={mon.passes}; staging+compile run took {stage_s:.1f}s, "
        f"{stage_mon.program_compiles} staging compiles; metrics "
        f"parity-checked vs numpy/arrow oracles)"
    )
    log(f"[device-profile] phases: {phases}")
    log(
        f"[device-profile] warm state_fetch={fetch_s:.2f}s vs "
        f"device_dispatch={dispatch_s:.2f}s -> "
        f"{'fetch-bound' if fetch_s > dispatch_s else 'dispatch-bound'}"
    )
    return {
        "rows_per_sec": rate,
        "rows": rows,
        "vs_single_core": vs_single,
        "stage_seconds": stage_s,
        "compile_probe_seconds": compile_probe_s,
        "staging_compiles": stage_mon.program_compiles,
        **monitor_phase_fields(mon),
    }


def run_device_merge_stage(
    n_states: int = 64, n_hll_states: int = 2048, target_seconds: float = 3.0
) -> dict:
    """On-device sketch-merge throughput: lax.scan fold of the analyzers'
    semigroup merges over stacked DEVICE-RESIDENT states (the program
    merge_states_batched compiles), timed without any host fetch."""
    import jax
    import jax.numpy as jnp

    from deequ_tpu.ops.hll import M as HLL_M
    from deequ_tpu.ops.kll import kll_init, kll_merge, kll_update

    rng = np.random.default_rng(3)

    # realistic populated states: KLL sketches built from 64k values each
    base = kll_init()
    ones = jnp.ones(1 << 16, dtype=bool)
    build = jax.jit(lambda s, v: kll_update(s, v, ones))
    kll_states = []
    for i in range(n_states):
        vals = jnp.asarray(rng.normal(size=1 << 16))
        kll_states.append(build(base, vals))
    kll_stacked = jax.device_put(
        jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *kll_states)
    )
    hll_stacked = jax.device_put(
        jnp.asarray(rng.integers(0, 40, (n_hll_states, HLL_M)), dtype=jnp.int32)
    )
    jax.block_until_ready((kll_stacked, hll_stacked))

    # the product's batched-merge path (sequential scan fold: measured 4x
    # FASTER than a vmapped log-depth tree for KLL on a v5e chip, whose
    # compaction dynamic_update_slices lower to gathers under vmap)
    @jax.jit
    def fold_kll(stacked):
        first = jax.tree_util.tree_map(lambda x: x[0], stacked)
        rest = jax.tree_util.tree_map(lambda x: x[1:], stacked)
        return jax.lax.scan(lambda acc, s: (kll_merge(acc, s), None), first, rest)[0]

    @jax.jit
    def fold_hll(regs):
        return jax.lax.scan(
            lambda acc, r: (jnp.maximum(acc, r), None), regs[0], regs[1:]
        )[0]

    kll_bytes = sum(
        np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(kll_stacked)
    )
    hll_bytes = hll_stacked.nbytes

    results = {}
    for name, fold, stacked, nbytes in (
        ("kll", fold_kll, kll_stacked, kll_bytes),
        ("hll", fold_hll, hll_stacked, hll_bytes),
    ):
        # fetch-forced sync (see run_device_resident_stage): each timed
        # region ends with a full host fetch of the folded state, because
        # block_until_ready alone can return early on tunnel transports
        def fetch(out):
            return jax.tree_util.tree_map(np.asarray, out)

        def timed_chain(iters):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fold(stacked)
            fetch(out)
            return time.perf_counter() - t0

        timed_chain(1)  # compile + one forced run
        # rough per-fold estimate from one (2, 8) pair, then size the
        # measurement delta so the compute difference dwarfs RTT jitter
        # (the single-run `once` is fetch-RTT-polluted on a congested
        # tunnel — calibrating from it repeats the bug this methodology
        # exists to fix). Floors: a jitter-negative delta falls back to the
        # RTT-inclusive t8/8 (never near-zero), and k2 is capped so a bad
        # estimate cannot turn the stage into a 30k-fold marathon.
        t8 = timed_chain(8)
        rough = (t8 - timed_chain(2)) / 6
        if rough <= 0:
            rough = t8 / 8
        k1 = 2
        k2 = k1 + min(max(32, int(target_seconds / rough)), 512)
        # median slope over three (k1, k2) pairs cancels the fetch RTT
        chain_times = [(timed_chain(k2), timed_chain(k1)) for _ in range(3)]
        slopes = sorted((tb - ta) / (k2 - k1) for tb, ta in chain_times)
        per_fold = slopes[1]
        note = ""
        if per_fold <= 0:  # jitter beat the delta even at this size
            per_fold = chain_times[-1][0] / k2  # reuse the measured k2 chain
            note = " (RTT-polluted upper bound: slope fell below jitter)"
        gbps = nbytes / per_fold / 1e9
        results[name] = gbps
        n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        log(
            f"[device-merge] {name}: {n} states ({nbytes/1e6:.1f}MB) "
            f"folded on device in {per_fold*1e3:.1f}ms -> {gbps:.2f} GB/s{note}"
        )
    return results


# ---------------------------------------------------------------------------
# stage 2c: ingestion plane (ROADMAP item 4 / PR 9 acceptance) — sustained
# in-process Arrow IPC throughput, the double-buffered transfer overlap on
# the device tier, and a bounded-admission concurrency soak point
# ---------------------------------------------------------------------------


def build_overlap_data(rows: int):
    """Mixed workload whose STAGED host cost (feature build + transfer) is
    a real fraction of the pass: numeric columns feed the device battery,
    plain high-cardinality string columns pay genuine per-batch host
    feature work (native hash/length kernels) on the feed thread — the
    shape where double buffering has something to hide on every platform
    (on a TPU the host->device copy itself dominates the staged cost; on
    CPU XLA the copy is a memcpy and the feature kernels are what
    overlap)."""
    import pyarrow as pa

    rng = np.random.default_rng(5)
    base = np.array([
        f"user-{i:08x}-{i * 2654435761 % 100000007:09d}"
        for i in range(1 << 16)
    ])

    def strings():
        return pa.array(np.char.add(
            base[rng.integers(0, len(base), rows)],
            np.char.mod("%07d", rng.integers(0, 10**7, rows)),
        ))

    return pa.table({
        "x0": pa.array(rng.normal(size=rows)),
        "x1": pa.array(rng.normal(size=rows)),
        "s0": strings(),
        "s1": strings(),
    })


def run_ingest_overlap(rows: int, batch_size: int = 1 << 20) -> dict:
    """Serial (DEEQU_TPU_PREFETCH_DEPTH=0) vs double-buffered (depth 2)
    device-tier fold over the same data: the wall-clock saving divided by
    the serial run's staged host cost (feature build + host->device
    transfer) is the fraction of transfer time the pipeline HIDES under
    device compute. Median of three runs per depth (the saving is a
    difference of walls, so single samples are jitter-bound); metrics
    must match bit-exact across depths."""
    import os

    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLSketch,
        MaxLength,
        Mean,
    )
    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import RunMonitor

    data = Dataset.from_arrow(build_overlap_data(rows))
    analyzers = [Mean("x0"), Mean("x1"), KLLSketch("x0")]
    for s in ("s0", "s1"):
        analyzers += [Completeness(s), MaxLength(s), ApproxCountDistinct(s)]

    def run(depth: int):
        prior = os.environ.get("DEEQU_TPU_PREFETCH_DEPTH")
        os.environ["DEEQU_TPU_PREFETCH_DEPTH"] = str(depth)
        try:
            mon = RunMonitor()
            t0 = time.perf_counter()
            ctx = AnalysisRunner.do_analysis_run(
                data, analyzers, batch_size=batch_size, monitor=mon,
                placement="device",
            )
            wall = time.perf_counter() - t0
        finally:
            if prior is None:
                os.environ.pop("DEEQU_TPU_PREFETCH_DEPTH", None)
            else:
                os.environ["DEEQU_TPU_PREFETCH_DEPTH"] = prior
        metrics = {
            repr(a): m.value.get()
            for a, m in ctx.metric_map.items() if m.value.is_success
        }
        staged_s = (
            mon.phase_seconds.get("feature_build", 0.0)
            + mon.phase_seconds.get("device_feed", 0.0)
        )
        return wall, staged_s, metrics

    run(2)  # warm: compile + page the table in
    points = [(run(0), run(2)) for _ in range(3)]
    m0, m2 = points[0][0][2], points[0][1][2]
    for (w0, s0, a), (w2, _s2, b) in points:
        if a != m0 or b != m2:
            log("PARITY MISMATCH ingest overlap: repeat runs disagree")
            sys.exit(1)
    if m0 != m2:
        log(f"PARITY MISMATCH ingest overlap: {m0} != {m2}")
        sys.exit(1)
    wall0 = sorted(p[0][0] for p in points)[1]
    staged0 = sorted(p[0][1] for p in points)[1]
    wall2 = sorted(p[1][0] for p in points)[1]
    hidden = (wall0 - wall2) / staged0 if staged0 > 0 else 0.0
    log(
        f"[ingest] double-buffer overlap on {rows:,} rows (median of 3): "
        f"serial {wall0:.2f}s (staged host cost {staged0:.2f}s) vs "
        f"pipelined {wall2:.2f}s -> {hidden:.0%} of transfer hidden, "
        f"metrics bit-exact"
    )
    return {
        "serial_s": round(wall0, 3), "pipelined_s": round(wall2, 3),
        "staged_s": round(staged0, 3), "hidden_fraction": round(hidden, 3),
    }


def run_ingest_stage(rows: int) -> dict:
    """Three acceptance points: (1) sustained in-process Arrow IPC stream
    throughput (decode + checksum-free fold through the real session
    path, target >= 500 MB/s vs the 6-30 MB/s feed-link probe); (2) the
    double-buffered host->device overlap (>= 50% of staged transfer
    hidden); (3) a >=1000-concurrent-session bounded-admission soak point
    (sessions/s + MB/s sustained through the scheduler)."""
    from tools.ingest_soak import run_concurrency_soak, run_stream_throughput

    stream_rows = max(min(rows, 32_000_000), 1 << 20)
    # enough volume that per-stream session overhead amortizes: MB/s here
    # means SUSTAINED, not first-stream
    stream_mb = max(stream_rows * 32 / 1e6, 768)  # 4 f64-ish wire cols
    tput = run_stream_throughput(target_mb=stream_mb, workers=4)
    if not tput["parity_ok"]:
        log("PARITY MISMATCH ingest stream throughput")
        sys.exit(1)
    log(
        f"[ingest] in-process Arrow stream: {tput['ingested_mb']:.0f}MB in "
        f"{tput['wall_s']:.2f}s -> {tput['mb_per_s']:.0f} MB/s "
        f"({tput['rows_per_s']/1e6:.1f}M rows/s) at 1M-row frames, "
        f"metrics parity ok"
    )
    big = run_stream_throughput(
        target_mb=stream_mb, workers=4, rows_per_batch=4 << 20
    )
    if not big["parity_ok"]:
        log("PARITY MISMATCH ingest stream throughput (4M-row frames)")
        sys.exit(1)
    log(
        f"[ingest] 4M-row frames: {big['mb_per_s']:.0f} MB/s "
        f"({big['rows_per_s']/1e6:.1f}M rows/s)"
    )

    overlap = run_ingest_overlap(max(min(rows, 8_000_000), 1 << 20))

    soak = run_concurrency_soak(
        sessions=1000, batches=2, rows=4096, workers=8, queue_depth=256,
    )
    log(
        f"[ingest] soak: {soak['sessions']} sessions x "
        f"{soak['batches_per_session']} batches under bounded admission "
        f"(queue {soak['queue_depth']}): {soak['wall_s']:.1f}s -> "
        f"{soak['sessions_per_s']:.0f} sessions/s, {soak['mb_per_s']:.0f} "
        f"MB/s, shed={soak['shed']}, failed={soak['failed_folds']}"
    )
    if "fold_latency_p99_s" in soak:
        log(
            f"[ingest] soak tail latency: fold "
            f"p50={soak.get('fold_latency_p50_s', 0) * 1e3:.1f}ms "
            f"p99={soak['fold_latency_p99_s'] * 1e3:.1f}ms, admission "
            f"wait p99={soak.get('admission_wait_p99_s', 0) * 1e3:.1f}ms "
            "(from the per-tenant SLO histograms)"
        )
    if not soak["ok"]:
        log("[ingest] soak FAILED (incomplete sessions or failed folds)")
        sys.exit(1)
    return {
        "mb_per_s": tput["mb_per_s"],
        "mb_per_s_4m_frames": big["mb_per_s"],
        "stream_rows_per_s": tput["rows_per_s"],
        "overlap_hidden_fraction": overlap["hidden_fraction"],
        "overlap_serial_s": overlap["serial_s"],
        "overlap_pipelined_s": overlap["pipelined_s"],
        "soak_sessions": soak["sessions"],
        "soak_sessions_per_s": soak["sessions_per_s"],
        "soak_mb_per_s": soak["mb_per_s"],
        "soak_shed": soak["shed"],
        # absent on runs whose histograms never filled (bench_diff
        # tolerates missing scalars in OLDER runs by design)
        **{
            k: soak[k]
            for k in (
                "fold_latency_p50_s", "fold_latency_p99_s",
                "admission_wait_p50_s", "admission_wait_p99_s",
            )
            if k in soak
        },
    }


# ---------------------------------------------------------------------------
# stage 2d: streaming knee (ISSUE 10 acceptance) — sessions/s with and
# without cross-session fold coalescing on the PR 9 soak workload
# ---------------------------------------------------------------------------


def run_streaming_knee_stage() -> dict:
    """Sessions/s at {100, 1000} sessions x {4096, 65536}-row micro-batches,
    coalescing ON vs OFF, plus the bit-exact parity gate between the two
    modes (tools/streaming_knee.py). Runs in a DETACHED subprocess so each
    grid point's service/scheduler state starts cold and an interpreter
    carrying this bench's device programs cannot flatter the numbers."""
    import json as _json
    import os
    import subprocess

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.streaming_knee", "--stage-json"],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=subprocess_timeout_s(),
    )
    if proc.returncode != 0 and not proc.stdout.strip():
        raise RuntimeError(
            f"streaming_knee subprocess rc={proc.returncode}: "
            f"{proc.stderr[-500:]}"
        )
    result = _json.loads(proc.stdout.strip().splitlines()[-1])
    result["stage_seconds"] = time.perf_counter() - t0
    if not result["parity"]["bit_exact"]:
        log("PARITY MISMATCH streaming knee: coalesced != serial metrics")
        sys.exit(1)
    for p in result["points"]:
        log(
            f"[streaming_knee] {p['sessions']} sessions x {p['rows']} rows: "
            f"serial {p['serial_sessions_per_s']:.0f}/s -> coalesced "
            f"{p['coalesced_sessions_per_s']:.0f}/s ({p['speedup']:.1f}x, "
            f"shed={p['shed']})"
        )
    log(
        f"[streaming_knee] headline (1000x4096): "
        f"{result['headline_sessions_per_s']:.0f} sessions/s "
        f"({result['headline_speedup']:.1f}x serial), parity bit-exact"
    )
    return result


# ---------------------------------------------------------------------------
# stage 2d': self-tuning calibration (ISSUE 18 acceptance) — the boot-time
# calibrator measured end to end, then the SAME streaming+grouping point
# static vs tuned; bench_diff gates tuned >= static within the band
# ---------------------------------------------------------------------------


def run_calibration_stage() -> dict:
    """Run ``deequ_tpu.tuning.calibrate`` fresh in a DETACHED subprocess
    against a throwaway profile dir (probe values + derived knobs + wall
    time land in the partial JSON), then measure one streaming+grouping
    throughput point twice in two more detached service processes:
    STATIC (``DEEQU_TPU_AUTOTUNE=0``) and TUNED (the freshly calibrated
    profile loaded at service boot). Each point starts from a cold
    interpreter so neither arm inherits the other's compiled programs or
    router EWMAs. bench_diff gates tuned >= static within the band."""
    import json as _json
    import os
    import subprocess
    import tempfile

    t0 = time.perf_counter()
    here = os.path.dirname(os.path.abspath(__file__))
    profile_dir = tempfile.mkdtemp(prefix="bench-tuning-profile-")
    base_env = dict(os.environ)
    base_env["DEEQU_TPU_TUNING_PROFILE_DIR"] = profile_dir

    def detached(module_args: list, extra_env: dict, label: str) -> dict:
        env = dict(base_env)
        env.update(extra_env)
        proc = subprocess.run(
            [sys.executable, "-m"] + module_args,
            cwd=here, capture_output=True, text=True,
            timeout=subprocess_timeout_s(), env=env,
        )
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"calibration {label} subprocess rc={proc.returncode}: "
                f"{proc.stderr[-500:]}"
            )
        return _json.loads(proc.stdout.strip().splitlines()[-1])

    cal = detached(["deequ_tpu.tuning.calibrate", "--json"], {}, "probe")
    log(
        f"[calibration] {len(cal['probes'])} probes in "
        f"{cal['wall_s']:.2f}s on substrate {cal['fingerprint']}: "
        f"device_fixed {cal['probes']['device_fixed_s'] * 1e3:.2f}ms, "
        f"device {cal['probes']['device_rows_per_s'] / 1e6:.0f}M rows/s, "
        f"group host/device "
        f"{cal['probes']['group_host_rows_per_s'] / 1e6:.1f}M/"
        f"{cal['probes']['group_device_rows_per_s'] / 1e6:.1f}M rows/s"
    )
    static = detached(["tools.tuning_report", "--bench-point"],
                      {"DEEQU_TPU_AUTOTUNE": "0"}, "static-point")
    tuned = detached(["tools.tuning_report", "--bench-point"], {},
                     "tuned-point")
    log(
        f"[calibration] streaming {static['sessions_per_s']:.0f} static -> "
        f"{tuned['sessions_per_s']:.0f} tuned sessions/s "
        f"({tuned['sessions_per_s'] / static['sessions_per_s']:.2f}x); "
        f"grouping {static['grouping_rows_per_s'] / 1e6:.1f}M static -> "
        f"{tuned['grouping_rows_per_s'] / 1e6:.1f}M tuned rows/s "
        f"({tuned['grouping_rows_per_s'] / static['grouping_rows_per_s']:.2f}x); "
        f"tuned knobs: {', '.join(tuned['tuned_knobs']) or 'none'}"
    )
    return {
        "wall_s": cal["wall_s"],
        "fingerprint": cal["fingerprint"],
        "probes": cal["probes"],
        "knobs": cal["knobs"],
        "static": static,
        "tuned": tuned,
        "stage_seconds": time.perf_counter() - t0,
    }


# ---------------------------------------------------------------------------
# stage 2e: anomaly fleet (ISSUE 15 acceptance) — the fleet watch's
# per-harvest scoring core: 10k tenants' metric histories, serial vs ONE
# batched detect_batch call, parity-gated
# ---------------------------------------------------------------------------


def run_anomaly_fleet_stage(n_series: int = 10_000) -> dict:
    """Series/s for the fleet-watch scoring pass (tools/
    anomaly_fleet_bench.py): N ragged series with newest-point intervals,
    scored serially (one detect per series) and batched (ONE detect_batch
    over the fleet tensor), flag indices and messages element-identical.
    Runs DETACHED so the child's numpy working set starts cold."""
    import json as _json
    import os
    import subprocess

    t0 = time.perf_counter()
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.anomaly_fleet_bench",
            "--series", str(n_series),
        ],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=subprocess_timeout_s(),
    )
    if not proc.stdout.strip():
        raise RuntimeError(
            f"anomaly_fleet subprocess rc={proc.returncode}: "
            f"{proc.stderr[-500:]}"
        )
    result = _json.loads(proc.stdout.strip().splitlines()[-1])
    result["stage_seconds"] = time.perf_counter() - t0
    if not result["parity"]:
        log("PARITY MISMATCH anomaly fleet: batched != serial scoring")
        sys.exit(1)
    log(
        f"[anomaly_fleet] {result['series']:,} series "
        f"({result['points_total']:,} points): batched "
        f"{result['series_per_s']:,.0f} series/s in "
        f"{result['detect_calls']} call vs serial "
        f"{result['serial_series_per_s']:,.0f}/s "
        f"({result['speedup']:.1f}x), {result['flagged']} flagged, "
        f"parity element-exact"
    )
    return result


# ---------------------------------------------------------------------------
# stage 2f: multi-host cluster soak (ISSUE 16 acceptance) — aggregate
# sessions/s across 1 and 2 real worker PROCESSES routed by the front
# tier, parity-gated against the closed-form exact-sum oracle
# ---------------------------------------------------------------------------


def run_cluster_soak_stage(
    procs=(1, 2), sessions: int = 8, batches: int = 8, rows: int = 4096,
) -> dict:
    """Cluster tier scale-out (tools/cluster_soak.py): each point spawns N
    worker processes — whole service planes with their own scheduler and
    HTTP ingest endpoint — behind the consistent-hash front tier on one
    shared partition store, and measures aggregate sessions/s. Every point
    carries the bit-exact parity gate (integer-valued sums are fold-order
    independent, so the routed cluster must equal the closed-form oracle
    EXACTLY). Runs DETACHED per point so each cluster starts cold and a
    point's worker processes can never leak into the next. On one box the
    processes share cores, so the 2-proc point understates real two-host
    scaling — the SHAPE (and the ≥1.6x gate tools/bench_diff tracks via
    cluster_soak_sessions_per_s) is what transfers."""
    import json as _json
    import os
    import subprocess

    t0 = time.perf_counter()
    points = {}
    for n in procs:
        proc = subprocess.run(
            [
                sys.executable, "-m", "tools.cluster_soak", "--stage-json",
                "--procs", str(n), "--sessions", str(sessions),
                "--batches", str(batches), "--rows", str(rows),
            ],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=subprocess_timeout_s(),
        )
        if not proc.stdout.strip():
            raise RuntimeError(
                f"cluster_soak subprocess rc={proc.returncode}: "
                f"{proc.stderr[-500:]}"
            )
        point = _json.loads(proc.stdout.strip().splitlines()[-1])
        if point.get("skipped"):
            # the environment cannot spawn the worker processes (no free
            # ports, sandboxed sockets): the stage reports itself skipped
            # instead of failing the bench
            log(f"[cluster_soak] skipped: {point.get('reason')}")
            return {"skipped": True, "reason": point.get("reason")}
        if point["parity_failures"]:
            log(
                f"PARITY MISMATCH cluster soak at {n} procs: "
                f"{point['parity_failures'][:3]}"
            )
            sys.exit(1)
        points[str(n)] = point
        log(
            f"[cluster_soak] {n} proc: "
            f"{point['sessions_per_s']:.1f} sessions/s "
            f"({point['folds_per_s']:.0f} folds/s), parity bit-exact"
        )
    head = points[str(procs[-1])]
    base = points[str(procs[0])]
    scaling = head["sessions_per_s"] / base["sessions_per_s"]
    log(
        f"[cluster_soak] headline ({procs[-1]} procs): "
        f"{head['sessions_per_s']:.1f} sessions/s, "
        f"{scaling:.2f}x vs {procs[0]} proc"
    )
    return {
        "points": {
            k: {
                "sessions_per_s": p["sessions_per_s"],
                "folds_per_s": p["folds_per_s"],
                "elapsed_s": p["elapsed_s"],
            } for k, p in points.items()
        },
        "sessions_per_s": head["sessions_per_s"],
        "scaling_vs_1p": round(scaling, 3),
        "routes_total": head["counters"][
            "deequ_service_cluster_routes_total"
        ],
        "stage_seconds": time.perf_counter() - t0,
    }


def run_catalog_soak_stage(
    registered: int = 400, active: int = 24,
    gate_batches: int = 24, gate_rows: int = 65_536,
) -> dict:
    """Tenant isolation plane (tools/catalog_soak.py): registered >>
    active catalog tiering with the mid-soak edit and corrupt-edit
    drills, plus the gated-vs-ungated throughput fraction (acceptance
    floor 0.8; tools/bench_diff tracks it as a throughput scalar so the
    row gate's steady-state cost cannot silently grow). Runs DETACHED so
    the soak's service plane starts cold."""
    import json as _json
    import os
    import subprocess

    t0 = time.perf_counter()
    proc = subprocess.run(
        [
            sys.executable, "-m", "tools.catalog_soak", "--stage-json",
            "--registered", str(registered), "--active", str(active),
            "--gate-batches", str(gate_batches),
            "--gate-rows", str(gate_rows),
        ],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=subprocess_timeout_s(),
    )
    if not proc.stdout.strip():
        raise RuntimeError(
            f"catalog_soak subprocess rc={proc.returncode}: "
            f"{proc.stderr[-500:]}"
        )
    summary = _json.loads(proc.stdout.strip().splitlines()[-1])
    if not summary["ok"]:
        log(
            "catalog soak VERDICT FAILED: "
            f"soak={summary['soak'].get('ok')} "
            f"gate={summary['gate'].get('ok')} "
            f"fraction={summary['gated_throughput_fraction']}"
        )
        sys.exit(1)
    log(
        f"[catalog_soak] {registered} registered / {active} active: "
        f"{summary['soak']['sessions_per_s']:.1f} sessions/s hot, "
        f"edit + corrupt drills ok; gate fraction "
        f"{summary['gated_throughput_fraction']:.2f} "
        f"({summary['gate']['gated_mb_per_s']:.0f} vs "
        f"{summary['gate']['ungated_mb_per_s']:.0f} MB/s), bit-exact"
    )
    return {
        "registered": registered,
        "active": active,
        "sessions_per_s": summary["soak"]["sessions_per_s"],
        "registers_per_s": summary["soak"]["registers_per_s"],
        "edit_drill": summary["soak"]["edit_drill"]["ok"],
        "corrupt_drill": summary["soak"]["corrupt_drill"]["ok"],
        "gated_throughput_fraction": summary["gated_throughput_fraction"],
        "gated_mb_per_s": summary["gate"]["gated_mb_per_s"],
        "ungated_mb_per_s": summary["gate"]["ungated_mb_per_s"],
        "stage_seconds": time.perf_counter() - t0,
    }


# ---------------------------------------------------------------------------
# stage 3: incremental/stateful partitions + sketch-state merge (BASELINE
# config 4: partition states persisted, table metrics refreshed from merged
# states WITHOUT rescanning data, anomaly check on the history)
# ---------------------------------------------------------------------------


def run_incremental_stage(rows_per_partition: int, n_partitions: int = 2) -> dict:
    """BASELINE config 4: day partitions persist states; table metrics
    refresh from merged states with no rescan; an anomaly check on
    Size/Mean runs over the metric history (the part BENCH_r03 omitted)."""
    import jax

    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLSketch,
        Mean,
        Size,
    )
    from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
    from deequ_tpu.anomalydetection import RelativeRateOfChangeStrategy
    from deequ_tpu.checks import CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.repository import ResultKey
    from deequ_tpu.repository.memory import InMemoryMetricsRepository
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.verification import VerificationSuite

    analyzers = [Size(), Completeness("x0"), Mean("x0"), Mean("x1"),
                 ApproxCountDistinct("cat"), KLLSketch("x0")]
    log(f"[incremental] {n_partitions} day partitions x {rows_per_partition:,} rows")
    providers = []
    repo = InMemoryMetricsRepository()
    table = build_scan_data(rows_per_partition * n_partitions)
    for p in range(n_partitions):
        part = Dataset.from_arrow(
            table.slice(p * rows_per_partition, rows_per_partition)
        )
        sp = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(
            part, analyzers, save_states_with=sp,
            metrics_repository=repo,
            save_or_append_results_with_key=ResultKey(p, {"day": str(p)}),
        )
        providers.append(sp)
    schema = Dataset.from_arrow(table.slice(0, 1)).schema

    # warm the merge programs, then time the state-only refresh
    AnalysisRunner.run_on_aggregated_states(schema, analyzers, providers)
    state_bytes = 0
    for sp in providers:
        for a in analyzers:
            state = sp.load(a)
            leaves = jax.tree_util.tree_leaves(state)
            state_bytes += sum(np.asarray(x).nbytes for x in leaves)
    t0 = time.perf_counter()
    ctx = AnalysisRunner.run_on_aggregated_states(schema, analyzers, providers)
    merge_s = time.perf_counter() - t0
    total_rows = rows_per_partition * n_partitions
    assert ctx.metric(Size()).value.get() == float(total_rows)

    # anomaly check over the day-partition metric history: a steady day-N+1
    # passes, a half-size day fails (config 4's "anomaly detection on
    # Size/Mean")
    def day(rows: int, key: int):
        part = Dataset.from_arrow(table.slice(0, rows))
        return (
            VerificationSuite.on_data(part)
            .use_repository(repo)
            .save_or_append_result(ResultKey(key, {"day": str(key)}))
            .add_anomaly_check(
                RelativeRateOfChangeStrategy(max_rate_increase=1.5,
                                             max_rate_decrease=0.5),
                Size(),
            )
            .add_anomaly_check(
                RelativeRateOfChangeStrategy(max_rate_increase=1.1,
                                             max_rate_decrease=0.9),
                Mean("x1"),  # mean ~100; x0's mean ~0 makes ratios unstable
            )
            .run()
        )
    from deequ_tpu.checks import CheckStatus

    steady = day(rows_per_partition, n_partitions)
    anomalous = day(max(rows_per_partition // 4, 1), n_partitions + 1)
    assert steady.status == CheckStatus.SUCCESS, steady.status
    assert anomalous.status != CheckStatus.SUCCESS, anomalous.status
    log(
        f"[incremental] table metrics refreshed from {n_partitions} partition "
        f"states in {merge_s*1e3:.0f}ms — no data rescan "
        f"({state_bytes/1e6:.1f}MB of sketch states, "
        f"{state_bytes/merge_s/1e9:.2f}GB/s merge); anomaly check on "
        f"Size/Mean: steady day passes, quarter-size day flagged"
    )
    result = {"merge_seconds": merge_s, "state_bytes": state_bytes}
    result.update(run_partition_growth_point(table))
    return result


def run_partition_growth_point(table) -> dict:
    """ISSUE 13 acceptance point: a partitioned table grows by ~1% and the
    incremental verify must touch <= 2% of the rows and cost <= 10% of the
    measured full-scan wall time, with suite metrics BIT-EXACT against the
    full re-scan (partition-aligned batches, so merges associate
    identically). The stored baseline is populated through the
    PartitionStateStore's own scan path; the +1% point is measured twice —
    cold (first merge of the grown shape compiles) and steady-state (the
    daily-growth repeat, after invalidating the growth partition) — and
    the steady-state number is the gated one."""
    import tempfile

    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.repository.partition_store import PartitionStateStore
    from deequ_tpu.runners.engine import RunMonitor
    from deequ_tpu.verification import VerificationSuite

    # cap the point's scale: its METRICS are ratios (cost fraction, reuse
    # ratio), and populate pays one engine pass per baseline partition —
    # at the full 50M-row stage shape that alone would eat the per-stage
    # SIGALRM budget the existing halves of this stage already share
    total_rows = min(int(table.num_rows), 10_000_000)
    table = table.slice(0, total_rows)
    # ~1% growth granularity needs ~100 baseline partitions; floor the
    # partition size so smoke-scale runs still exercise the full protocol
    # (their ratios are recorded but only meaningful at real scale)
    n_base = min(100, max(4, total_rows // 50_000))
    part_rows = total_rows // n_base
    checks = [
        Check(CheckLevel.ERROR, "incremental growth")
        .has_size(lambda n: n > 0)
        .is_complete("x0")
        .has_mean("x0", lambda m: -50 < m < 50)
        .has_sum("x1", lambda s: s != 0)
        .has_approx_count_distinct("cat", lambda c: c > 0)
    ]
    analyzers = scan_battery()

    def part_name(i: int) -> str:
        return f"2026-{1 + i // 28:02d}-{1 + i % 28:02d}"

    def partition(i: int) -> Dataset:
        return Dataset.from_arrow(table.slice(i * part_rows, part_rows))

    base = {part_name(i): (lambda i=i: partition(i)) for i in range(n_base)}
    versions = {part_name(i): f"v-{i}" for i in range(n_base)}
    store_dir = tempfile.mkdtemp(prefix="deequ-bench-partition-store-")
    store = PartitionStateStore(store_dir)
    log(
        f"[incremental] partition growth point: {n_base} x {part_rows:,}"
        f"-row partitions + 1 growth partition"
    )
    t0 = time.perf_counter()
    VerificationSuite.verify_partitioned(
        store, "bench", base, checks, analyzers,
        checksums=versions, batch_size=part_rows,
    )
    populate_s = time.perf_counter() - t0

    # two growth days of FRESH ~1% partitions: day 1 is the COLD point
    # (the rollup+suffix merge shape compiles once), day 2 is the
    # steady-state daily cost — scan one partition, fold it onto the
    # rollup cache, rewrite the rollup — which is what the 10%-of-full
    # acceptance bar gates
    import pyarrow as pa

    def growth_part(day: int):
        rng = np.random.default_rng(7 + day)
        return pa.table({
            **{f"x{i}": pa.array(rng.normal(100 * i, 10, part_rows),
                                 mask=rng.random(part_rows) < 0.05)
               for i in range(4)},
            "cat": pa.array(rng.integers(0, 100_000, part_rows)),
        })

    g1, g2 = growth_part(1), growth_part(2)
    grown = dict(base)
    gname1, gname2 = part_name(n_base), part_name(n_base + 1)
    grown[gname1] = lambda: Dataset.from_arrow(g1)
    gversions = dict(versions)
    gversions[gname1] = "v-growth-1"

    # full-scan baseline over the final grown table, partition-aligned
    full_data = Dataset.from_arrow(pa.concat_tables([table, g1, g2]))
    t0 = time.perf_counter()
    full = VerificationSuite.do_verification_run(
        full_data, checks, analyzers, batch_size=part_rows,
    )
    full_s = time.perf_counter() - t0

    mon = RunMonitor()
    t0 = time.perf_counter()
    inc = VerificationSuite.verify_partitioned(
        store, "bench", grown, checks, analyzers,
        checksums=gversions, batch_size=part_rows, monitor=mon,
    )
    delta_cold_s = time.perf_counter() - t0
    assert inc.incremental.plan.scan == [gname1], inc.incremental.plan.scan

    # steady state: day-2 growth (merge programs warm, rollup advances)
    grown[gname2] = lambda: Dataset.from_arrow(g2)
    gversions[gname2] = "v-growth-2"
    mon2 = RunMonitor()
    t0 = time.perf_counter()
    inc2 = VerificationSuite.verify_partitioned(
        store, "bench", grown, checks, analyzers,
        checksums=gversions, batch_size=part_rows, monitor=mon2,
    )
    delta_s = time.perf_counter() - t0
    assert inc2.incremental.plan.scan == [gname2], inc2.incremental.plan.scan
    assert mon2.partitions_rolled_up == n_base + 1, mon2.partitions_rolled_up
    report = inc2.incremental

    # non-sketch metrics are BIT-EXACT (partition-aligned batches make the
    # merges associate identically); KLL sketches compact differently when
    # folded per-partition vs continuously, so they hold their documented
    # rank-error envelope instead: identical bucket boundaries (min/max
    # merge exactly) and CDFs within 2% rank error
    parity = all(
        inc2.metrics[a].value.get() == m.value.get()
        for a, m in full.metrics.items()
        if a.name not in ("KLLSketch",)
    )

    def kll_close(got, want) -> bool:
        gb, wb = got.buckets, want.buckets
        if len(gb) != len(wb):
            return False
        if gb and (gb[0].low_value != wb[0].low_value
                   or gb[-1].high_value != wb[-1].high_value):
            return False
        n_g = sum(b.count for b in gb)
        n_w = sum(b.count for b in wb)
        if n_g != n_w or n_g == 0:
            return False
        cg = cw = 0
        for g, w in zip(gb, wb):
            cg += g.count
            cw += w.count
            if abs(cg - cw) / n_g > 0.02:
                return False
        return True

    kll_parity = all(
        kll_close(inc2.metrics[a].value.get(), m.value.get())
        for a, m in full.metrics.items()
        if a.name == "KLLSketch"
    )
    out = {
        "partitions": n_base + 2,
        "partition_rows": part_rows,
        "populate_s": round(populate_s, 3),
        "full_scan_s": round(full_s, 3),
        "delta_cold_s": round(delta_cold_s, 3),
        "delta_s": round(delta_s, 3),
        "cost_fraction": round(delta_s / full_s, 4) if full_s else None,
        "speedup_vs_full": round(full_s / delta_s, 2) if delta_s else None,
        "reuse_ratio": round(report.reuse_ratio, 4),
        "rows_touched_fraction": round(report.rows_touched_fraction, 4),
        "rows_scanned": report.rows_scanned,
        "rows_total": report.rows_total,
        "parity_bit_exact": bool(parity and kll_parity),
    }
    log(
        f"[incremental] +1% growth: full scan {full_s:.2f}s vs incremental "
        f"{delta_s:.3f}s ({out['cost_fraction']:.1%} of full, cold "
        f"{delta_cold_s:.3f}s) — reuse ratio {out['reuse_ratio']:.2%}, "
        f"rows touched {out['rows_touched_fraction']:.2%}, parity "
        f"bit-exact={out['parity_bit_exact']}"
    )
    import shutil

    shutil.rmtree(store_dir, ignore_errors=True)
    return {"partition_growth": out}


# ---------------------------------------------------------------------------
# stage 3a2: device-resident frequency engine (ROADMAP item 3) — the
# BENCH_r04 [spill] workload shape through the device table path, with the
# host group-by measured in a sibling process for the before/after ratio
# ---------------------------------------------------------------------------


def run_grouping_stage(rows: int) -> dict:
    """25M rows / ~3.6M distinct (rows//7) grouping battery through the
    DEVICE frequency engine, versus the same workload through the host
    accumulator — each in a FRESH subprocess so peak RSS is the engine's
    own, not this process's high-water mark. Metrics must be BIT-exact
    across the two engines; the host point runs under the r04 [spill]
    stage's frequency-entry budget so the 'before' includes the disk-spill
    cost the device engine eliminates."""
    import subprocess

    from tools.grouping_sweep import subprocess_point

    distinct = max(rows // 7, 1000)
    budget = max(distinct // 8, 1000)  # the r04 spill-forcing budget

    def point(engine: str, extra_env: dict) -> dict:
        try:
            return subprocess_point(
                rows, distinct, engine, seed=1,
                timeout=subprocess_timeout_s(), extra_env=extra_env,
            )
        except subprocess.TimeoutExpired:
            # the stage's SIGALRM normally fires first (its budget is below
            # this cap); if the child itself times out, record the stage as
            # deadline-skipped rather than killing the stages after it
            raise StageDeadline("grouping") from None

    dev = point("device", {})
    host = point("host", {"DEEQU_TPU_MAX_FREQUENCY_ENTRIES": str(budget)})
    if dev["metrics"] != host["metrics"]:
        log(f"PARITY MISMATCH grouping engines: {dev['metrics']} != {host['metrics']}")
        sys.exit(1)
    ratio = dev["rows_per_sec"] / host["rows_per_sec"]
    # the r04 comparison only means something at the r04 workload shape
    # (25M rows / 3.6M distinct); a smoke-scale run must not write the
    # ROADMAP acceptance ratio from an incomparable workload
    r04_rate = 1.66e6 if rows == 25_000_000 else None
    r04_clause = (
        f"{dev['rows_per_sec']/r04_rate:.1f}x the r04 host-spill rate; "
        if r04_rate else ""
    )
    log(
        f"[grouping] {rows:,} rows / {dev['distinct']:.0f} distinct: device "
        f"table {dev['seconds']:.2f}s ({dev['rows_per_sec']/1e6:.1f}M rows/s, "
        f"peak RSS {dev['peak_rss_gb']:.2f}GB, overflow fallbacks="
        f"{dev['freq_overflow_fallbacks']}) vs host spill "
        f"{host['seconds']:.2f}s ({host['rows_per_sec']/1e6:.2f}M rows/s, "
        f"peak RSS {host['peak_rss_gb']:.2f}GB) -> {ratio:.1f}x live, "
        f"{r04_clause}metrics bit-exact"
    )
    out = {
        "rows_per_sec": dev["rows_per_sec"],
        "peak_rss_gb": dev["peak_rss_gb"],
        "distinct": dev["distinct"],
        "host_rows_per_sec": host["rows_per_sec"],
        "host_peak_rss_gb": host["peak_rss_gb"],
        "vs_host_spill": round(ratio, 2),
        "overflow_fallbacks": dev["freq_overflow_fallbacks"],
    }
    if r04_rate:
        out["vs_r04_spill"] = round(dev["rows_per_sec"] / r04_rate, 2)
    return out


# ---------------------------------------------------------------------------
# stage 3b: high-cardinality frequency spill (the Spark shuffle-spill
# analog): Uniqueness completes under a deliberately small budget —
# SINCE the device frequency engine landed this is the LAST-RESORT tier,
# measured here with the engine disabled
# ---------------------------------------------------------------------------


def run_spill_stage(rows: int) -> dict:
    import os
    import resource

    from deequ_tpu.analyzers import CountDistinct, Uniqueness
    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner

    distinct = max(rows // 7, 1000)
    budget = max(distinct // 8, 1000)
    rng = np.random.default_rng(1)
    keys = rng.integers(0, distinct, rows)
    data = Dataset.from_dict({"k": keys})
    prior_budget = os.environ.get("DEEQU_TPU_MAX_FREQUENCY_ENTRIES")
    os.environ["DEEQU_TPU_MAX_FREQUENCY_ENTRIES"] = str(budget)
    try:
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        t0 = time.perf_counter()
        ctx = AnalysisRunner.do_analysis_run(
            data, [Uniqueness(["k"]), CountDistinct(["k"])], placement="host"
        )
        elapsed = time.perf_counter() - t0
        rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    finally:
        if prior_budget is None:
            del os.environ["DEEQU_TPU_MAX_FREQUENCY_ENTRIES"]
        else:
            os.environ["DEEQU_TPU_MAX_FREQUENCY_ENTRIES"] = prior_budget
    rate = rows / elapsed
    got = ctx.metric(CountDistinct(["k"])).value.get()
    vc = np.bincount(keys, minlength=distinct)
    assert got == float((vc > 0).sum()), (got, (vc > 0).sum())
    log(
        f"[spill] Uniqueness over {rows:,} rows / {got:.0f} distinct under a "
        f"{budget:,}-entry budget: {elapsed:.1f}s ({rate/1e6:.2f}M rows/s), "
        f"peak RSS {rss1:.2f}GB (was {rss0:.2f}GB before)"
    )
    return {
        "rows_per_sec": rate, "distinct": got, "budget": budget,
        "peak_rss_gb": round(rss1, 3),
    }


# ---------------------------------------------------------------------------
# stage 4: constraint suggestion on the wide mixed table (BASELINE config 5
# shape: profile + rule application + held-out evaluation of the suggested
# constraints)
# ---------------------------------------------------------------------------


def run_suggestion_stage(rows: int) -> dict:
    from deequ_tpu.data import Dataset
    from deequ_tpu.suggestions import ConstraintSuggestionRunner, Rules

    # config 5 SHAPE: 50 mixed-type columns (30 numeric / 10 string / 10
    # categorical); row count scales with the CLI arg
    n_numeric, n_string, n_cat = 30, 10, 10
    n_cols = n_numeric + n_string + n_cat
    log(f"[suggest] {rows:,}-row x {n_cols}-col constraint suggestion run")
    table = build_wide_data(rows, n_numeric=n_numeric, n_string=n_string, n_cat=n_cat)
    data = Dataset.from_arrow(table)

    def run_once() -> tuple:
        t0 = time.perf_counter()
        result = (
            ConstraintSuggestionRunner.on_data(data)
            .add_constraint_rules(Rules.DEFAULT)
            .use_train_test_split_with_testset_ratio(0.25, testset_split_random_seed=0)
            .run()
        )
        return time.perf_counter() - t0, result

    # the held-out evaluation's constraint battery is data-dependent, so its
    # fused fold program compiles on first use; report cold (incl. compile)
    # and warm (program-cache hit) separately like the other stages' warmups
    cold_s, result = run_once()
    warm_s, result = run_once()
    n_suggestions = len(result.all_suggestions)
    evaluated = result.verification_result is not None
    log(
        f"[suggest] {n_suggestions} suggestions over {len(result.column_profiles)} "
        f"columns: cold {cold_s:.2f}s (persistent-XLA-cache-assisted), warm "
        f"{warm_s:.2f}s ({rows/warm_s/1e6:.2f}M rows/s, held-out evaluation="
        f"{'yes' if evaluated else 'no'})"
    )
    return {"seconds": warm_s, "cold_seconds": cold_s, "suggestions": n_suggestions}


def main() -> None:
    import os

    import jax

    from deequ_tpu.runners.engine import probe_feed_bandwidth

    scan_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000_000
    profile_rows = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000_000
    log(f"devices: {jax.devices()}")
    log(f"feed-link probe: {probe_feed_bandwidth():.0f} MB/s")

    # Partial-result protocol: a wall-clock kill (rc:124) in ANY stage must
    # not destroy the numbers the earlier stages already measured — that
    # exact failure erased two rounds of benchmarks. After EVERY stage a
    # full parse-able JSON snapshot of everything measured so far goes to
    # stdout with "partial": true; the driver takes the LAST JSON line, so
    # a timeout leaves the freshest snapshot as the artifact. On top of
    # that, every stage runs under a HARD per-stage deadline
    # (DEEQU_TPU_BENCH_STAGE_BUDGET_S, run_stage_with_deadline): a stage
    # that blows its budget is marked "skipped_deadline" in the "stages"
    # map and the bench proceeds — no stage can starve the ones after it.
    out: dict = {}
    completed: list = []
    stages: dict = {}

    def checkpoint(stage: str, status: str = "ok", extra: dict | None = None) -> None:
        # each stage's entry carries its status plus the compile/fetch
        # observability fields (compiles, state_fetch_s, device_dispatch_s)
        # so a compile or fetch regression is parseable from the artifact
        entry = {"status": status}
        if extra:
            entry.update(extra)
        stages[stage] = entry
        if status == "ok":
            completed.append(stage)
        write_stage_trace(stage)
        line = dict(out)
        line["partial"] = True
        line["completed_stages"] = list(completed)
        line["stages"] = dict(stages)
        print(json.dumps(line), flush=True)

    def staged(name: str, fn, *args, **kwargs):
        result, status, _seconds = run_stage_with_deadline(name, fn, *args, **kwargs)
        if status != "ok":
            checkpoint(name, status)
        return result

    def phase_extra(result: dict) -> dict:
        keys = ("compiles", "state_fetch_s", "device_dispatch_s",
                "staging_compiles")
        return {k: result[k] for k in keys if k in result}

    # NORTH-STAR-FIRST stage order (VERDICT r5 ask #1b): the device-placed
    # profile and the config-3 profile produce the numbers the project is
    # judged on, so they run before the synthetic device stages — a late
    # wall-clock kill costs synthetic numbers, never the headline ones.
    # The detached prewarm subprocess populates the persistent XLA cache
    # FIRST, so the measured stage deserializes its programs instead of
    # compiling them (the r05 rc:124 root cause).
    prewarm = staged(
        "xla_prewarm", run_xla_prewarm_stage,
        # the stage exists to absorb a cold compile LONGER than one stage
        # budget — under the default 1x SIGALRM a >budget compile would be
        # killed mid-prewarm, leaving a partial cache for the measured
        # stage to re-pay (the r05 failure mode). The subprocess enforces
        # its own timeout; the alarm is the backstop above it.
        budget_s=subprocess_timeout_s() + 30,
    )
    if prewarm is not None:
        out["xla_prewarm_s"] = round(prewarm["seconds"], 1)
        checkpoint("xla_prewarm", status="ok" if prewarm["ok"] else "failed")

    device_profile = staged("device_profile", run_device_profile_stage)
    if device_profile is not None:
        out["device_profile_rows_per_sec"] = round(device_profile["rows_per_sec"], 1)
        out["device_profile_rows"] = device_profile["rows"]
        out["device_profile_compile_probe_s"] = round(
            device_profile["compile_probe_seconds"], 1
        )
        out["device_profile_staging_s"] = round(device_profile["stage_seconds"], 2)
        out["device_profile_state_fetch_s"] = device_profile["state_fetch_s"]
        out["device_profile_device_dispatch_s"] = device_profile["device_dispatch_s"]
        # vs_baseline lands in EVERY partial line from config-3 on (VERDICT
        # r5 ask #4): a later-stage timeout can no longer erase the
        # north-star ratio. The host profile stage overwrites it with its
        # larger-oracle measurement when it completes.
        out["vs_baseline"] = round(device_profile["vs_single_core"], 2)
        checkpoint("device_profile", extra=phase_extra(device_profile))

    # The bench host is SHARED: under heavy contention the host-tier stages
    # can run 10-50x slower than on a quiet box, and the BASELINE-shape row
    # counts would blow any reasonable wall-clock. The reported METRIC is
    # rows/s, so when a 1M-row calibration projects a stage far past its
    # budget, shrink the row count (never below the round-3 scale) and say
    # so — a completed smaller run beats a timed-out full-shape one.
    # the calibration budget must never exceed the per-stage SIGALRM: a
    # row count sized to 600s of projected work under a 180s stage
    # deadline guarantees a skipped_deadline, not a bigger number
    profile_budget = float(
        os.environ.get(
            "DEEQU_TPU_BENCH_PROFILE_BUDGET_S", str(0.9 * stage_budget_s())
        )
    )
    if profile_rows > 4_000_000:
        from deequ_tpu.data import Dataset
        from deequ_tpu.profiles import ColumnProfilerRunner

        cal_table = build_lineitem_data(1 << 20)
        # warm on the SAME 1M shape the timed run uses (a smaller warm slice
        # would leave the 1<<20 batch program uncompiled and the timed run
        # would measure XLA compile, not throughput)
        ColumnProfilerRunner.on_data(Dataset.from_arrow(cal_table)).run()
        t0 = time.perf_counter()
        ColumnProfilerRunner.on_data(Dataset.from_arrow(cal_table)).run()
        cal_rate = (1 << 20) / (time.perf_counter() - t0)
        projected = profile_rows / cal_rate
        if projected > profile_budget:
            effective = min(
                profile_rows, max(10_000_000, int(cal_rate * profile_budget))
            )
            log(
                f"[main] box congested: calibration {cal_rate/1e6:.2f}M rows/s "
                f"projects {projected:.0f}s for {profile_rows:,} profile rows "
                f"(budget {profile_budget:.0f}s) -> running {effective:,} rows"
            )
            profile_rows = effective
            scan_rows = min(scan_rows, max(10_000_000, profile_rows // 2))

    profile = staged("profile", run_profile_stage, profile_rows)
    if profile is not None:
        out["metric"] = "column_profiler_rows_per_sec_per_chip"
        out["value"] = round(profile["rows_per_sec"], 1)
        out["unit"] = "rows/s"
        out["vs_baseline"] = round(profile["vs_single_core"], 2)
        out["vs_64core_linear"] = round(profile["vs_64core_linear"], 3)
        checkpoint("profile", extra=phase_extra(profile))

    scan = staged("scan", run_scan_stage, scan_rows, batch_size=1 << 20)
    if scan is not None:
        out["scan_rows_per_sec_per_chip"] = round(scan["rows_per_sec"], 1)
        out["scan_vs_baseline"] = round(scan["vs_single_core"], 2)
        checkpoint("scan", extra=phase_extra(scan))

    ingest = staged("ingest", run_ingest_stage, max(scan_rows // 4, 1 << 20))
    if ingest is not None:
        out["ingest_mb_per_s"] = ingest["mb_per_s"]
        out["ingest_overlap_hidden"] = ingest["overlap_hidden_fraction"]
        out["ingest_soak_sessions"] = ingest["soak_sessions"]
        out["ingest_soak_sessions_per_s"] = ingest["soak_sessions_per_s"]
        out["ingest_soak_mb_per_s"] = ingest["soak_mb_per_s"]
        for q_key in (
            "fold_latency_p50_s", "fold_latency_p99_s",
            "admission_wait_p50_s", "admission_wait_p99_s",
        ):
            if q_key in ingest:
                out[f"ingest_{q_key}"] = ingest[q_key]
        checkpoint("ingest", extra=ingest)

    device = staged("device_scan", run_device_resident_stage)
    if device is not None:
        out["device_scan_rows_per_sec"] = round(device["rows_per_sec"], 1)
        out["device_scan_gbps"] = round(device["achieved_gbps"], 2)
        checkpoint("device_scan")

    merge = staged("device_merge", run_device_merge_stage)
    if merge is not None:
        out["sketch_merge_gbps"] = round(merge["kll"], 3)
        out["hll_merge_gbps"] = round(merge["hll"], 3)
        checkpoint("device_merge")

    incremental = staged(
        "incremental", run_incremental_stage,
        max(scan_rows // 2, 100_000), n_partitions=2,
    )
    if incremental is not None:
        out["state_merge_seconds"] = round(incremental["merge_seconds"], 3)
        out["state_merge_bytes"] = incremental["state_bytes"]
        growth = incremental.get("partition_growth") or {}
        if growth:
            # the ISSUE-13 acceptance point: +1% growth verified at a
            # fraction of full-scan cost, gated by tools/bench_diff
            out["incremental_full_scan_s"] = growth["full_scan_s"]
            out["incremental_delta_s"] = growth["delta_s"]
            out["incremental_cost_fraction"] = growth["cost_fraction"]
            out["incremental_speedup_vs_full"] = growth["speedup_vs_full"]
            out["incremental_reuse_ratio"] = growth["reuse_ratio"]
            out["incremental_rows_touched_fraction"] = growth[
                "rows_touched_fraction"
            ]
            out["incremental_parity_bit_exact"] = growth["parity_bit_exact"]
        checkpoint(
            "incremental",
            extra={"partition_growth": growth} if growth else None,
        )

    grouping = staged("grouping", run_grouping_stage, max(scan_rows // 2, 100_000))
    if grouping is not None:
        out["grouping_rows_per_sec"] = round(grouping["rows_per_sec"], 1)
        out["grouping_peak_rss_gb"] = grouping["peak_rss_gb"]
        out["grouping_vs_host_spill"] = grouping["vs_host_spill"]
        if "vs_r04_spill" in grouping:
            out["grouping_vs_r04_spill"] = grouping["vs_r04_spill"]
        checkpoint("grouping", extra={
            "peak_rss_gb": grouping["peak_rss_gb"],
            "host_rows_per_sec": grouping["host_rows_per_sec"],
            "host_peak_rss_gb": grouping["host_peak_rss_gb"],
            "distinct": grouping["distinct"],
        })

    spill = staged("spill", run_spill_stage, max(scan_rows // 2, 100_000))
    if spill is not None:
        out["spill_rows_per_sec"] = round(spill["rows_per_sec"], 1)
        out["spill_peak_rss_gb"] = spill["peak_rss_gb"]
        checkpoint("spill", extra={"peak_rss_gb": spill["peak_rss_gb"]})

    knee = staged(
        "streaming_knee", run_streaming_knee_stage,
        # four soak grid points x two modes in one detached child: give it
        # the subprocess budget, not one in-process stage's
        budget_s=subprocess_timeout_s() + 30,
    )
    if knee is not None:
        out["streaming_knee_sessions_per_s"] = knee[
            "headline_sessions_per_s"
        ]
        out["streaming_knee_speedup"] = knee["headline_speedup"]
        checkpoint("streaming_knee", extra={
            "points": [
                {k: p[k] for k in (
                    "sessions", "rows", "serial_sessions_per_s",
                    "coalesced_sessions_per_s",
                    "coalesced_sessions_per_s_min",
                    "coalesced_sessions_per_s_max",
                    "speedup", "shed",
                ) if k in p}
                for p in knee["points"]
            ],
            "parity_bit_exact": knee["parity"]["bit_exact"],
        })

    calibration = staged(
        "calibration", run_calibration_stage,
        # three detached children (probe + two measured points), each with
        # its own interpreter startup
        budget_s=3 * subprocess_timeout_s() + 30,
    )
    if calibration is not None:
        out["calibration_wall_s"] = round(calibration["wall_s"], 2)
        out["tuning_streaming_sessions_per_s_static"] = round(
            calibration["static"]["sessions_per_s"], 1
        )
        out["tuning_streaming_sessions_per_s_tuned"] = round(
            calibration["tuned"]["sessions_per_s"], 1
        )
        out["tuning_grouping_rows_per_s_static"] = round(
            calibration["static"]["grouping_rows_per_s"], 1
        )
        out["tuning_grouping_rows_per_s_tuned"] = round(
            calibration["tuned"]["grouping_rows_per_s"], 1
        )
        checkpoint("calibration", extra={
            "fingerprint": calibration["fingerprint"],
            "probes": {
                k: round(v, 6) for k, v in calibration["probes"].items()
            },
            "knobs": calibration["knobs"],
            "tuned_knobs": calibration["tuned"]["tuned_knobs"],
        })

    anomaly_fleet = staged(
        "anomaly_fleet", run_anomaly_fleet_stage,
        # detached child with its own process startup: give it the
        # subprocess budget, not one in-process stage's
        budget_s=subprocess_timeout_s() + 30,
    )
    if anomaly_fleet is not None:
        out["anomaly_fleet_series_per_s"] = anomaly_fleet["series_per_s"]
        out["anomaly_fleet_serial_series_per_s"] = anomaly_fleet[
            "serial_series_per_s"
        ]
        out["anomaly_fleet_speedup"] = anomaly_fleet["speedup"]
        out["anomaly_fleet_flagged"] = anomaly_fleet["flagged"]
        checkpoint("anomaly_fleet", extra={
            "series": anomaly_fleet["series"],
            "detect_calls": anomaly_fleet["detect_calls"],
            "parity": anomaly_fleet["parity"],
        })

    cluster_soak = staged(
        "cluster_soak", run_cluster_soak_stage,
        # two detached points (1-proc, 2-proc), each spawning worker
        # processes with their own interpreter startup: give the stage
        # two subprocess budgets, not one in-process stage's
        budget_s=2 * subprocess_timeout_s() + 30,
    )
    if cluster_soak is not None and not cluster_soak.get("skipped"):
        out["cluster_soak_sessions_per_s"] = cluster_soak["sessions_per_s"]
        out["cluster_soak_scaling_vs_1p"] = cluster_soak["scaling_vs_1p"]
        checkpoint("cluster_soak", extra={
            "points": cluster_soak["points"],
            "scaling_vs_1p": cluster_soak["scaling_vs_1p"],
            "routes_total": cluster_soak["routes_total"],
        })
    elif cluster_soak is not None:
        checkpoint("cluster_soak", status="skipped_env",
                   extra={"reason": cluster_soak.get("reason")})

    catalog_soak = staged(
        "catalog_soak", run_catalog_soak_stage,
        # one detached soak process with its own interpreter startup
        budget_s=subprocess_timeout_s() + 30,
    )
    if catalog_soak is not None:
        out["catalog_soak_sessions_per_s"] = catalog_soak["sessions_per_s"]
        out["gated_throughput_fraction"] = catalog_soak[
            "gated_throughput_fraction"
        ]
        checkpoint("catalog_soak", extra={
            "registered": catalog_soak["registered"],
            "active": catalog_soak["active"],
            "registers_per_s": catalog_soak["registers_per_s"],
            "gated_mb_per_s": catalog_soak["gated_mb_per_s"],
            "ungated_mb_per_s": catalog_soak["ungated_mb_per_s"],
        })

    mesh_scaling = staged(
        "mesh_scaling", run_mesh_scaling_stage,
        min(2_000_000, max(scan_rows // 25, 400_000)),
    )
    if mesh_scaling is not None:
        out["mesh_scaling_rows_per_sec"] = {
            k: round(v, 1) for k, v in mesh_scaling["points"].items()
        }
        # the SUBSTRATE rides the partial JSON so a virtual-CPU-device
        # scaling curve can never be misread as an accelerator one (the
        # r06 vs_baseline lesson applied to mesh points): real mesh vs
        # 8-virtual-CPU-device fallback, device kind, chip count
        substrate = mesh_scaling.get("mesh_substrate") or {}
        if substrate:
            out["mesh_substrate"] = substrate
        chaos = mesh_scaling.get("chaos") or {}
        if chaos:
            out["mesh_recovery_s"] = chaos["recovery_s"]
            out["mesh_chaos_parity_ok"] = chaos["parity_ok"]
        checkpoint("mesh_scaling", extra={
            "points": {k: round(v, 1) for k, v in mesh_scaling["points"].items()},
            **({"mesh_substrate": substrate} if substrate else {}),
            **({"chaos": chaos} if chaos else {}),
        })

    suggest = staged(
        "suggest", run_suggestion_stage, max(profile_rows // 20, 100_000)
    )
    if suggest is not None:
        out["suggest_seconds"] = round(suggest["seconds"], 2)
        out["suggest_cold_seconds"] = round(suggest["cold_seconds"], 2)
        out["suggestions"] = suggest["suggestions"]
        checkpoint("suggest")

    # perf-regression EPILOGUE (ROADMAP item 1's standing gate): diff this
    # run against the latest committed BENCH_r*/KNEE_r* trajectory and
    # record the verdict in the artifact. Report-only here — the bench's
    # job is to emit its numbers; CI enforces with `python -m
    # tools.bench_diff <fresh.json>` whose exit code is the gate.
    def run_bench_diff_stage() -> dict:
        from tools.bench_diff import render_report, run_diff_on_metrics

        fresh = dict(out)
        fresh["stages"] = dict(stages)
        fresh["completed_stages"] = list(completed)
        try:
            # ONE orchestration shared with the CLI gate (`python -m
            # tools.bench_diff`): same baseline/knee discovery, same
            # comparison — the epilogue and CI can never disagree about
            # what was compared
            result = run_diff_on_metrics(
                fresh, repo_dir=os.path.dirname(os.path.abspath(__file__))
            )
        except FileNotFoundError:
            return {"ok": True, "note": "no committed baseline parses",
                    "regressions": []}
        for line in render_report(result).splitlines():
            log(f"[bench_diff] {line}")
        return result

    bench_diff = staged("bench_diff", run_bench_diff_stage)
    if bench_diff is not None:
        out["bench_diff_ok"] = bench_diff["ok"]
        checkpoint("bench_diff", extra={
            "ok": bench_diff["ok"],
            "baseline": bench_diff.get("baseline"),
            "regressions": [
                f"{r['stage']}:{r['metric']}"
                for r in bench_diff.get("regressions", [])
            ],
        })

    final = dict(out)
    final["partial"] = False
    final["completed_stages"] = completed
    final["stages"] = stages
    print(json.dumps(final), flush=True)


if __name__ == "__main__":
    main()
