"""Perf-regression gate: diff a fresh bench run against the committed
trajectory (ROADMAP item 1's standing gate; ISSUE 12 satellite).

The bench artifacts (``BENCH_r*.json``, ``KNEE_r*.json``) are the
machine-readable trajectory PERF.md narrates; this tool diffs a NEW run's
final JSON line against the latest committed artifacts per stage and
exits non-zero with a named-stage report when a tracked metric regressed
beyond the tolerance band — so the perf wins PRs 6/10 measured (16-22M
rows/s grouping, 900+ sessions/s streaming) can never silently rot.

Tracked per stage:

- **throughput** (higher is better): profile/scan/ingest/grouping/spill/
  device-scan rows-or-MB per second, mesh-scaling per-device-count
  points, streaming-knee sessions/s;
- **memory** (lower is better): grouping/spill peak RSS;
- **compile counts** (must not increase): each stage's ``compiles`` field
  — a warm stage recompiling is a regression at ANY throughput;
- **incremental verification** (ISSUE 13): the +1%-growth point's
  full-scan speedup and reuse ratio (higher-better) and its cost as a
  fraction of the full scan (lower-better);
- **fleet watch** (ISSUE 15): the batched anomaly-scoring series/s
  (higher-better).

Substrate guard: scaling numbers measured on the 8-virtual-CPU-device
fallback model nothing about an accelerator mesh (the r06
``vs_baseline: 0.8`` lesson). When both artifacts record a
``mesh_substrate`` and they disagree, mesh-scaling points are reported as
SKIPPED rather than compared.

Usage::

    python bench.py ... | tail -1 > /tmp/fresh.json
    python -m tools.bench_diff /tmp/fresh.json            # gate (rc != 0 on regression)
    python -m tools.bench_diff /tmp/fresh.json --tolerance 0.4
    python -m tools.bench_diff /tmp/fresh.json --baseline BENCH_r06.json

Exit codes: 0 = no regression, 1 = at least one named regression,
2 = usage/artifact error. ``bench.py`` runs the same diff as its final
``bench_diff`` stage epilogue (report-only: the bench's job is to emit
its artifact; CI enforces with this tool's exit code).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

#: default tolerance band for throughput/RSS comparisons: bench boxes are
#: SHARED (r06's note), so run-to-run noise of tens of percent is normal;
#: a regression must clear this band to flag
DEFAULT_TOLERANCE = 0.25

#: (stage, metric key, kind); kind: "throughput" higher-better,
#: "rss" lower-better
_SCALARS: List[Tuple[str, str, str]] = [
    ("device_profile", "device_profile_rows_per_sec", "throughput"),
    ("profile", "profile_rows_per_sec", "throughput"),
    ("scan", "scan_rows_per_sec_per_chip", "throughput"),
    ("ingest", "ingest_mb_per_s", "throughput"),
    ("ingest", "ingest_soak_sessions_per_s", "throughput"),
    # per-tenant SLO histogram tails (ISSUE 20): soak fold latency and
    # admission wait p99 must not rot (lower-better -> rss comparator);
    # absent in runs older than the histograms — compare() skips None
    ("ingest", "ingest_fold_latency_p99_s", "rss"),
    ("ingest", "ingest_admission_wait_p99_s", "rss"),
    ("device_scan", "device_scan_rows_per_sec", "throughput"),
    ("grouping", "grouping_rows_per_sec", "throughput"),
    ("spill", "spill_rows_per_sec", "throughput"),
    ("streaming_knee", "streaming_knee_sessions_per_s", "throughput"),
    # cluster tier (ISSUE 16): 2-process aggregate sessions/s through the
    # consistent-hash front tier, parity-gated in the stage itself
    ("cluster_soak", "cluster_soak_sessions_per_s", "throughput"),
    ("grouping", "grouping_peak_rss_gb", "rss"),
    ("spill", "spill_peak_rss_gb", "rss"),
    # incremental verification (ISSUE 13): the +1%-growth point's
    # full-scan speedup and reuse ratio must not rot (higher-better), and
    # its cost fraction of the full scan must not grow (lower-better —
    # gated with the rss comparator)
    ("incremental", "incremental_speedup_vs_full", "throughput"),
    ("incremental", "incremental_reuse_ratio", "throughput"),
    ("incremental", "incremental_cost_fraction", "rss"),
    # fleet watch (ISSUE 15): the per-harvest batched scoring rate must
    # not rot (higher-better)
    ("anomaly_fleet", "anomaly_fleet_series_per_s", "throughput"),
    # tenant isolation plane (ISSUE 17): hot-tier fold rate and the row
    # gate's steady-state cost (gated/ungated MB/s; floor 0.8 enforced in
    # the stage, drift gated here) must not rot
    ("catalog_soak", "catalog_soak_sessions_per_s", "throughput"),
    ("catalog_soak", "gated_throughput_fraction", "throughput"),
    # self-tuning plane (ISSUE 18): the TUNED point's throughput must not
    # rot across rounds; the in-run tuned-vs-static gate lives in
    # diff_metrics (needs no committed baseline)
    ("calibration", "tuning_streaming_sessions_per_s_tuned", "throughput"),
    ("calibration", "tuning_grouping_rows_per_s_tuned", "throughput"),
]


def _latest_artifact(repo_dir: str, pattern: str) -> Optional[str]:
    """The highest-round committed artifact matching e.g. BENCH_r*.json
    that parses (and, for BENCH artifacts, carries stage metrics — early
    rounds are known-torn)."""
    best: Tuple[int, Optional[str]] = (-1, None)
    rx = re.compile(re.escape(pattern).replace(r"\*", r"(\d+)"))
    needs_metrics = pattern.startswith("BENCH")
    for path in glob.glob(os.path.join(repo_dir, pattern)):
        m = rx.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except Exception:  # noqa: BLE001 - early rounds are known-torn
            continue
        if needs_metrics and _metrics_of(doc) is None:
            continue
        n = int(m.group(1))
        if n > best[0]:
            best = (n, path)
    return best[1]


def _metrics_of(doc: Dict) -> Optional[Dict]:
    """The flat metrics dict of a bench artifact: the driver wraps the
    bench's final JSON line under ``parsed``; a raw bench line (or this
    tool's own input) IS the metrics dict."""
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    if "completed_stages" in doc or "stages" in doc:
        return doc
    return None


def _stage_status(metrics: Dict, stage: str) -> Optional[str]:
    return (metrics.get("stages") or {}).get(stage, {}).get("status")


def _substrates_comparable(fresh: Dict, committed: Dict) -> Tuple[bool, str]:
    fs = (fresh.get("mesh_substrate") or {}).get("substrate")
    cs = (committed.get("mesh_substrate") or {}).get("substrate")
    if fs is None or cs is None:
        return True, "unrecorded"  # pre-ISSUE-12 artifacts carry no field
    return fs == cs, f"{cs} -> {fs}"


def diff_metrics(
    fresh: Dict,
    committed: Dict,
    tolerance: float = DEFAULT_TOLERANCE,
    knee: Optional[Dict] = None,
) -> Dict:
    """Compare one fresh bench metrics dict against the committed one.
    Returns {"regressions": [...], "improvements": [...], "skipped":
    [...], "ok": bool}; each entry names its stage and metric."""
    regressions: List[Dict] = []
    improvements: List[Dict] = []
    skipped: List[Dict] = []

    def compare(stage: str, metric: str, new, old, kind: str) -> None:
        if old in (None, 0) or new is None:
            return
        if kind == "throughput":
            ratio = new / old
            bad = ratio < 1.0 - tolerance
        else:  # rss: lower is better
            ratio = new / old
            bad = ratio > 1.0 + tolerance
        entry = {
            "stage": stage, "metric": metric,
            "committed": round(float(old), 2), "fresh": round(float(new), 2),
            "ratio": round(ratio, 3), "kind": kind,
        }
        if bad:
            regressions.append(entry)
        elif (kind == "throughput" and ratio > 1.0 + tolerance) or (
            kind == "rss" and ratio < 1.0 - tolerance
        ):
            improvements.append(entry)

    for stage, metric, kind in _SCALARS:
        if _stage_status(fresh, stage) not in (None, "ok"):
            # the fresh run skipped/failed the stage: the gate cannot
            # clear it, but a deadline skip is not a measured regression
            skipped.append({
                "stage": stage, "metric": metric,
                "reason": f"fresh stage {_stage_status(fresh, stage)}",
            })
            continue
        compare(stage, metric, fresh.get(metric), committed.get(metric), kind)

    # mesh-scaling per-device-count points, substrate-guarded
    f_points = fresh.get("mesh_scaling_rows_per_sec") or {}
    c_points = committed.get("mesh_scaling_rows_per_sec") or {}
    comparable, substrate_note = _substrates_comparable(fresh, committed)
    for n_dev, old in sorted(c_points.items(), key=lambda kv: int(kv[0])):
        new = f_points.get(n_dev)
        if not comparable:
            skipped.append({
                "stage": "mesh_scaling",
                "metric": f"mesh_scaling_rows_per_sec[{n_dev}]",
                "reason": f"substrate changed ({substrate_note})",
            })
            continue
        if new is None:
            # a committed point the fresh run never produced (stage
            # deadline, fewer devices) must be VISIBLE, not a silent
            # green — compare() cannot see an absent value
            skipped.append({
                "stage": "mesh_scaling",
                "metric": f"mesh_scaling_rows_per_sec[{n_dev}]",
                "reason": "missing from fresh run",
            })
            continue
        compare(
            "mesh_scaling", f"mesh_scaling_rows_per_sec[{n_dev}]",
            new, old, "throughput",
        )

    # self-tuning IN-RUN gate (ISSUE 18): the calibration stage measures
    # the SAME workload point static vs tuned in the SAME run, so this
    # comparison needs no committed baseline — tuned must be >= static
    # within the band. A tuner that makes the box slower is a regression
    # even if both numbers beat the committed trajectory.
    for metric_base in ("tuning_streaming_sessions_per_s",
                        "tuning_grouping_rows_per_s"):
        static_v = fresh.get(f"{metric_base}_static")
        tuned_v = fresh.get(f"{metric_base}_tuned")
        if static_v in (None, 0) or tuned_v is None:
            continue
        ratio = tuned_v / static_v
        entry = {
            "stage": "calibration",
            "metric": f"{metric_base}_tuned_vs_static",
            "committed": round(float(static_v), 2),
            "fresh": round(float(tuned_v), 2),
            "ratio": round(ratio, 3), "kind": "throughput",
        }
        if ratio < 1.0 - tolerance:
            regressions.append(entry)
        elif ratio > 1.0 + tolerance:
            improvements.append(entry)

    # compile counts: a warm stage that recompiles regressed regardless
    # of wall clock (the compile-budget contract, per-stage)
    f_stages = fresh.get("stages") or {}
    c_stages = committed.get("stages") or {}
    for stage, c_entry in c_stages.items():
        old = c_entry.get("compiles")
        new = (f_stages.get(stage) or {}).get("compiles")
        if old is None or new is None:
            continue
        if new > old:
            regressions.append({
                "stage": stage, "metric": "compiles",
                "committed": old, "fresh": new,
                "ratio": None, "kind": "compiles",
            })

    # streaming-knee trajectory (KNEE_r*.json): the committed headline
    # sessions/s, against either a fresh knee artifact or the bench's
    # streaming_knee stage
    if knee:
        old = knee.get("headline_sessions_per_s")
        new = fresh.get("streaming_knee_sessions_per_s") or (
            fresh.get("headline_sessions_per_s")
        )
        if old and new is not None:
            compare(
                "streaming_knee", "headline_sessions_per_s(KNEE_r*)",
                new, old, "throughput",
            )

    return {
        "regressions": regressions,
        "improvements": improvements,
        "skipped": skipped,
        "tolerance": tolerance,
        "ok": not regressions,
    }


def render_report(result: Dict) -> str:
    lines = []
    tol = result["tolerance"]
    if result["regressions"]:
        lines.append(
            f"PERF REGRESSION: {len(result['regressions'])} metric(s) "
            f"beyond the {tol:.0%} band vs the committed trajectory:"
        )
        for r in result["regressions"]:
            if r["kind"] == "compiles":
                lines.append(
                    f"  [{r['stage']}] compiles {r['committed']} -> "
                    f"{r['fresh']} (warm stage recompiled)"
                )
            else:
                lines.append(
                    f"  [{r['stage']}] {r['metric']}: "
                    f"{r['committed']:,} -> {r['fresh']:,} "
                    f"({r['ratio']:.2f}x, {r['kind']})"
                )
    else:
        lines.append(
            f"no regression beyond the {tol:.0%} band vs the committed "
            "trajectory"
        )
    for s in result["skipped"]:
        lines.append(
            f"  skipped [{s['stage']}] {s['metric']}: {s['reason']}"
        )
    for i in result["improvements"]:
        lines.append(
            f"  improved [{i['stage']}] {i['metric']}: "
            f"{i['committed']:,} -> {i['fresh']:,} ({i['ratio']:.2f}x)"
        )
    return "\n".join(lines)


def run_diff_on_metrics(
    fresh: Dict,
    baseline_path: Optional[str] = None,
    knee_path: Optional[str] = None,
    tolerance: float = DEFAULT_TOLERANCE,
    repo_dir: Optional[str] = None,
) -> Dict:
    """Gate an IN-MEMORY fresh metrics dict against the committed
    trajectory: baseline/knee discovery, artifact load, diff, and
    baseline stamping. The single orchestration both the CLI gate
    (:func:`run_diff`) and bench.py's epilogue stage call — their
    baseline-selection rules can never drift apart."""
    repo_dir = repo_dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    baseline_path = baseline_path or _latest_artifact(
        repo_dir, "BENCH_r*.json"
    )
    if baseline_path is None:
        raise FileNotFoundError(
            "no committed BENCH_r*.json artifact parses; nothing to gate "
            "against"
        )
    with open(baseline_path) as fh:
        committed = _metrics_of(json.load(fh))
    knee = None
    knee_path = knee_path or _latest_artifact(repo_dir, "KNEE_r*.json")
    if knee_path:
        try:
            with open(knee_path) as fh:
                knee = json.load(fh)
        except Exception:  # noqa: BLE001 - knee trajectory is optional
            knee = None
    result = diff_metrics(fresh, committed, tolerance=tolerance, knee=knee)
    result["baseline"] = os.path.basename(baseline_path)
    if knee_path and knee:
        result["knee_baseline"] = os.path.basename(knee_path)
    return result


def run_diff(
    fresh_path: str,
    baseline_path: Optional[str] = None,
    knee_path: Optional[str] = None,
    tolerance: float = DEFAULT_TOLERANCE,
    repo_dir: Optional[str] = None,
) -> Dict:
    with open(fresh_path) as fh:
        text = fh.read().strip()
    # accept either a JSON document or a full bench stdout capture (take
    # the last parseable JSON line — the bench's partial-result protocol)
    fresh = None
    for line in reversed(text.splitlines()):
        try:
            fresh = _metrics_of(json.loads(line))
            if fresh is not None:
                break
        except Exception:  # noqa: BLE001 - not a JSON line
            continue
    if fresh is None:
        raise ValueError(f"no bench metrics JSON found in {fresh_path}")
    return run_diff_on_metrics(
        fresh, baseline_path=baseline_path, knee_path=knee_path,
        tolerance=tolerance, repo_dir=repo_dir,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("fresh", help="fresh bench JSON (final line, or a "
                                      "full bench stdout capture)")
    parser.add_argument("--baseline", help="committed BENCH_r*.json to gate "
                                           "against (default: latest that "
                                           "parses)")
    parser.add_argument("--knee", help="committed KNEE_r*.json trajectory "
                                       "(default: latest)")
    parser.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                        help="relative band a metric may move before it "
                             "flags (default 0.25)")
    parser.add_argument("--json", action="store_true",
                        help="emit the full result JSON on stdout")
    args = parser.parse_args(argv)
    try:
        result = run_diff(
            args.fresh, baseline_path=args.baseline, knee_path=args.knee,
            tolerance=args.tolerance,
        )
    except (OSError, ValueError) as exc:
        print(f"bench_diff: {exc}", file=sys.stderr)
        return 2
    print(render_report(result), file=sys.stderr, flush=True)
    if args.json:
        print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
