"""Ingestion-plane soak: concurrency knee + sustained in-process MB/s.

Two measurements back the ROADMAP item-4 acceptance bar ("thousands of
concurrent sessions under bounded admission", "saturate the feed link"):

1. **Concurrency soak** (``run_concurrency_soak``): N streaming sessions
   ingest B micro-batches each through the service scheduler under
   bounded admission with backpressure (``block_s``) — feeder threads park
   when the queue fills instead of dropping. Reports sessions/s and MB/s
   sustained, jobs shed, and the per-batch fold results. ``--sweep`` runs
   a doubling ladder of session counts so the knee (where sessions/s
   stops scaling) is visible in one invocation.

2. **Stream throughput** (``run_stream_throughput``): ONE session fed
   Arrow IPC payloads through `deequ_tpu.ingest.fold_stream` — the same
   decode + atomic-fold path the HTTP endpoint runs — at production batch
   shapes. Reports sustained MB/s and rows/s including decode, checksum
   (optional) and the full verification fold, versus the raw feed-link
   probe the bench reports.

Usage::

    python -m tools.ingest_soak --sessions 1000 --batches 2 --rows 4096
    python -m tools.ingest_soak --stream-mb 512            # throughput only
    python -m tools.ingest_soak --sweep                    # knee ladder

Exit code 0 iff every fold terminated (result or typed shed) and the
stream-throughput parity check held. JSON summary on stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional


def _checks():
    from deequ_tpu.checks import Check, CheckLevel

    return [
        Check(CheckLevel.ERROR, "ingest battery")
        .has_size(lambda n: n > 0)
        .is_complete("x")
        .has_mean("y", lambda m: -100.0 < m < 100.0),
    ]


def _build_table(rows: int, seed: int = 7):
    import numpy as np
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    return pa.table({
        "x": rng.normal(size=rows),
        "y": rng.normal(10.0, 2.0, size=rows),
        "k": rng.integers(0, 1000, size=rows),
        "v": rng.uniform(0, 1, size=rows),
    })


# ---------------------------------------------------------------------------
# measurement 1: concurrency soak under bounded admission
# ---------------------------------------------------------------------------


def run_concurrency_soak(
    sessions: int = 1000,
    batches: int = 2,
    rows: int = 4096,
    workers: int = 8,
    queue_depth: int = 256,
    block_s: float = 30.0,
    feeders: int = 32,
    service=None,
) -> Dict:
    """Drive ``sessions`` concurrent streaming sessions, ``batches``
    micro-batches each, through bounded admission with backpressure.
    Every session shares one table's slices (zero-copy record batches) so
    the measurement is the SERVICE's, not the data generator's."""
    import threading

    from deequ_tpu.service import ServiceError, VerificationService

    table = _build_table(rows * batches)
    slices = [table.slice(b * rows, rows) for b in range(batches)]
    payload_mb = sum(s.nbytes for s in slices) / 1e6
    checks = _checks()
    own_service = service is None
    if own_service:
        service = VerificationService(
            workers=workers, max_queue_depth=queue_depth,
            background_warm=False,
        )
    summary: Dict = {
        "sessions": sessions, "batches_per_session": batches,
        "rows_per_batch": rows, "workers": workers,
        "queue_depth": queue_depth,
    }
    try:
        # pre-create the sessions (registration is not the measurement)
        sess = [
            service.session(f"soak-{i}", "stream", checks,
                            admission_block_s=block_s)
            for i in range(sessions)
        ]
        # one tiny warm fold compiles the (shared) bucketed program shape
        # so the soak measures the service, not one XLA compile
        warm = service.session("soak-warm", "stream", checks,
                               admission_block_s=block_s)
        warm.ingest(slices[0])

        shed_before = service.metrics.counter_value(
            "deequ_service_jobs_shed_total"
        )
        errors: List[str] = []
        handles_lock = threading.Lock()
        all_handles = []

        def feed(lo: int, hi: int) -> None:
            mine = []
            for i in range(lo, hi):
                for b in range(batches):
                    try:
                        mine.append(sess[i].ingest(slices[b], wait=False))
                    except ServiceError as exc:
                        with handles_lock:
                            errors.append(type(exc).__name__)
            with handles_lock:
                all_handles.extend(mine)

        n_feeders = max(1, min(feeders, sessions))
        per = -(-sessions // n_feeders)
        threads = [
            threading.Thread(
                target=feed, args=(f * per, min((f + 1) * per, sessions)),
                daemon=True,
            )
            for f in range(n_feeders)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        failed = 0
        for h in all_handles:
            try:
                h.result(timeout=300)
            except Exception:  # noqa: BLE001 - counted, soak verdict below
                failed += 1
        wall = time.perf_counter() - t0
        done_sessions = sum(
            1 for s in sess if s.batches_ingested == batches
        )
        total_mb = sum(s.bytes_ingested for s in sess) / 1e6
        summary.update({
            "wall_s": round(wall, 3),
            "sessions_completed": done_sessions,
            "sessions_per_s": round(done_sessions / wall, 1),
            "folds_per_s": round(len(all_handles) / wall, 1),
            "mb_per_s": round(total_mb / wall, 1),
            "ingested_mb": round(total_mb, 1),
            "payload_mb_per_session": round(payload_mb, 3),
            "shed": int(
                service.metrics.counter_value("deequ_service_jobs_shed_total")
                - shed_before
            ),
            "feeder_errors": len(errors),
            "failed_folds": failed,
            "ok": failed == 0 and done_sessions == sessions,
        })
        # tail latency from the service's own SLO histograms (merged
        # across tenants): what fleetwatch burn rates are computed from,
        # surfaced here so bench_diff can regress on it
        from deequ_tpu.service.metrics import histogram_quantile

        for slug, series in (
            ("fold_latency", "deequ_service_fold_latency_seconds"),
            ("admission_wait", "deequ_service_admission_wait_seconds"),
        ):
            state = service.metrics.histogram_merged(series)
            for q, tag in ((0.5, "p50"), (0.99, "p99")):
                value = histogram_quantile(state, q)
                if value is not None and value != float("inf"):
                    summary[f"{slug}_{tag}_s"] = round(value, 6)
    finally:
        if own_service:
            service.close()
    return summary


# ---------------------------------------------------------------------------
# measurement 2: sustained in-process Arrow stream throughput
# ---------------------------------------------------------------------------


def run_stream_throughput(
    target_mb: float = 512.0,
    rows_per_batch: int = 1 << 20,
    checksum: bool = False,
    workers: int = 4,
) -> Dict:
    """Feed ONE session Arrow IPC payloads through ``fold_stream`` until
    ``target_mb`` of wire bytes have folded; report sustained MB/s and
    rows/s (decode + optional checksum + the full verification fold), and
    parity-check the folded metrics against a direct in-process run of
    the same battery over the same concatenated data."""
    import numpy as np

    from deequ_tpu.ingest import encode_ipc_stream, fold_stream
    from deequ_tpu.integrity import checksum_bytes
    from deequ_tpu.service import VerificationService

    table = _build_table(rows_per_batch, seed=11)
    payload = encode_ipc_stream(table)
    digest = checksum_bytes(payload) if checksum else None
    n_streams = max(1, int(target_mb * 1e6 / len(payload)))
    checks = _checks()
    with VerificationService(
        workers=workers, max_queue_depth=64, background_warm=False
    ) as service:
        session = service.session("tput", "stream", checks,
                                  admission_block_s=60.0)
        # warm fold: compile the bucketed batch shape outside the timing
        warm = service.session("tput-warm", "stream", checks)
        warm.ingest(table.slice(0, rows_per_batch))

        t0 = time.perf_counter()
        frames = 0
        for _ in range(n_streams):
            report = fold_stream(session, payload, checksum=digest,
                                 source="soak")
            frames += report.frames
        wall = time.perf_counter() - t0
        total_mb = n_streams * len(payload) / 1e6
        total_rows = n_streams * rows_per_batch

        # parity: cumulative session metrics == one direct run over the
        # same data repeated n_streams times (algebraic states make the
        # mean/completeness identical; size is n_streams * rows)
        cum = session.current()
        from deequ_tpu.checks import CheckStatus

        parity_ok = cum.status == CheckStatus.SUCCESS
        mean_direct = float(np.mean(table["y"].to_numpy()))
        mean_stream = None
        for a, m in cum.metrics.items():
            if a.name == "Mean" and a.instance == "y" and m.value.is_success:
                mean_stream = m.value.get()
        if mean_stream is not None:
            parity_ok = parity_ok and abs(mean_stream - mean_direct) <= 1e-9
    return {
        "streams": n_streams,
        "frames": frames,
        "rows_per_batch": rows_per_batch,
        "checksum": bool(checksum),
        "wall_s": round(wall, 3),
        "mb_per_s": round(total_mb / wall, 1),
        "rows_per_s": round(total_rows / wall, 1),
        "ingested_mb": round(total_mb, 1),
        "parity_ok": parity_ok,
        "ok": parity_ok,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--sessions", type=int, default=1000)
    parser.add_argument("--batches", type=int, default=2)
    parser.add_argument("--rows", type=int, default=4096)
    parser.add_argument("--workers", type=int, default=8)
    parser.add_argument("--queue-depth", type=int, default=256)
    parser.add_argument("--block-s", type=float, default=30.0)
    parser.add_argument("--stream-mb", type=float, default=0.0,
                        help="run ONLY the stream-throughput measurement "
                        "at this many MB")
    parser.add_argument("--checksum", action="store_true",
                        help="verify xxhash64 on every stream payload")
    parser.add_argument("--sweep", action="store_true",
                        help="double session counts up to --sessions to "
                        "expose the concurrency knee")
    args = parser.parse_args(argv)
    if args.stream_mb > 0:
        summary = run_stream_throughput(
            target_mb=args.stream_mb, checksum=args.checksum,
            workers=args.workers,
        )
    elif args.sweep:
        points = []
        n = max(args.sessions // 8, 8)
        while n <= args.sessions:
            points.append(run_concurrency_soak(
                sessions=n, batches=args.batches, rows=args.rows,
                workers=args.workers, queue_depth=args.queue_depth,
                block_s=args.block_s,
            ))
            n *= 2
        summary = {
            "sweep": [
                {k: p[k] for k in ("sessions", "sessions_per_s", "mb_per_s",
                                   "shed", "ok")}
                for p in points
            ],
            "ok": all(p["ok"] for p in points),
        }
    else:
        summary = run_concurrency_soak(
            sessions=args.sessions, batches=args.batches, rows=args.rows,
            workers=args.workers, queue_depth=args.queue_depth,
            block_s=args.block_s,
        )
    print(json.dumps(summary), flush=True)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
