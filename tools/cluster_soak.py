"""Multi-process cluster soak: N worker PROCESSES, one service.

The in-process cluster tier (``deequ_tpu.cluster``) is exercised here
with real OS-process workers: each worker runs a whole
VerificationService — its own FleetScheduler, coalescer, HTTP ingest
endpoint and metrics exporter — against ONE shared partition store, and
the parent drives the REAL :class:`~deequ_tpu.cluster.front.FrontTier`
over HTTP-fronted worker adapters: session keys route on the consistent-
hash ring, micro-batches POST as Arrow IPC to the ring-chosen worker's
``/ingest/v1/...`` endpoint, fold boundaries flush into the store, and
losses recover by adoption + journal replay.

Two modes, both printing ONE machine-readable JSON line (exit 0 = pass,
1 = verdict failed, 2 = environment cannot run the scenario — skipped):

- **throughput** (default; ``--procs N --sessions S --batches B``):
  S sessions stream B exact-sum batches each, concurrently, across N
  worker processes. Reports aggregate ``sessions_per_s`` and gates on
  PARITY: every session's final Sum/Size must equal the closed-form
  oracle EXACTLY (integer-valued data makes the sums order-independent),
  so scale-out is only counted when the metrics are bit-identical to a
  single process.
- **kill-one drill** (``--drill kill-one``): sessions stream and flush
  mid-window, then the parent SIGKILLs one worker. The membership scan
  declares it lost, the ring re-hashes to the survivor, every orphaned
  session is adopted from its last flushed partition and the journaled
  post-flush folds replay. The verdict asserts exact parity (no lost, no
  double-committed folds) AND the typed
  ``deequ_service_cluster_*`` counters that prove recovery ran — and
  that the VICTIM's span journal is non-empty (a SIGKILLed worker still
  leaves a worker-side flight dump behind).

Both modes run with the observability plane default-ON: every process
(front + workers) journals its spans (``DEEQU_TPU_TRACE_JOURNAL``), the
trace context rides the ctl file-RPC (``trace`` field) and the Arrow
ingest wire (``X-Deequ-Trace``), and after the run the per-host journals
merge into ONE Perfetto trace (``merged.trace.json``). The verdict gates
on a CROSS-PROCESS trace: at least one trace_id whose front-side
``cluster_ingest`` span and worker-side spans live in different
journals. It also fetches a worker's ``/statusz``, schema-validates it,
and requires all six ops planes present.

``--stage-json`` is accepted for bench-stage symmetry (the JSON line is
always printed). The worker side (``--worker I --dir D``) is internal.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

DEFAULT_SESSIONS = 8
DEFAULT_BATCHES = 8
DEFAULT_ROWS = 4_096
WORKER_BOOT_TIMEOUT_S = 120.0
CTL_TIMEOUT_S = 120.0


# --------------------------------------------------------------------------
# shared: the exact-sum battery + deterministic per-session data
# --------------------------------------------------------------------------

def _battery_checks():
    from deequ_tpu.checks import Check, CheckLevel

    return [
        Check(CheckLevel.ERROR, "cluster-soak")
        .is_complete("v")
        .has_size(lambda n: n > 0)
    ]


def _required_analyzers():
    from deequ_tpu.analyzers import Sum

    return [Sum("v")]


def _batch_values(session_index: int, batch_index: int, rows: int):
    """Integer-valued float64s, unique per (session, batch) — sums are
    EXACT in any fold order (all intermediates < 2**53), which is what
    lets the parity gate demand bit-equality across process counts."""
    import numpy as np

    base = session_index * 100_000_000 + batch_index * rows
    return np.arange(base, base + rows, dtype=np.float64)


def _oracle(session_index: int, batches: int, rows: int) -> dict:
    total = 0
    for b in range(batches):
        base = session_index * 100_000_000 + b * rows
        total += (2 * base + rows - 1) * rows // 2
    return {"sum": float(total), "size": float(batches * rows)}


def _session_key(i: int):
    return (f"tenant-{i % 4}", f"stream-{i}")


# --------------------------------------------------------------------------
# worker process
# --------------------------------------------------------------------------

def run_worker(worker_id: int, run_dir: str) -> None:
    """One cluster worker process: a full service plane + file-RPC
    control loop. The control files (``ctl/<host>-<seq>.json`` ->
    ``ack/<same>.json``) carry the session protocol the HTTP ingest
    endpoint does not: open / adopt / flush / release / stats / stop."""
    from deequ_tpu.cluster import HeartbeatMembership, LocalWorker
    from deequ_tpu.service import VerificationService

    host_id = f"w{worker_id}"
    store_root = os.path.join(run_dir, "store")
    service = VerificationService(
        workers=2, background_warm=False, partition_store=store_root
    )
    exporter = service.start_exporter("127.0.0.1", 0)
    membership = HeartbeatMembership(
        os.path.join(run_dir, "hb"), host_id=host_id,
        heartbeat_period_s=0.2,
    )
    worker = LocalWorker(host_id, service, membership=membership)
    worker.start()

    port_path = os.path.join(run_dir, f"port-{host_id}.json")
    with open(port_path + ".tmp", "w", encoding="utf-8") as fh:
        fh.write(json.dumps({"port": exporter.port, "pid": os.getpid()}))
    os.replace(port_path + ".tmp", port_path)

    ctl_dir = os.path.join(run_dir, "ctl")
    ack_dir = os.path.join(run_dir, "ack")
    os.makedirs(ctl_dir, exist_ok=True)
    os.makedirs(ack_dir, exist_ok=True)

    def session_values(tenant: str, dataset: str) -> dict:
        session = service.get_session(tenant, dataset)
        if session is None:
            return {}
        res = session.current()
        out = {
            str(a): float(m.value.get()) for a, m in res.metrics.items()
        }
        out["_batches"] = float(session.batches_ingested)
        out["_rows"] = float(session.rows_ingested)
        return out

    def handle(op: dict) -> dict:
        kind = op["op"]
        tenant, dataset = op.get("tenant", ""), op.get("dataset", "")
        # the ctl file-RPC carries the front tier's serialized trace
        # context: the worker-side protocol span parents into the
        # front's trace, one trace_id across the process hop
        trace = op.get("trace")
        if kind == "open":
            worker.open_session(
                tenant, dataset, _battery_checks(), trace_ctx=trace,
                required_analyzers=_required_analyzers(),
            )
            return {"ok": True}
        if kind == "adopt":
            worker.adopt_session(
                tenant, dataset, _battery_checks(),
                partition=op.get("partition") or None, trace_ctx=trace,
                required_analyzers=_required_analyzers(),
            )
            return {"ok": True}
        if kind == "flush":
            return {"ok": True,
                    "partition": worker.flush(tenant, dataset,
                                              trace_ctx=trace)}
        if kind == "release":
            return {"ok": True,
                    "partition": worker.release(tenant, dataset,
                                                trace_ctx=trace)}
        if kind == "stats":
            return {"ok": True, "values": session_values(tenant, dataset)}
        if kind == "stop":
            return {"ok": True, "stopping": True}
        return {"ok": False, "error": f"unknown op {kind!r}"}

    idle_deadline = time.monotonic() + 600
    prefix = f"{host_id}-"
    while time.monotonic() < idle_deadline:
        handled = False
        try:
            names = sorted(os.listdir(ctl_dir))
        except OSError:
            names = []
        for name in names:
            if not name.startswith(prefix):
                continue
            path = os.path.join(ctl_dir, name)
            try:
                with open(path, encoding="utf-8") as fh:
                    op = json.load(fh)
            except (OSError, ValueError):
                continue  # mid-write; next poll sees the full file
            try:
                result = handle(op)
            except Exception as exc:  # noqa: BLE001 - reported to parent
                result = {"ok": False, "error": repr(exc)}
            ack = os.path.join(ack_dir, name)
            with open(ack + ".tmp", "w", encoding="utf-8") as fh:
                fh.write(json.dumps(result))
            os.replace(ack + ".tmp", ack)
            os.unlink(path)
            handled = True
            idle_deadline = time.monotonic() + 600
            if result.get("stopping"):
                worker.close(wait=False)
                os._exit(0)  # noqa: SLF001 - fast teardown by design
        if not handled:
            time.sleep(0.02)
    os._exit(0)  # noqa: SLF001 - parent went away


# --------------------------------------------------------------------------
# parent: HTTP-fronted worker adapter speaking the LocalWorker protocol
# --------------------------------------------------------------------------

class HttpWorker:
    """The front tier's view of a REMOTE worker process: the
    :class:`~deequ_tpu.cluster.worker.LocalWorker` protocol over the
    worker's HTTP ingest endpoint (data plane) + file-RPC control files
    (session plane). Checks live worker-side; the spec args the front
    tier forwards are ignored here by design."""

    def __init__(self, host_id: str, run_dir: str, port: int, pid: int):
        self.host_id = host_id
        self.run_dir = run_dir
        self.port = port
        self.pid = pid
        self._seq = 0
        self._seq_lock = threading.Lock()

    def start(self) -> None:  # heartbeats run worker-side
        pass

    def _ctl(self, op: str, timeout_s: float = CTL_TIMEOUT_S, **fields):
        with self._seq_lock:
            self._seq += 1
            name = f"{self.host_id}-{self._seq:06d}.json"
        ctl = os.path.join(self.run_dir, "ctl", name)
        ack = os.path.join(self.run_dir, "ack", name)
        with open(ctl + ".tmp", "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"op": op, **fields}))
        os.replace(ctl + ".tmp", ctl)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(ack):
                try:
                    with open(ack, encoding="utf-8") as fh:
                        result = json.load(fh)
                except (OSError, ValueError):
                    time.sleep(0.02)
                    continue
                os.unlink(ack)
                if not result.get("ok"):
                    raise RuntimeError(
                        f"{self.host_id} {op} failed: {result.get('error')}"
                    )
                return result
            time.sleep(0.02)
        raise TimeoutError(f"{self.host_id} did not ack {op}")

    def open_session(self, tenant, dataset, checks=(), trace_ctx=None, **kw):
        self._ctl("open", tenant=tenant, dataset=dataset, trace=trace_ctx)

    def adopt_session(self, tenant, dataset, checks=(), partition=None,
                      trace_ctx=None, **kw):
        self._ctl("adopt", tenant=tenant, dataset=dataset,
                  partition=partition, trace=trace_ctx)

    def flush(self, tenant, dataset, partition=None, trace_ctx=None):
        return self._ctl("flush", tenant=tenant, dataset=dataset,
                         trace=trace_ctx).get("partition")

    def release(self, tenant, dataset, trace_ctx=None):
        return self._ctl("release", tenant=tenant, dataset=dataset,
                         trace=trace_ctx).get("partition")

    def stats(self, tenant, dataset) -> dict:
        return self._ctl("stats", tenant=tenant, dataset=dataset).get(
            "values", {}
        )

    def statusz(self) -> dict:
        import urllib.request

        url = f"http://127.0.0.1:{self.port}/statusz"
        with urllib.request.urlopen(url, timeout=30) as resp:
            return json.loads(resp.read())

    def ingest(self, tenant, dataset, data, trace_ctx=None, **kw):
        import http.client

        import pyarrow as pa

        from deequ_tpu.ingest.arrow_stream import encode_ipc_stream
        from deequ_tpu.observability.trace import TRACE_HEADER

        body = encode_ipc_stream(pa.table(data))
        headers = {"Content-Length": str(len(body))}
        if trace_ctx:
            # the Arrow data plane carries the trace too: the worker's
            # ingest_request span joins the front's trace_id
            headers[TRACE_HEADER] = trace_ctx
        conn = http.client.HTTPConnection("127.0.0.1", self.port, timeout=60)
        try:
            conn.request(
                "POST", f"/ingest/v1/{tenant}/{dataset}", body=body,
                headers=headers,
            )
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"ingest on {self.host_id} -> {resp.status}: "
                    f"{payload[:200]!r}"
                )
            return json.loads(payload)
        finally:
            conn.close()

    def close(self, **kw) -> None:
        try:
            self._ctl("stop", timeout_s=10)
        except (RuntimeError, TimeoutError, OSError):
            pass
        try:
            os.kill(self.pid, signal.SIGKILL)
        except OSError:
            pass


# --------------------------------------------------------------------------
# parent orchestration
# --------------------------------------------------------------------------

def _journal_dir(run_dir: str) -> str:
    return os.path.join(run_dir, "journal")


def _enable_front_journal(run_dir: str) -> None:
    """Journal the PARENT's spans (the front tier runs in this process)
    beside the workers' — the merged artifact needs both halves of every
    hop. Must run before the first front-tier span finishes: the flight
    recorder probes the env once, lazily."""
    os.makedirs(_journal_dir(run_dir), exist_ok=True)
    os.environ["DEEQU_TPU_TRACE_JOURNAL"] = _journal_dir(run_dir)
    os.environ["DEEQU_TPU_TRACE_HOST"] = "front"


def _spawn_cluster(procs: int, run_dir: str):
    """Spawn worker processes; returns (popen list, HttpWorker list) or
    raises TimeoutError when the environment cannot boot them."""
    os.makedirs(os.path.join(run_dir, "ctl"), exist_ok=True)
    os.makedirs(os.path.join(run_dir, "ack"), exist_ok=True)
    os.makedirs(_journal_dir(run_dir), exist_ok=True)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DEEQU_TPU_TRACE_JOURNAL"] = _journal_dir(run_dir)
    children = [
        subprocess.Popen(
            [sys.executable, "-m", "tools.cluster_soak",
             "--worker", str(i), "--dir", run_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env={**env, "DEEQU_TPU_TRACE_HOST": f"w{i}"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(procs)
    ]
    workers = []
    deadline = time.monotonic() + WORKER_BOOT_TIMEOUT_S
    for i in range(procs):
        host_id = f"w{i}"
        port_path = os.path.join(run_dir, f"port-{host_id}.json")
        while not os.path.exists(port_path):
            if time.monotonic() > deadline or children[i].poll() is not None:
                if children[i].poll() is not None:
                    detail = children[i].communicate()[1].decode()[-400:]
                else:
                    detail = "boot timeout"
                raise TimeoutError(
                    f"worker {host_id} never came up: {detail}"
                )
            time.sleep(0.05)
        with open(port_path, encoding="utf-8") as fh:
            boot = json.load(fh)
        workers.append(HttpWorker(host_id, run_dir, boot["port"], boot["pid"]))
    return children, workers


def _build_front(workers, run_dir: str, ttl_s: float = 2.0):
    from deequ_tpu.cluster import FrontTier, HeartbeatMembership

    front = FrontTier(
        membership=HeartbeatMembership(
            os.path.join(run_dir, "hb"), ttl_s=ttl_s
        )
    )
    for worker in workers:
        front.add_worker(worker)
    return front


def _parity(front, sessions: int, batches: int, rows: int):
    """Compare every session's final metrics to the closed-form oracle.
    EXACT equality — integer-valued sums are order-independent."""
    failures = []
    for i in range(sessions):
        tenant, dataset = _session_key(i)
        host = front.placement(tenant, dataset)
        values = front.workers[host].stats(tenant, dataset)
        want = _oracle(i, batches, rows)
        got_sum = next(
            (v for k, v in values.items() if k.startswith("Sum(")), None
        )
        got_size = next(
            (v for k, v in values.items() if k.startswith("Size(")), None
        )
        if got_sum != want["sum"] or got_size != want["size"]:
            failures.append({
                "session": f"{tenant}/{dataset}", "host": host,
                "got_sum": got_sum, "want_sum": want["sum"],
                "got_size": got_size, "want_size": want["size"],
            })
    return failures


def _counters(front) -> dict:
    names = [
        "deequ_service_cluster_routes_total",
        "deequ_service_cluster_migrations_total",
        "deequ_service_cluster_host_losses_total",
        "deequ_service_cluster_ring_moves_total",
        "deequ_service_cluster_sessions_recovered_total",
        "deequ_service_cluster_replayed_folds_total",
    ]
    return {n: front.metrics.counter_value(n) for n in names}


def _observability_verdict(run_dir: str, worker) -> dict:
    """The cross-process tentpole assertions, evaluated from artifacts —
    not internals: merge every per-host span journal into ONE Perfetto
    trace, demand at least one ingest whose front-side ``cluster_ingest``
    span and worker-side spans share a trace_id across journals, and
    schema-validate a live worker's ``/statusz`` (all six ops planes)."""
    import glob

    from deequ_tpu.observability.export import load_journal, merge_journals
    from deequ_tpu.service.statusz import validate_statusz

    journals = sorted(
        glob.glob(os.path.join(_journal_dir(run_dir), "spans-*.jsonl"))
    )
    merged_path = None
    front_ingest = set()
    worker_traces = {}
    hosts_by_trace = {}
    if journals:
        merged_path = os.path.join(run_dir, "merged.trace.json")
        merge_journals(journals, out_path=merged_path)
        for path in journals:
            header, spans, _skipped = load_journal(path)
            host = header.get("host") or os.path.basename(path)
            for s in spans:
                tid = s.get("trace_id")
                if not tid:
                    continue
                hosts_by_trace.setdefault(tid, set()).add(host)
                if host == "front" and s.get("name") == "cluster_ingest":
                    front_ingest.add(tid)
                elif host != "front":
                    worker_traces.setdefault(tid, set()).add(host)
    cross = [t for t, h in hosts_by_trace.items() if len(h) >= 2]
    cross_ingest = [t for t in front_ingest if worker_traces.get(t)]

    problems = []
    planes = []
    try:
        doc = worker.statusz()
        problems = validate_statusz(doc)
        planes = sorted((doc.get("planes") or {}))
    except Exception as exc:  # noqa: BLE001 - reported in the verdict
        problems = [f"statusz fetch failed: {exc!r}"]
    return {
        "ok": bool(cross_ingest) and not problems,
        "journals": len(journals),
        "merged_trace": merged_path,
        "cross_process_traces": len(cross),
        "cross_process_ingest_traces": len(cross_ingest),
        "statusz_planes": planes,
        "statusz_problems": problems,
    }


def run_throughput(procs: int, sessions: int, batches: int,
                   rows: int) -> int:
    from concurrent.futures import ThreadPoolExecutor

    run_dir = tempfile.mkdtemp(prefix="cluster-soak-")
    _enable_front_journal(run_dir)
    children = []
    try:
        try:
            children, workers = _spawn_cluster(procs, run_dir)
        except (TimeoutError, OSError) as exc:
            print(json.dumps({"ok": False, "skipped": True,
                              "reason": str(exc)}))
            return 2
        front = _build_front(workers, run_dir)
        for i in range(sessions):
            tenant, dataset = _session_key(i)
            front.open_session(tenant, dataset)

        def drive(i: int):
            tenant, dataset = _session_key(i)
            for b in range(batches):
                front.ingest(
                    tenant, dataset, {"v": _batch_values(i, b, rows)}
                )

        started = time.monotonic()
        with ThreadPoolExecutor(max_workers=sessions) as pool:
            for future in [pool.submit(drive, i) for i in range(sessions)]:
                future.result()
        elapsed = time.monotonic() - started

        front.flush_all()
        failures = _parity(front, sessions, batches, rows)
        obs = _observability_verdict(run_dir, workers[0])
        report = {
            "ok": not failures and obs["ok"], "skipped": False,
            "mode": "throughput",
            "procs": procs, "sessions": sessions, "batches": batches,
            "rows": rows, "elapsed_s": round(elapsed, 4),
            "sessions_per_s": round(sessions / elapsed, 4),
            "folds_per_s": round(sessions * batches / elapsed, 4),
            "parity_failures": failures,
            "counters": _counters(front),
            "observability": obs,
        }
        front.close()
        print(json.dumps(report))
        return 0 if report["ok"] else 1
    finally:
        for child in children:
            if child.poll() is None:
                child.kill()
            child.communicate()


def run_kill_one(sessions: int, batches: int, rows: int) -> int:
    run_dir = tempfile.mkdtemp(prefix="cluster-drill-")
    _enable_front_journal(run_dir)
    children = []
    try:
        try:
            children, workers = _spawn_cluster(2, run_dir)
        except (TimeoutError, OSError) as exc:
            print(json.dumps({"ok": False, "skipped": True,
                              "reason": str(exc)}))
            return 2
        front = _build_front(workers, run_dir, ttl_s=1.5)
        for i in range(sessions):
            tenant, dataset = _session_key(i)
            front.open_session(tenant, dataset)

        half = max(1, batches // 2)
        for i in range(sessions):
            tenant, dataset = _session_key(i)
            for b in range(half):
                front.ingest(tenant, dataset,
                             {"v": _batch_values(i, b, rows)})
            # fold boundary: states + contract hit the shared store and
            # the journal clears — what the victim's folds survive by
            front.flush(tenant, dataset)
        for i in range(sessions):
            tenant, dataset = _session_key(i)
            for b in range(half, batches):
                front.ingest(tenant, dataset,
                             {"v": _batch_values(i, b, rows)})

        placements_before = {
            _session_key(i): front.placement(*_session_key(i))
            for i in range(sessions)
        }
        victims = sorted(
            {h for h in placements_before.values()}
        )
        victim = victims[0]
        victim_sessions = [
            k for k, h in placements_before.items() if h == victim
        ]
        killed_at = time.monotonic()
        os.kill(front.workers[victim].pid, signal.SIGKILL)

        # wait out the heartbeat TTL, then let the membership sweep find
        # the corpse and run recovery (ring re-hash + adopt + replay)
        deadline = time.monotonic() + 30
        recovered = []
        while time.monotonic() < deadline and not recovered:
            time.sleep(0.3)
            recovered = front.check_membership()
        # SIGKILL -> every orphaned session adopted + replayed; dominated
        # by the heartbeat TTL (the detection floor), not the recovery
        recovery_s = time.monotonic() - killed_at

        moved = {
            f"{k[0]}/{k[1]}": [placements_before[k],
                               front.placement(*k)]
            for k in victim_sessions
        }
        failures = _parity(front, sessions, batches, rows)
        counters = _counters(front)
        # the worker-side flight dump: a SIGKILLed worker can't export,
        # but its line-buffered span journal survives the kill — a
        # victim that emitted no spans had no post-mortem
        victim_journal = os.path.join(
            _journal_dir(run_dir), f"spans-{victim}.jsonl"
        )
        victim_spans = 0
        try:
            with open(victim_journal, encoding="utf-8") as fh:
                victim_spans = sum(
                    1 for line in fh if '"span_id"' in line
                )
        except OSError:
            pass
        survivor = next(w for w in workers if w.host_id != victim)
        obs = _observability_verdict(run_dir, survivor)
        ok = (
            not failures
            and recovered == [victim]
            and all(src != dst for src, dst in moved.values())
            and counters["deequ_service_cluster_host_losses_total"] >= 1
            and counters["deequ_service_cluster_sessions_recovered_total"]
            >= len(victim_sessions)
            and counters["deequ_service_cluster_replayed_folds_total"]
            >= len(victim_sessions)
            and victim_spans >= 1
            and obs["ok"]
        )
        report = {
            "ok": ok, "skipped": False, "mode": "kill-one",
            "victim": victim, "recovered_hosts": recovered,
            "victim_sessions": len(victim_sessions), "rehomed": moved,
            "recovery_s": round(recovery_s, 3),
            "parity_failures": failures, "counters": counters,
            "victim_journal_spans": victim_spans,
            "observability": obs,
        }
        front.close()
        print(json.dumps(report))
        return 0 if ok else 1
    finally:
        for child in children:
            if child.poll() is None:
                child.kill()
            child.communicate()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--worker", type=int, default=None)
    parser.add_argument("--dir", default=None)
    parser.add_argument("--procs", type=int, default=2)
    parser.add_argument("--sessions", type=int, default=DEFAULT_SESSIONS)
    parser.add_argument("--batches", type=int, default=DEFAULT_BATCHES)
    parser.add_argument("--rows", type=int, default=DEFAULT_ROWS)
    parser.add_argument("--drill", choices=["kill-one"], default=None)
    parser.add_argument("--stage-json", action="store_true",
                        help="bench-stage symmetry flag (JSON always prints)")
    args = parser.parse_args()

    if args.worker is not None:
        run_worker(args.worker, args.dir)
        return 0
    if args.drill == "kill-one":
        return run_kill_one(args.sessions, args.batches, args.rows)
    return run_throughput(args.procs, args.sessions, args.batches, args.rows)


if __name__ == "__main__":
    sys.exit(main())
