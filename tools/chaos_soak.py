"""Chaos soak: drive the full verification service under injected faults.

One-shot drill (``python -m tools.chaos_soak``) that arms the seeded fault
injector (`deequ_tpu.reliability.faults`) with a mixed plan — device
failures, OOMs, per-analyzer faults, worker deaths, streaming-fold crashes
— then pushes a burst of one-shot verification jobs plus a streaming
session through the `VerificationService` scheduler and asserts the
reliability invariants:

1. every job TERMINATES: a result or a typed ``ServiceError``, never a
   hung handle;
2. every completed verification carries a verdict for every analyzer —
   injected analyzer faults degrade to typed ``Failure`` metrics, they do
   not shrink the metric map;
3. device faults never kill a run: the engine fails over to the host tier
   (RunMonitor records it, the placement router learns);
4. the streaming session's fold count equals its successful ingests (no
   double-folds from retries, no silent drops).

Exit code 0 iff all invariants hold; a JSON summary goes to stdout. The
same ``run_soak`` body backs ``tests/test_chaos_soak.py`` (tier-1 runs a
small soak; the big one is marked slow).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict


def _build_data(rows: int, seed: int):
    import numpy as np

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(seed)
    return Dataset.from_dict(
        {
            "x": rng.normal(size=rows),
            "y": rng.normal(10.0, 2.0, size=rows),
            "cat": [f"c{i % 13}" for i in range(rows)],
        }
    )


def _checks():
    from deequ_tpu.checks import Check, CheckLevel

    return [
        Check(CheckLevel.ERROR, "chaos battery")
        .has_size(lambda n: n > 0)
        .is_complete("x")
        .has_mean("y", lambda m: 5.0 < m < 15.0)
        .has_standard_deviation("y", lambda s: s > 0)
        .has_approx_count_distinct("cat", lambda c: c > 0),
    ]


def default_plan(seed: int):
    """The mixed fault plan: every major site, seeded probabilities, plus
    one deterministic per-analyzer fault so isolation is always hit."""
    from deequ_tpu.reliability import FaultSpec

    return [
        FaultSpec("device_update", "device", p=0.10, count=None),
        FaultSpec("device_update", "oom", p=0.04, count=None),
        FaultSpec("host_partial", "poison", p=0.01, count=3),
        FaultSpec("analyzer", "analyzer", match="StandardDeviation", p=0.25,
                  count=None),
        FaultSpec("worker", "worker_death", p=0.08, count=None),
        FaultSpec("stream_fold", "worker_death", p=0.10, count=None),
        FaultSpec("compile", "stall", p=0.2, count=2, delay_s=0.05),
        # data-plane integrity faults: a corrupt persisted state blob at
        # load time degrades exactly the analyzer that needed it; a
        # drifted micro-batch is rejected BEFORE the fold (the parity
        # invariant below proves rejected batches never half-fold)
        FaultSpec("state_load", "corrupt", p=0.05, count=3),
        FaultSpec("stream_fold", "drift", p=0.08, count=2),
        # the repository drill's second read sees a whole-file corruption
        FaultSpec("repository_load", "corrupt", at=2, count=1),
    ]


def run_soak(
    jobs: int = 30,
    stream_batches: int = 8,
    rows: int = 4096,
    seed: int = 0,
    workers: int = 4,
    specs=None,
    cluster_drill: bool = False,
) -> Dict:
    """Run the soak; returns the summary dict (see module docstring for
    the invariants it asserts). ``cluster_drill=True`` additionally runs
    the multi-PROCESS kill-one drill (ISSUE 16): real worker processes
    behind the front tier, one SIGKILLed mid-stream — off by default
    because it spawns interpreters (the tier-1 soak stays in-process;
    the CLI and the slow soak turn it on)."""
    import tempfile

    from deequ_tpu.exceptions import SchemaDriftError
    from deequ_tpu.reliability import WorkerCrash, install, clear
    from deequ_tpu.runners.analysis_runner import collect_required_analyzers
    from deequ_tpu.service import ServiceError, VerificationService

    checks = _checks()
    n_analyzers = len(dict.fromkeys(collect_required_analyzers(checks)))
    data = _build_data(rows, seed)
    injector = install(specs if specs is not None else default_plan(seed),
                       seed=seed)
    t0 = time.perf_counter()
    summary: Dict = {
        "jobs": jobs, "stream_batches": stream_batches, "seed": seed,
        "succeeded": 0, "typed_failures": 0, "untyped_failures": 0,
        "unterminated": 0, "incomplete_metric_maps": 0,
        "degraded_metrics": 0, "stream_folds_ok": 0, "drift_rejects": 0,
    }
    state_root = tempfile.mkdtemp(prefix="chaos-soak-states-")
    try:
        with VerificationService(
            workers=workers, max_queue_depth=jobs + stream_batches + 8,
            background_warm=False,
            # filesystem-backed session states: the streaming folds then
            # exercise the checksummed state path and its state_load site
            state_root=state_root,
        ) as service:
            handles = [
                service.submit_verification(
                    data, checks, tenant=f"t{i % 3}",
                    max_retries=2, retry_on=(WorkerCrash,),
                )
                for i in range(jobs)
            ]
            session = service.session(
                "chaos", "stream", checks, max_retries=0
            )
            stream_results = []
            for b in range(stream_batches):
                batch = _build_data(512, seed + 1000 + b)
                try:
                    stream_results.append(session.ingest(batch, timeout=120))
                except SchemaDriftError:
                    # an injected drift fires BEFORE the fold: the batch is
                    # rejected typed and must not count as folded
                    summary["drift_rejects"] += 1
                    stream_results.append(None)
                except ServiceError:
                    stream_results.append(None)
            for handle in handles:
                try:
                    result = handle.result(timeout=180)
                except ServiceError:
                    summary["typed_failures"] += 1
                    continue
                except TimeoutError:
                    summary["unterminated"] += 1
                    continue
                except Exception:  # noqa: BLE001 - invariant breach
                    summary["untyped_failures"] += 1
                    continue
                summary["succeeded"] += 1
                if len(result.metrics) != n_analyzers:
                    summary["incomplete_metric_maps"] += 1
                summary["degraded_metrics"] += sum(
                    1 for m in result.metrics.values() if m.value.is_failure
                )
            summary["stream_folds_ok"] = sum(
                1 for r in stream_results if r is not None
            )
            # no silent drops/double folds: the session folded exactly the
            # ingests that returned a result
            summary["stream_fold_parity"] = (
                session.batches_ingested == summary["stream_folds_ok"]
            )
            summary["repo_drill"] = _repository_drill(data, state_root)
            summary["partition_drill"] = _partition_drill(data, state_root)
            summary["fleetwatch_drill"] = _fleetwatch_drill(data, state_root)
            summary["mesh_drill"] = _mesh_drill(data)
            summary["ingest_drill"] = _ingest_drill(service)
            summary["coalesce_drill"] = _coalesce_drill(service)
            summary["fleet_drill"] = _fleet_drill()
            summary["catalog_drill"] = _catalog_drill()
            summary["row_gate_drill"] = _row_gate_drill(service)
            summary["tuning_drill"] = _tuning_drill(service)
            from tools.tuning_report import controller_report

            summary["tuning_report"] = controller_report(service)
            summary["statusz_drill"] = _statusz_drill(service)
            summary["faults_fired"] = len(injector.fired)
            snapshot = service.json_snapshot()["counters"]
            summary["device_failures_learned"] = snapshot.get(
                "deequ_service_device_failures_total", 0
            )
    finally:
        clear()
    if cluster_drill:
        # after clear(): the drill is whole child PROCESSES, which never
        # see this process's fault plan — only its own injected losses
        summary["cluster_drill"] = _cluster_drill()
    summary.update(_write_trace_artifact(state_root))
    summary["seconds"] = round(time.perf_counter() - t0, 2)
    invariants = {
        "unterminated": summary["unterminated"] == 0,
        "untyped_failures": summary["untyped_failures"] == 0,
        "incomplete_metric_maps": summary["incomplete_metric_maps"] == 0,
        "stream_fold_parity": bool(summary["stream_fold_parity"]),
        "jobs_accounted":
            summary["succeeded"] + summary["typed_failures"] == jobs,
        "repo_drill": summary["repo_drill"]["ok"],
        "partition_drill": summary["partition_drill"]["ok"],
        "fleetwatch_drill": summary["fleetwatch_drill"]["ok"],
        "mesh_drill": summary["mesh_drill"]["ok"],
        "ingest_drill": summary["ingest_drill"]["ok"],
        "coalesce_drill": summary["coalesce_drill"]["ok"],
        "fleet_drill": summary["fleet_drill"]["ok"],
        "catalog_drill": summary["catalog_drill"]["ok"],
        "row_gate_drill": summary["row_gate_drill"]["ok"],
        "tuning_drill": summary["tuning_drill"]["ok"],
        "statusz_drill": summary["statusz_drill"]["ok"],
    }
    if "cluster_drill" in summary:
        invariants["cluster_drill"] = summary["cluster_drill"]["ok"]
    # name what broke: a soak verdict that just says False costs a whole
    # re-run under a debugger to attribute
    summary["failed_invariants"] = sorted(
        name for name, held in invariants.items() if not held
    )
    summary["ok"] = not summary["failed_invariants"]
    if not summary["ok"]:
        print(
            "chaos soak invariants BROKEN: "
            + ", ".join(summary["failed_invariants"]),
            file=sys.stderr, flush=True,
        )
    return summary


def _statusz_drill(service) -> Dict:
    """The unified ops snapshot must stay schema-valid — and cover every
    plane — on a service that just absorbed a whole soak's worth of
    faults. Asserted through the public snapshot + validator, not
    internals: exactly what an operator's probe sees."""
    from deequ_tpu.service.statusz import REQUIRED_PLANES, validate_statusz

    try:
        doc = service.statusz.snapshot()
        problems = validate_statusz(doc)
    except Exception as exc:  # noqa: BLE001 - a crash IS the finding
        return {"ok": False, "error": repr(exc)}
    planes = sorted((doc.get("planes") or {}))
    return {
        "ok": not problems,
        "planes": planes,
        "missing_planes": sorted(set(REQUIRED_PLANES) - set(planes)),
        "problems": problems,
    }


def _mesh_drill(data) -> Dict:
    """Kill-one-shard drill, run inside the soak: a small sharded battery
    takes an injected ``mesh_loss`` on its mesh fold and must complete with
    metrics equal to the clean sharded run (salvage + re-shard, walking to
    the host tier when only one device exists), with the loss visible on
    the RunMonitor. ``inject`` swaps the soak's ambient fault plan out for
    the drill's deterministic one and restores it after."""
    import jax

    from deequ_tpu.analyzers import Completeness, Mean, Size
    from deequ_tpu.parallel import make_mesh
    from deequ_tpu.reliability import FaultSpec, inject
    from deequ_tpu.runners.analysis_runner import AnalysisRunner
    from deequ_tpu.runners.engine import RunMonitor

    n_dev = min(4, len(jax.devices()))
    analyzers = [Size(), Completeness("x"), Mean("x")]
    clean = AnalysisRunner.do_analysis_run(
        data, analyzers, batch_size=256, sharding=make_mesh(n_dev),
        placement="host",
    )
    mon = RunMonitor()
    with inject(
        FaultSpec("sharded_fold", "mesh_loss", at=1, shard=n_dev - 1)
    ) as inj:
        lossy = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=256, sharding=make_mesh(n_dev),
            placement="host", monitor=mon,
        )
    parity = all(
        abs(clean.metric(a).value.get() - lossy.metric(a).value.get())
        <= 1e-9 * max(1.0, abs(clean.metric(a).value.get()))
        for a in analyzers
    )
    return {
        "devices": n_dev,
        "faults_fired": len(inj.fired),
        "shard_losses": mon.shard_losses,
        "mesh_reshards": mon.mesh_reshards,
        "salvaged_states": mon.salvaged_states,
        "parity": parity,
        "ok": parity and mon.shard_losses >= 1 and mon.mesh_reshards >= 1,
    }


def _cluster_drill() -> Dict:
    """Multi-host kill-one drill (ISSUE 16), run as real PROCESSES: worker
    processes behind the consistent-hash front tier on one shared
    partition store, one SIGKILLed mid-stream. The verdict comes from
    tools/cluster_soak's own gate — the ring re-hashed to the survivor,
    every orphaned session was adopted from its last flushed partition
    and its journaled folds replayed to EXACT parity, and the typed
    deequ_service_cluster_* counters prove recovery ran. Skip-tolerant:
    an environment that cannot spawn the workers (sandboxed sockets, no
    free ports) reports skipped=True with ok=True — absence of evidence,
    not a broken invariant."""
    import os
    import subprocess

    cmd = [
        sys.executable, "-m", "tools.cluster_soak", "--drill", "kill-one",
        "--sessions", "4", "--batches", "4", "--rows", "1024",
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=420,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "skipped": False, "reason": "drill timed out"}
    report: Dict = {}
    lines = proc.stdout.strip().splitlines()
    if lines:
        try:
            report = json.loads(lines[-1])
        except ValueError:
            pass
    if proc.returncode == 2 or report.get("skipped"):
        return {
            "ok": True, "skipped": True,
            "reason": report.get("reason") or proc.stderr[-200:],
        }
    counters = report.get("counters", {})
    ok = (
        proc.returncode == 0
        and bool(report.get("ok"))
        and not report.get("parity_failures", ["missing report"])
        and counters.get(
            "deequ_service_cluster_sessions_recovered_total", 0) >= 1
    )
    return {
        "ok": ok,
        "skipped": False,
        "rc": proc.returncode,
        "victim": report.get("victim"),
        "recovered_hosts": report.get("recovered_hosts"),
        "host_losses": counters.get(
            "deequ_service_cluster_host_losses_total"),
        "sessions_recovered": counters.get(
            "deequ_service_cluster_sessions_recovered_total"),
        "replayed_folds": counters.get(
            "deequ_service_cluster_replayed_folds_total"),
    }


def _fleet_drill() -> Dict:
    """Fleet drill (ISSUE 12): a multi-tenant streaming soak on DISJOINT
    sub-meshes takes a SIGKILL-equivalent shard loss mid-soak (injected
    ``mesh_loss`` on the sharded fold — from the fold's side a killed
    chip and a killed process look identical: the collective dies). The
    verdict asserts the fleet RE-PACKED tenants onto the surviving
    sub-meshes with ZERO sheds and per-tenant cumulative metrics
    BIT-EXACT against clean single-chip runs (the battery's merges are
    exact integer sums, so shard-split re-association cannot round).
    Needs >= 2 devices (the conftest's virtual 8; skipped-as-ok on a
    single-chip box, like the mesh drill's host-ladder leg)."""
    import os

    import jax
    import numpy as np
    import pyarrow as pa

    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.reliability import FaultSpec, inject
    from deequ_tpu.service import VerificationService

    if len(jax.devices()) < 2:
        return {"skipped": "single device", "ok": True}

    def fleet_checks():
        return [
            Check(CheckLevel.ERROR, "fleet soak")
            .has_size(lambda n: n > 0)
            .is_complete("x")
            .has_min("x", lambda v: v >= 0)
            .has_sum("x", lambda s: s > 0),
        ]

    def table(tenant_seed: int, batch: int, rows: int = 4096):
        r = np.random.default_rng(1000 * tenant_seed + batch)
        return pa.table({"x": r.integers(0, 997, rows).astype(np.float64)})

    tenants = ("fleet-a", "fleet-b")
    batches = 4
    out: Dict = {}
    os.environ["DEEQU_TPU_FLEET_STREAM_MIN_ROWS"] = "0"
    os.environ["DEEQU_TPU_FAST_PATH_MAX_ROWS"] = "0"
    try:
        # the loss fires on the THIRD sharded fold — mid-soak, after both
        # tenants folded at least once on their original slices
        with inject(
            FaultSpec("sharded_fold", "mesh_loss", at=3, shard=1)
        ) as inj:
            with VerificationService(
                workers=4, background_warm=False, fleet=True,
            ) as svc:
                sessions = {
                    t: svc.session(t, "soak", fleet_checks())
                    for t in tenants
                }
                slices_before = {}
                for b in range(batches):
                    for i, t in enumerate(tenants):
                        sessions[t].ingest(table(i, b))
                    if b == 0:
                        # both tenants leased once: the pre-loss packing
                        slices_before = {
                            t: svc.fleet.devices_of(t) for t in tenants
                        }
                snapshot = svc.fleet.snapshot()
                cumulative = {
                    t: {
                        repr(a): m.value.get()
                        for a, m in sessions[t].current().metrics.items()
                        if m.value.is_success
                    }
                    for t in tenants
                }
                committed = {
                    t: sessions[t].batches_ingested for t in tenants
                }
                shed = svc.metrics.counter_value(
                    "deequ_service_jobs_shed_total"
                )
                mesh_folds = svc.metrics.counter_value(
                    "deequ_service_fleet_stream_folds_total"
                )
        # clean single-chip reference per tenant (fleet off entirely);
        # inject() with an EMPTY plan keeps the soak's ambient faults
        # out of the reference run — its job is to define ground truth
        with inject():
            with VerificationService(
                workers=2, background_warm=False, fleet=False,
            ) as ref_svc:
                parity = {}
                for i, t in enumerate(tenants):
                    ref = ref_svc.session(t, "soak", fleet_checks())
                    for b in range(batches):
                        ref.ingest(table(i, b))
                    parity[t] = cumulative[t] == {
                        repr(a): m.value.get()
                        for a, m in ref.current().metrics.items()
                        if m.value.is_success
                    }
    finally:
        os.environ.pop("DEEQU_TPU_FLEET_STREAM_MIN_ROWS", None)
        os.environ.pop("DEEQU_TPU_FAST_PATH_MAX_ROWS", None)
    disjoint_before = bool(slices_before.get(tenants[0])) and not (
        set(slices_before[tenants[0]]) & set(slices_before[tenants[1]])
    )
    repacked_assignment = snapshot["assignment"]
    disjoint_after = not (
        set(repacked_assignment.get(tenants[0], ()))
        & set(repacked_assignment.get(tenants[1], ()))
    )
    out.update({
        "fault_fired": bool(inj.fired),
        "slices_before": {t: list(p) for t, p in slices_before.items()},
        "assignment_after": repacked_assignment,
        "healthy_after": snapshot["healthy"],
        "repacks": snapshot["repacks"],
        "shed": shed or 0,
        "mesh_stream_folds": mesh_folds or 0,
        "committed": committed,
        "parity": parity,
    })
    out["ok"] = (
        bool(inj.fired)
        and disjoint_before and disjoint_after
        and len(snapshot["healthy"]) < len(jax.devices())  # loss stuck
        and (out["shed"] or 0) == 0
        and all(committed[t] == batches for t in tenants)
        and all(parity.values())
    )
    return out


def _catalog_drill() -> Dict:
    """Tenant-catalog corruption drill (ISSUE 17): a catalog-driven
    session takes (1) a REAL torn write as its tenant's newest document
    version and (2) an injected ``catalog_load`` corrupt fault on a
    freshly registered good version. Both must degrade to LAST-GOOD —
    the session keeps folding under its live config, each bad version is
    quarantined content-addressed with EXACTLY one counter bump (the
    move semantics: repeated fold boundaries never re-walk a quarantined
    version) — and the plane must still hot-reload a subsequent GOOD
    edit without restart. ``inject()`` swaps the soak's ambient plan out
    so an ambient hit cannot shift the pinned counts."""
    import os
    import tempfile

    import numpy as np

    from deequ_tpu.reliability import FaultSpec, inject
    from deequ_tpu.service import TenantCatalog, VerificationService
    from deequ_tpu.service.scheduler import Priority

    def doc(priority="normal"):
        return {
            "checks": [{"name": "drill", "constraints": [
                {"kind": "complete", "column": "id"},
                {"kind": "size", "min": 1},
            ]}],
            "row_gate": {"columns": [
                {"name": "id", "type": "int", "nullable": False},
            ]},
            "priority": priority,
        }

    def frame(start=0, rows=256):
        return {"id": np.arange(start, start + rows)}

    out: Dict = {}
    root = tempfile.mkdtemp(prefix="chaos-catalog-")
    with inject():
        catalog = TenantCatalog(os.path.join(root, "catalog"))
        catalog.register("drill", doc())
        with VerificationService(
            workers=2, background_warm=False, catalog=catalog,
        ) as svc:
            plane = svc.catalog_plane
            plane.poll_s = 0.0  # every boundary polls: no debounce waits
            session = plane.ensure_session("drill", "stream")
            ok0 = session.ingest(frame(0)).status.name == "SUCCESS"

            # (1) real torn write lands as the newest version
            torn = os.path.join(
                catalog.path, "t-drill", "v00000077.json"
            )
            with open(torn, "w") as fh:
                fh.write('{"torn": tru')
            for _ in range(3):  # repeated boundaries: ONE bump, not 3
                plane.on_fold_boundary(session)
            ok1 = session.ingest(frame(256)).status.name == "SUCCESS"
            torn_bumps = svc.metrics.counter_value(
                "deequ_service_catalog_quarantined_total", tenant="drill"
            )

            # (2) injected corrupt on a GOOD new version: quarantined
            # like the real thing, the previous version keeps serving
            catalog.register("drill", doc(priority="high"))
            with inject(
                FaultSpec("catalog_load", "corrupt", at=1)
            ) as inj:
                plane.on_fold_boundary(session)
            ok2 = session.ingest(frame(512)).status.name == "SUCCESS"
            injected_bumps = svc.metrics.counter_value(
                "deequ_service_catalog_quarantined_total", tenant="drill"
            ) - torn_bumps

            # (3) the NEXT good edit still hot-reloads — corruption must
            # not wedge the reload path
            catalog.register("drill", doc(priority="low"))
            plane.on_fold_boundary(session)
            ok3 = session.ingest(frame(768)).status.name == "SUCCESS"
            out.update({
                "folds_ok": [ok0, ok1, ok2, ok3],
                "torn_bumps": torn_bumps,
                "injected_fired": len(inj.fired),
                "injected_bumps": injected_bumps,
                "quarantine_files": sorted(
                    os.listdir(catalog.path + ".quarantine")
                ),
                "priority_after": session.priority.name,
            })
    out["ok"] = (
        all(out["folds_ok"])
        and out["torn_bumps"] == 1
        and out["injected_fired"] == 1 and out["injected_bumps"] == 1
        and len(out["quarantine_files"]) == 2
        and out["priority_after"] == Priority.LOW.name
    )
    return out


def _row_gate_drill(service) -> Dict:
    """Row-gate drill, run inside the soak against the live service: a
    gated session takes (1) an injected ``row_gate`` corrupt fault — a
    frame whose conformance mask cannot be computed — which must surface
    TYPED with NOTHING folded, and the session must keep folding after;
    (2) a real partial-garbage frame whose clean rows fold while the
    rejects land decodable in the typed quarantine sidecar; (3) an
    all-garbage frame which must raise typed ``FrameQuarantinedError``
    with nothing folded. ``inject`` swaps the soak's ambient plan out so
    an ambient hit cannot shift the pinned fold counts."""
    import tempfile

    import numpy as np

    from deequ_tpu.exceptions import MetricCalculationRuntimeException
    from deequ_tpu.ingest import (
        FrameQuarantinedError,
        QuarantineSidecar,
        RowGate,
    )
    from deequ_tpu.reliability import FaultSpec, inject
    from deequ_tpu.schema import RowLevelSchema

    from deequ_tpu.checks import Check, CheckLevel

    checks = [Check(CheckLevel.ERROR, "row-gate drill")
              .has_size(lambda n: n > 0).is_complete("id")]
    schema = RowLevelSchema().with_int_column("id", is_nullable=False)
    sidecar = QuarantineSidecar(
        tempfile.mkdtemp(prefix="chaos-rowgate-")
    )
    gate = RowGate(schema, sidecar=sidecar, metrics=service.metrics)
    out: Dict = {}
    with inject():
        session = service.session(
            "rowgate-drill", "stream", checks, row_gate=gate,
        )
        # (1) injected corrupt: typed, nothing folds, session survives
        with inject(FaultSpec("row_gate", "corrupt", at=1)) as inj:
            try:
                session.ingest({"id": np.arange(64)})
                out["injected_typed"] = False
            except MetricCalculationRuntimeException:
                out["injected_typed"] = True
        out["injected_fired"] = len(inj.fired)
        out["committed_after_fault"] = session.batches_ingested

        # (2) partial garbage: nulls in a non-nullable column reject;
        # the clean rows fold and the rejects decode back exactly
        mixed = {"id": np.array([1.0, np.nan, 3.0, np.nan, 5.0])}
        r = session.ingest(mixed)
        out["partial_status"] = r.status.name
        out["committed_after_partial"] = session.batches_ingested
        quarantined = sidecar.read_all("rowgate-drill", "stream")
        out["quarantined_rows"] = (
            int(quarantined.num_rows) if quarantined is not None else 0
        )

        # (3) full garbage: typed FrameQuarantinedError, nothing folds
        try:
            session.ingest({"id": np.array([np.nan, np.nan])})
            out["full_reject_typed"] = False
        except FrameQuarantinedError:
            out["full_reject_typed"] = True
        out["committed_final"] = session.batches_ingested
        out["rejected_counter"] = service.metrics.counter_value(
            "deequ_service_rowgate_rejected_rows_total",
            tenant="rowgate-drill", dataset="stream",
        )
    out["ok"] = (
        out["injected_typed"] and out["injected_fired"] == 1
        and out["committed_after_fault"] == 0
        and out["partial_status"] == "SUCCESS"
        and out["committed_after_partial"] == 1
        and out["quarantined_rows"] == 2
        and out["full_reject_typed"]
        and out["committed_final"] == 1
        and out["rejected_counter"] == 4
    )
    return out


def _coalesce_drill(service) -> Dict:
    """Cross-session fold coalescing drill, run inside the soak against
    the live service: four sessions' micro-batch folds are forced onto
    the coalesced DEVICE path (``DEEQU_TPU_FAST_PATH_MAX_ROWS=0``) and an
    injected ``coalesced_fold`` poison matching ONE session's tag fires
    on every launch attempt — group bisection must quarantine exactly
    that session (typed JobFailed, zero batches committed) while the
    three siblings commit their folds. ``inject`` swaps the soak's
    ambient plan out so an ambient hit cannot shift the pinned counts."""
    import os

    import numpy as np
    import pyarrow as pa

    from deequ_tpu.reliability import FaultSpec, inject
    from deequ_tpu.service.errors import JobFailed

    checks = _checks()
    out: Dict = {}
    os.environ["DEEQU_TPU_FAST_PATH_MAX_ROWS"] = "0"
    try:
        with inject(FaultSpec(
            "coalesced_fold", "poison", every=1, count=None,
            match="coalesce-drill-2/stream",
        )):
            sessions = [
                service.session(f"coalesce-drill-{i}", "stream", checks)
                for i in range(4)
            ]
            handles = []
            for i, s in enumerate(sessions):
                r = np.random.default_rng(40 + i)
                table = pa.table({
                    "x": r.normal(size=512),
                    "y": r.normal(10.0, 2.0, size=512),
                    "cat": pa.array([f"c{j % 13}" for j in range(512)]),
                })
                handles.append(s.ingest(table, wait=False))
            outcomes = []
            for h in handles:
                try:
                    h.result(120)
                    outcomes.append("ok")
                except JobFailed:
                    outcomes.append("quarantined")
                except Exception:  # noqa: BLE001 - verdict below
                    outcomes.append("untyped")
    finally:
        os.environ.pop("DEEQU_TPU_FAST_PATH_MAX_ROWS", None)
    out["outcomes"] = outcomes
    out["committed"] = [s.batches_ingested for s in sessions]
    out["quarantined_counter"] = service.metrics.counter_value(
        "deequ_service_coalesce_quarantined_total"
    )
    out["ok"] = (
        outcomes == ["ok", "ok", "quarantined", "ok"]
        and out["committed"] == [1, 1, 0, 1]
    )
    return out


def _tuning_drill(service) -> Dict:
    """Self-tuning guardrail drill, run inside the soak against the live
    service: a PLANTED mis-calibration (``fast_path_max_rows=0`` tuned in
    — "the crossover says the device always wins" — forcing every small
    fold onto the fixed-cost device path) must be demoted back to static
    defaults by the controller's never-below-static floor, and the
    post-demotion ingest rate must not sit below the static-default
    reference burst. The drill measures three bursts — static reference,
    poisoned, recovered — through one streaming session; the controller
    sees every fold via the coalescer's timing sites. The battery is
    fast-path-capable and the folds sit below the fleet-sharding
    threshold, so routing (not sharding) is the only variable.
    ``inject()`` swaps the soak's ambient fault plan out — an injected
    fold crash would fail the bursts and corrupt the timing evidence."""
    import os
    import time as _time

    import numpy as np
    import pyarrow as pa

    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.reliability import inject
    from deequ_tpu.tuning import knobs

    controller = getattr(service, "tuning_controller", None)
    out: Dict = {}
    if controller is None:
        # DEEQU_TPU_AUTOTUNE=0 soaks have no controller to drill; that is
        # the escape hatch working, not a failure
        out["skipped"] = "autotune disabled"
        out["ok"] = True
        return out

    checks = [
        Check(CheckLevel.ERROR, "tuning drill")
        .is_complete("x")
        .has_mean("y", lambda m: 5.0 < m < 15.0)
    ]
    session = service.session("tuning-drill", "stream", checks)
    rng = np.random.default_rng(77)
    table = pa.table({
        "x": rng.normal(size=8192),
        "y": rng.normal(10.0, 2.0, size=8192),
    })

    def burst(n: int) -> float:
        t0 = _time.perf_counter()
        for _ in range(n):
            session.ingest(table, timeout=120)
        return n / (_time.perf_counter() - t0)

    # verdicts must land within the drill's bursts, not after hours of
    # soak traffic; restore the operator's env afterwards
    saved = os.environ.get("DEEQU_TPU_TUNING_MIN_SAMPLES")
    os.environ["DEEQU_TPU_TUNING_MIN_SAMPLES"] = "8"
    try:
        with inject():
            knobs.clear_tuned()  # the floor must be measured at true static
            session.ingest(table, timeout=120)  # warm the static route
            out["static_sessions_per_s"] = burst(24)
            demotions_before = service.metrics.counter_value(
                "deequ_service_tuning_demotions_total"
            )
            knobs.set_tuned("fast_path_max_rows", 0, source="drill")
            out["poisoned_sessions_per_s"] = burst(24)
            out["recovered_sessions_per_s"] = burst(24)
            out["demoted"] = not knobs.tuned_snapshot()
            out["floor_demotions"] = service.metrics.counter_value(
                "deequ_service_tuning_demotions_total"
            ) - demotions_before
            out["decisions"] = [
                d["verdict"] for d in controller.snapshot()["decisions"]
            ]
    finally:
        knobs.clear_tuned()
        if saved is None:
            os.environ.pop("DEEQU_TPU_TUNING_MIN_SAMPLES", None)
        else:
            os.environ["DEEQU_TPU_TUNING_MIN_SAMPLES"] = saved
    out["ok"] = (
        out.get("demoted", False)
        and out.get("floor_demotions", 0) >= 1
        # generous band: the recovered burst runs the same static config
        # as the reference, so halving it would mean the guardrail failed
        # to actually restore the static path
        and out.get("recovered_sessions_per_s", 0.0)
        >= 0.5 * out.get("static_sessions_per_s", float("inf"))
    )
    return out


def _ingest_drill(service) -> Dict:
    """Arrow ingestion-plane drill, run inside the soak against the live
    service: a truncated frame and a checksum-corrupted payload must both
    recover TYPED (FeedDisconnectError / MalformedFrameError) with the
    torn/corrupt frames never touching session state — complete leading
    frames stay committed, nothing else folds. An injected
    ``frame_corrupt`` at the ``frame_decode`` site exercises the same
    rejection without hand-crafting bytes. ``inject`` swaps the soak's
    ambient fault plan out for the drill's deterministic one."""
    import io

    import numpy as np
    import pyarrow as pa

    from deequ_tpu.exceptions import FeedDisconnectError, MalformedFrameError
    from deequ_tpu.ingest import fold_stream
    from deequ_tpu.integrity import checksum_bytes
    from deequ_tpu.reliability import FaultSpec, inject

    checks = _checks()

    def frame_table(seed: int, rows: int = 512):
        r = np.random.default_rng(seed)
        return pa.table({
            "x": r.normal(size=rows),
            "y": r.normal(10.0, 2.0, size=rows),
            "cat": pa.array([f"c{i % 13}" for i in range(rows)]),
        })

    # encode incrementally so the drill knows each frame's byte boundary
    tables = [frame_table(s) for s in (1, 2, 3)]
    sink = io.BytesIO()
    boundaries = []
    with pa.ipc.new_stream(sink, tables[0].schema) as writer:
        for t in tables:
            for b in t.to_batches():
                writer.write_batch(b)
            boundaries.append(sink.tell())
    payload = sink.getvalue()

    out: Dict = {}
    with inject():  # the drill's outcomes are deterministic: swap the
        # soak's ambient seeded plan out (restored on exit) so an ambient
        # worker_death/drift hit cannot shift the pinned counts below
        # 1. clean fold: all three frames commit
        clean = service.session("ingest-drill", "clean", checks)
        report = fold_stream(clean, payload, source="drill")
        out["clean_frames"] = report.frames
        baseline = clean.batches_ingested

        # 2. mid-stream disconnect: cut inside frame 3 — frames 1-2
        # commit, the torn tail recovers typed and never folds
        cut = boundaries[1] + (boundaries[2] - boundaries[1]) // 2
        torn = service.session("ingest-drill", "torn", checks)
        try:
            fold_stream(torn, payload[:cut], complete=False, source="drill")
            out["disconnect_typed"] = False
        except FeedDisconnectError:
            out["disconnect_typed"] = True
        except MalformedFrameError:
            out["disconnect_typed"] = False
        out["torn_committed"] = torn.batches_ingested

        # 3. checksum corruption: one flipped byte inside a buffer body
        # decodes silently in Arrow IPC — the declared digest is the
        # tripwire; NOTHING folds
        bad = bytearray(payload)
        bad[boundaries[0] + 32] ^= 0xFF
        corrupt = service.session("ingest-drill", "corrupt", checks)
        try:
            fold_stream(
                corrupt, bytes(bad), checksum=checksum_bytes(payload),
                source="drill",
            )
            out["corrupt_typed"] = False
        except MalformedFrameError:
            out["corrupt_typed"] = True
        out["corrupt_committed"] = corrupt.batches_ingested

    # 4. injected frame_corrupt at frame_decode: second frame rejected
    # typed, first frame's fold stays committed
    injected = service.session("ingest-drill", "injected", checks)
    with inject(FaultSpec("frame_decode", "frame_corrupt", at=2)) as inj:
        try:
            fold_stream(injected, payload, source="drill")
            out["injected_typed"] = False
        except MalformedFrameError:
            out["injected_typed"] = True
    out["injected_committed"] = injected.batches_ingested
    out["injected_fired"] = len(inj.fired)

    out["ok"] = (
        out["clean_frames"] == 3 and baseline == 3
        and out["disconnect_typed"] and out["torn_committed"] == 2
        and out["corrupt_typed"] and out["corrupt_committed"] == 0
        and out["injected_typed"] and out["injected_committed"] == 1
    )
    return out


def _partition_drill(data, tmpdir: str) -> Dict:
    """Incremental-verification corruption drill (ISSUE 13 acceptance): a
    partitioned table's stored states take (1) a flipped byte inside one
    partition's state blob and (2) a schema change flipping the contract
    fingerprint. Both must degrade TYPED — the corrupt partition
    quarantines and re-scans ALONE (siblings reuse, metrics equal to the
    clean merge), and the stale fingerprint invalidates without
    crashing. ``inject()`` swaps the soak's ambient plan out so an
    ambient hit cannot shift the pinned plan decisions."""
    import glob
    import os

    import pyarrow as pa

    from deequ_tpu.data import Dataset
    from deequ_tpu.reliability import inject
    from deequ_tpu.repository.partition_store import (
        PartitionStateStore,
        partition_quarantined_total,
    )
    from deequ_tpu.runners.engine import RunMonitor
    from deequ_tpu.runners.incremental import run_incremental

    from deequ_tpu.analyzers import Completeness, Mean, Size, Sum

    out: Dict = {}
    with inject():
        store = PartitionStateStore(os.path.join(tmpdir, "partition-store"))
        analyzers = [Size(), Completeness("x"), Mean("x"), Sum("y")]
        rows = int(data.num_rows)
        third = rows // 3
        parts = {
            f"p{i}": Dataset.from_arrow(data.arrow.slice(i * third, third))
            for i in range(3)
        }
        clean_ctx, first = run_incremental(
            store, "drill", parts, analyzers, batch_size=third,
        )
        out["first_scan"] = list(first.plan.scan)

        # (1) corrupt one partition's Mean blob. The rollup cache is
        # dropped first so the merge actually reads the partition blobs —
        # with the cache intact the corruption would simply be masked
        # (tests/test_incremental.py pins that separately)
        store.rollup_invalidate("drill")
        [blob] = glob.glob(os.path.join(
            store.path, "ds-drill", "*", "p-p1", "Mean-*-state.npz"
        ))
        raw = bytearray(open(blob, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(blob, "wb").write(bytes(raw))
        before = partition_quarantined_total()
        mon = RunMonitor()
        ctx, rep = run_incremental(
            store, "drill", parts, analyzers, batch_size=third,
            monitor=mon,
        )
        out["corrupt_reason"] = rep.plan.reasons.get("p1")
        out["corrupt_rescans"] = list(rep.plan.scan)
        out["corrupt_reused"] = sorted(rep.plan.reuse)
        out["quarantined"] = partition_quarantined_total() - before
        parity = all(
            ctx.metric(a).value.get() == clean_ctx.metric(a).value.get()
            for a in analyzers
        )
        out["parity"] = parity

        # (2) stale fingerprint: same names, changed schema -> every
        # partition invalidates typed (no crash, no stale merge)
        import numpy as np

        renamed = {
            name: Dataset.from_arrow(
                d.arrow.rename_columns(
                    ["x2" if c == "x" else c for c in d.arrow.column_names]
                )
            )
            for name, d in parts.items()
        }
        ctx2, rep2 = run_incremental(
            store, "drill", renamed,
            [Size(), Completeness("x2")], batch_size=third,
        )
        out["stale_reasons"] = sorted(set(rep2.plan.reasons.values()))
        out["ok"] = (
            out["corrupt_reason"] == "corrupt-state"
            and out["corrupt_rescans"] == ["p1"]
            and out["corrupt_reused"] == ["p0", "p2"]
            and out["quarantined"] >= 1
            and parity
            and out["stale_reasons"] == ["stale-fingerprint"]
        )
    return out


def _fleetwatch_drill(data, tmpdir: str) -> Dict:
    """Fleet-watch poisoned-history drill (ISSUE 15): two tenants'
    partitioned metric histories under a standing watch; after a clean
    batched harvest, ONE tenant's stored history takes a flipped byte
    mid-soak. The verdict asserts the poisoned tenant quarantines TYPED
    (report + export counter), the OTHER tenant's flags are identical to
    the clean harvest, and the flagged anomaly's trace-correlated flight
    dump exists and parses. ``inject()`` swaps the soak's ambient plan out
    so an ambient hit cannot shift the pinned counts."""
    import glob
    import json as _json
    import os
    import time

    from deequ_tpu.analyzers import Mean, Size
    from deequ_tpu.metrics import DoubleMetric, Entity, Success
    from deequ_tpu.reliability import inject
    from deequ_tpu.repository import PartitionedMetricsRepository, ResultKey
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.context import AnalyzerContext
    from deequ_tpu.service import VerificationService

    out: Dict = {}
    flight_dir = os.path.join(tmpdir, "fleetwatch-flight")
    prior_flight = os.environ.get("DEEQU_TPU_FLIGHT_DIR")
    os.environ["DEEQU_TPU_FLIGHT_DIR"] = flight_dir
    try:
        with inject():
            steady = AnalysisRunner.do_analysis_run(
                data, [Size(), Mean("x")]
            )
            wild = AnalyzerContext({
                Size(): steady.metric(Size()),
                Mean("x"): DoubleMetric(
                    Entity.COLUMN, "Mean", "x", Success(9999.0)
                ),
            })
            now = int(time.time() * 1000)
            day = 86_400_000
            repos = {}
            for tenant in ("drill-flagging", "drill-poisoned"):
                repo = PartitionedMetricsRepository(
                    os.path.join(tmpdir, f"fw-{tenant}")
                )
                for d in range(20):
                    repo.save(ResultKey(now - (20 - d) * day), steady)
                repo.save(
                    ResultKey(now),
                    wild if tenant == "drill-flagging" else steady,
                )
                repos[tenant] = repo
            with VerificationService(
                workers=2, background_warm=False, fleet=False,
            ) as svc:
                for tenant, repo in repos.items():
                    svc.watch_metrics(tenant, repo, [Size(), Mean("x")])
                clean = svc.fleetwatch.harvest_now()
                # poison one stored entry of the poisoned tenant: valid
                # JSON, failing checksum — the bit-rot shape
                poisoned = repos["drill-poisoned"]
                entry = sorted(glob.glob(
                    os.path.join(poisoned.path, "*", "e-*.json")
                ))[-1]
                raw = open(entry).read()
                i = raw.index("Mean") + 1
                open(entry, "w").write(
                    raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:]
                )
                after = svc.fleetwatch.harvest_now()
                quarantine_counter = svc.metrics.counter_value(
                    "deequ_service_anomaly_quarantined_total",
                    tenant="drill-poisoned",
                )
        clean_flags = [f for f in clean.flagged if f[0] == "drill-flagging"]
        after_flags = [f for f in after.flagged if f[0] == "drill-flagging"]
        dump_ok = False
        for path in glob.glob(os.path.join(flight_dir, "*.jsonl")):
            records = [_json.loads(line) for line in open(path)]
            header = records[0]
            if any(
                f.get("kind") == "AnomalyFlagged"
                for f in header.get("failures", [])
            ) and header.get("trace_id"):
                dump_ok = True
        out.update({
            "clean_quarantined": list(clean.quarantined_tenants),
            "after_quarantined": list(after.quarantined_tenants),
            "clean_flagged": len(clean_flags),
            "after_flagged": len(after_flags),
            "quarantine_counter": quarantine_counter,
            "flight_dump_parses": dump_ok,
        })
        out["ok"] = (
            clean.quarantined_tenants == []
            and after.quarantined_tenants == ["drill-poisoned"]
            and quarantine_counter == 1
            and clean_flags and after_flags == clean_flags
            and dump_ok
        )
    finally:
        if prior_flight is None:
            os.environ.pop("DEEQU_TPU_FLIGHT_DIR", None)
        else:
            os.environ["DEEQU_TPU_FLIGHT_DIR"] = prior_flight
    return out


def _write_trace_artifact(tmpdir: str) -> Dict:
    """Leave a summarized trace artifact behind after every soak: the
    flight-recorder ring exports as a Chrome trace, `tools.trace_summarize`
    renders the critical path / self-time / degradation summary beside it,
    and both paths land in the soak's JSON so an operator can open the
    incident directly from the drill output."""
    import os

    from deequ_tpu.observability import export as obs_export
    from deequ_tpu.observability import trace as obs_trace

    if not obs_trace.enabled():
        return {"trace_artifact": None}
    try:
        artifact = obs_export.write_chrome_trace(
            os.path.join(tmpdir, "chaos-trace.json")
        )
        from tools.trace_summarize import summarize

        text = summarize(artifact)
        summary_path = artifact + ".summary.txt"
        with open(summary_path, "w") as fh:
            fh.write(text + "\n")
        print(text, file=sys.stderr, flush=True)
        degradation_lines = sum(
            1 for line in text.splitlines() if line.startswith("  +")
        )
        return {
            "trace_artifact": artifact,
            "trace_summary": summary_path,
            "trace_degradations": degradation_lines,
        }
    except Exception:  # noqa: BLE001 - the soak verdict must not depend on
        # the post-mortem artifact writing cleanly
        import traceback

        traceback.print_exc()
        return {"trace_artifact": None}


def _repository_drill(data, tmpdir: str) -> Dict:
    """Corruption drill on the FS metrics repository, run INSIDE the armed
    fault plan: save two history entries, flip one byte inside one entry,
    then read the history three times. A flipped entry is quarantined to
    the ``.quarantine/`` sidecar while the other keeps serving; an
    injected ``repository_load`` whole-file corruption (default plan,
    at=2) quarantines the payload and serves an empty history for that
    read only — the source file stays in place, so the NEXT read recovers
    the surviving entry. No read ever crashes."""
    import os

    from deequ_tpu.analyzers import Completeness, Mean
    from deequ_tpu.repository import ResultKey
    from deequ_tpu.repository.fs import (
        FileSystemMetricsRepository,
        quarantined_total,
    )
    from deequ_tpu.runners.analysis_runner import AnalysisRunner

    path = os.path.join(tmpdir, "soak-repo.json")
    repo = FileSystemMetricsRepository(path)
    ctx = AnalysisRunner.do_analysis_run(data, [Mean("x"), Completeness("x")])
    before = quarantined_total()
    repo.save(ResultKey(1), ctx)
    repo.save(ResultKey(2), ctx)
    raw = open(path).read()
    i = raw.index("Mean") + 1
    open(path, "w").write(
        raw[:i] + ("X" if raw[i] != "X" else "Y") + raw[i + 1:]
    )
    survivors = [len(repo._read_all()) for _ in range(3)]
    quarantined = quarantined_total() - before
    return {
        "survivors_per_read": survivors,
        "quarantined": quarantined,
        # the final read must serve the surviving entry (corruption is
        # quarantined, never amplified), and at least one quarantine
        # must have been recorded for the flipped entry
        "ok": survivors[-1] == 1 and quarantined >= 1,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--jobs", type=int, default=30)
    parser.add_argument("--stream-batches", type=int, default=8)
    parser.add_argument("--rows", type=int, default=4096)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument(
        "--no-cluster-drill", action="store_true",
        help="skip the multi-process kill-one cluster drill",
    )
    args = parser.parse_args(argv)
    summary = run_soak(
        jobs=args.jobs, stream_batches=args.stream_batches, rows=args.rows,
        seed=args.seed, workers=args.workers,
        cluster_drill=not args.no_cluster_drill,
    )
    print(json.dumps(summary), flush=True)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
