"""Invariant linter: repo-specific static analysis for the contracts the
service plane is built on. ``python -m tools.statlint`` gates tier-1 via
``tests/test_statlint.py``; see ``core.py`` for the architecture and
``checks/`` for one module per machine-checked contract."""

from .core import (  # noqa: F401
    Finding,
    Module,
    ModuleIndex,
    apply_baseline,
    load_baseline,
    run_checks,
    write_baseline,
)
