"""Seeded violation for the trace-purity check: a jit-registered function
reads the wall clock, which would bake ONE trace-time timestamp into the
compiled program forever."""

import time

import jax


def impure_update(state, xs):
    stamp = time.time()  # trace-time read, baked into the program
    return state + xs.sum() + stamp


def chained_helper(state):
    return state.item()  # host materialization inside a trace


def traced_entry(state, xs):
    return chained_helper(impure_update(state, xs))


program = jax.jit(traced_entry)
