"""Seeded-violation fixtures: each module plants exactly the contract
violation its namesake check exists to catch. They are PARSED by the
linter (never imported/executed) and pinned by tests/test_statlint.py:
``python -m tools.statlint <fixture>`` must exit non-zero, one per check —
a check that cannot catch its own seeded violation is not a check."""
