"""Seeded violations for the failure-registry check: a typed exception
defined outside the registry modules (and not re-exported via
``exceptions._SUBSYSTEM_EXCEPTIONS``), plus a ``fault_point`` probe whose
site name is not registered in ``reliability/faults.KNOWN_FAULT_SITES``."""

from deequ_tpu.reliability.faults import fault_point


class RogueSubsystemError(RuntimeError):
    """A typed failure nobody can import from the taxonomy."""


def poke() -> None:
    fault_point("fixture_unregistered_site")
    raise RogueSubsystemError("boom")
