"""Seeded violation for the dead-import check: an import nothing uses."""

import json
import os


def where() -> str:
    return os.getcwd()
