"""Seeded violations for the lock-discipline check.

1. ``CommitLedger`` reproduces the PR 13 cross-key commit-inversion BUG
   SHAPE: a shared field written both under its owning lock and on a path
   that provably does not hold it (the class of race behind the three
   PR 13 flake fixes).
2. ``AccountA``/``AccountB`` acquire each other's locks in opposite
   orders — the textbook acquisition-order deadlock cycle.
"""

import threading


class CommitLedger:
    def __init__(self):
        self._lock = threading.Lock()
        self._committed = 0

    def commit(self, n):
        with self._lock:
            self._committed += n

    def commit_unlocked(self, n):
        # the PR 13 shape: same shared field, no owning lock held
        self._committed += n


class AccountA:
    def __init__(self, peer):
        self._lock = threading.Lock()
        self.peer = peer

    def transfer_from_a(self):
        with self._lock:
            self.peer.credit_b()

    def credit_a(self):
        with self._lock:
            pass


class AccountB:
    def __init__(self, peer):
        self._lock = threading.Lock()
        self.peer = peer

    def credit_b(self):
        with self._lock:
            self.peer.credit_a()
