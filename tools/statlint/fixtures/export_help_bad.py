"""Seeded violation for the export-plane completeness check: a
``deequ_service_*`` series incremented without a HELP description
registered anywhere."""


def bump(metrics) -> None:
    metrics.inc("deequ_service_fixture_undescribed_total", tenant="t")
