"""Seeded violation for the tuning-registry check: a hand-coded routing
threshold — a module-level numeric cutoff compared against at a decision
site — instead of a registered knob in deequ_tpu/tuning/knobs.py (so
boot-time calibration and the online controller could never move it)."""

FIXTURE_ROUTE_MIN_ROWS = 1 << 20


def fixture_route(rows: int) -> str:
    if rows <= FIXTURE_ROUTE_MIN_ROWS:
        return "host"
    return "device"
