"""Seeded violations for the state-algebra check: a *State class without a
merge() (not a semigroup), and an identity-merge-transparency registry
naming a class that does not exist."""


class OrphanState:
    @staticmethod
    def init() -> "OrphanState":
        return OrphanState()


IDENTITY_TRANSPARENT_STATES = frozenset({GhostState})  # noqa: F821
