"""Seeded violation for the env-knob-convention check: a DEEQU_TPU_* knob
read straight off os.environ instead of through utils.env_number /
env_str / env_flag (so a typo'd value would crash or silently diverge
instead of warning once and keeping the default)."""

import os

FIXTURE_KNOB_ENV = "DEEQU_TPU_FIXTURE_KNOB"


def fixture_knob() -> int:
    return int(os.environ.get(FIXTURE_KNOB_ENV, "4"))
