"""Seeded violation for the span-kind registry check: a span opened with
a kind invented at the call site — it renders, then silently falls out
of every kind-keyed view. The ``np.argsort(kind="stable")`` call is the
false-positive control: a ``kind=`` keyword on someone else's API must
NOT fire."""

from deequ_tpu.observability import trace as _trace


def do_work(values) -> None:
    import numpy as np

    order = np.argsort(values, kind="stable")  # not ours: must not fire
    with _trace.span("fixture_work", kind="freestyle_kind", n=len(order)):
        pass
