"""Core of the invariant linter: parse cache, findings, baseline.

The checks under ``tools/statlint/checks`` machine-check the contracts the
service plane is built on (trace purity, lock discipline, the env-knob
convention, the typed-failure and fault-site registries, export-plane
HELP/TYPE completeness, state-merge algebra, dead imports) — the repo's own
"unit tests for data" idea (Schelter et al., VLDB 2018) turned on the repo
itself: declarative invariants enforced by machine instead of by reviewer
memory.

Design:

- **ModuleIndex** walks the target tree ONCE and parses every module ONCE
  (the module-parse cache); each check receives the same index, so the
  whole seven-check suite is one parse pass plus seven AST walks — well
  under the 30s tier-1 budget.
- **Finding.fingerprint()** is line-number-free (check id, repo-relative
  path, a symbol-level key), so baselined findings survive unrelated edits
  to the same file.
- **Baseline**: ``baseline.json`` holds grandfathered findings, each with
  a mandatory human reason — no silent suppressions. The gate is zero
  NON-baselined findings; stale baseline entries (whose finding no longer
  fires) are themselves reported, so the file can only shrink honestly.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

#: what ``python -m tools.statlint`` scans when given no paths
DEFAULT_TARGETS = ("deequ_tpu",)


@dataclass(frozen=True)
class Finding:
    check: str     #: check id (e.g. "lock-unguarded-write")
    path: str      #: repo-relative module path
    line: int      #: 1-based line (display only; not part of the identity)
    message: str   #: one-line human statement of the violation
    key: str       #: line-free symbol-level identity within (check, path)

    def fingerprint(self) -> str:
        return f"{self.check}:{self.path}:{self.key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


class Module:
    """One parsed module plus the derived tables the checks share."""

    def __init__(self, path: str, relpath: str, source: str, tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self._constants: Optional[Dict[str, str]] = None

    @property
    def constants(self) -> Dict[str, str]:
        """Module-level ``NAME = "literal"`` string constants (how env-var
        names are spelled at their read sites)."""
        if self._constants is None:
            out: Dict[str, str] = {}
            for node in self.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    out[node.targets[0].id] = node.value.value
            self._constants = out
        return self._constants

    def line_has_noqa(self, node: ast.AST) -> bool:
        lines = self.source.splitlines()
        start = getattr(node, "lineno", 1) - 1
        end = getattr(node, "end_lineno", start + 1)
        return any("noqa" in line for line in lines[start:end])


class ModuleIndex:
    """The shared parse cache: every check reads from here, nothing parses
    twice. ``narrow`` is True when scanning the default package tree (some
    checks then restrict their sweep scope, e.g. dead-imports to
    ``service/`` + ``parallel/``); explicit file arguments — the fixture
    mode — scan everything they are given."""

    def __init__(self, paths: Sequence[str], narrow: Optional[bool] = None):
        self.modules: List[Module] = []
        self.errors: List[Finding] = []
        explicit_files = all(p.endswith(".py") for p in paths) if paths else False
        self.narrow = (not explicit_files) if narrow is None else narrow
        seen = set()
        for path in paths:
            for file_path in self._walk(path):
                if file_path in seen:
                    continue
                seen.add(file_path)
                self._load(file_path)
        self.modules.sort(key=lambda m: m.relpath)

    @staticmethod
    def _walk(path: str):
        path = os.path.abspath(path)
        if os.path.isfile(path):
            yield path
            return
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)

    def _load(self, file_path: str) -> None:
        relpath = os.path.relpath(file_path, REPO_ROOT)
        if relpath.startswith(".."):
            relpath = file_path
        try:
            with open(file_path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=file_path)
        except (OSError, SyntaxError) as exc:
            self.errors.append(
                Finding(
                    check="parse-error", path=relpath,
                    line=getattr(exc, "lineno", 0) or 0,
                    message=f"module failed to parse: {exc}",
                    key=type(exc).__name__,
                )
            )
            return
        self.modules.append(Module(file_path, relpath, source, tree))

    def get(self, relpath_suffix: str) -> Optional[Module]:
        """The unique module whose repo-relative path ends with the given
        suffix (e.g. ``"deequ_tpu/config.py"``), or None."""
        matches = [
            m for m in self.modules
            if m.relpath.endswith(relpath_suffix)
        ]
        return matches[0] if len(matches) == 1 else None

    def side_load(self, repo_relpath: str) -> Optional[Module]:
        """Parse one module from the REPO tree without adding it to the
        scanned set — how fixture scans resolve registries (fault sites)
        that live outside the fixture file."""
        path = os.path.join(REPO_ROOT, repo_relpath)
        if not os.path.isfile(path):
            return None
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            return Module(path, repo_relpath, source, ast.parse(source))
        except (OSError, SyntaxError):
            return None


# -- shared AST helpers ------------------------------------------------------

def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None when the chain bottoms out in a
    non-Name (a call result, a subscript)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def resolve_str(node: ast.AST, module: Module) -> Optional[str]:
    """A literal string, or a module-level constant holding one."""
    value = literal_str(node)
    if value is not None:
        return value
    if isinstance(node, ast.Name):
        return module.constants.get(node.id)
    return None


def iter_env_reads(module: Module):
    """Yield ``(node, env_name_or_None, style)`` for every environment
    read: style "direct" (``os.environ.get``/``os.getenv``/subscript —
    including the bound-name ``from os import environ``/``getenv`` idioms)
    or "helper" (``utils.env_number``/``env_str``/``env_flag``)."""
    helpers = {"env_number", "env_str", "env_flag"}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain is None:
                continue
            if (
                chain in (["os", "environ", "get"], ["environ", "get"],
                          ["os", "getenv"], ["getenv"])
            ):
                if node.args:
                    yield node, resolve_str(node.args[0], module), "direct"
            elif chain[-1] in helpers:
                arg = node.args[0] if node.args else None
                if arg is not None:
                    yield node, resolve_str(arg, module), "helper"
        elif isinstance(node, ast.Subscript) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            chain = attr_chain(node.value)
            if chain in (["os", "environ"], ["environ"]):
                yield node, resolve_str(node.slice, module), "direct"


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint -> reason. Entries without a reason are rejected: a
    suppression nobody can explain is a silent suppression."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    out: Dict[str, str] = {}
    for entry in payload.get("entries", ()):
        fingerprint = entry["fingerprint"]
        reason = entry.get("reason", "").strip()
        if not reason:
            raise ValueError(
                f"baseline entry {fingerprint!r} has no reason; every "
                "grandfathered finding must say why it is deliberate"
            )
        out[fingerprint] = reason
    return out


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [
        {"fingerprint": f.fingerprint(), "reason": "TODO: explain why this is deliberate"}
        for f in sorted(findings, key=lambda f: f.fingerprint())
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"entries": entries}, fh, indent=2, sort_keys=False)
        fh.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, str], baseline_path: str
) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (new, stale-baseline-entries-as-findings)."""
    fired = {f.fingerprint() for f in findings}
    new = [f for f in findings if f.fingerprint() not in baseline]
    relpath = baseline_path
    if baseline_path:
        relpath = os.path.relpath(baseline_path, REPO_ROOT)
        if relpath.startswith(".."):
            relpath = baseline_path
    stale = [
        Finding(
            check="baseline-stale", path=relpath, line=0,
            message=(
                f"baseline entry {fp!r} no longer fires "
                f"(reason was: {reason}); delete it"
            ),
            key=fp,
        )
        for fp, reason in sorted(baseline.items())
        if fp not in fired
    ]
    return new, stale


def known_check_ids() -> List[str]:
    from .checks import ALL_CHECKS

    return [check.CHECK for check in ALL_CHECKS]


def run_checks(index: ModuleIndex, only: Optional[Sequence[str]] = None) -> List[Finding]:
    from .checks import ALL_CHECKS

    if only:
        unknown = sorted(set(only) - set(known_check_ids()))
        if unknown:
            # an unvalidated scope would silently run ZERO checks and
            # exit green — the one failure mode a gate must not have
            raise ValueError(
                f"unknown check id(s) {unknown}; known: {known_check_ids()}"
            )
    findings: List[Finding] = list(index.errors)
    for check in ALL_CHECKS:
        if only and check.CHECK not in only:
            continue
        findings.extend(check.run(index))
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.key))
    return findings
