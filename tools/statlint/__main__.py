"""CLI: ``python -m tools.statlint [paths...]``.

Exit codes: 0 = zero non-baselined findings; 1 = findings (or stale
baseline entries); 2 = usage/baseline errors.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional, Sequence

from .core import (
    DEFAULT_BASELINE,
    DEFAULT_TARGETS,
    REPO_ROOT,
    ModuleIndex,
    apply_baseline,
    load_baseline,
    run_checks,
    write_baseline,
)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.statlint",
        description=(
            "Machine-check the repo's load-bearing invariants (trace "
            "purity, lock discipline, env-knob convention, failure/fault "
            "registries, export-plane completeness, state algebra, dead "
            "imports)."
        ),
    )
    parser.add_argument(
        "paths", nargs="*",
        help=f"files/dirs to scan (default: {' '.join(DEFAULT_TARGETS)})",
    )
    parser.add_argument(
        "--baseline", default=None,
        help=(
            "baseline JSON of grandfathered findings (default: the "
            "checked-in tools/statlint/baseline.json when scanning the "
            "default tree; NONE for explicit paths)"
        ),
    )
    parser.add_argument(
        "--write-baseline", metavar="PATH",
        help="write every current finding to PATH as a baseline and exit 0",
    )
    parser.add_argument(
        "--checks", default=None,
        help="comma-separated check ids to run (default: all)",
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    import os

    if args.paths:
        paths = args.paths
        baseline_path = args.baseline
    else:
        paths = [os.path.join(REPO_ROOT, t) for t in DEFAULT_TARGETS]
        baseline_path = args.baseline or DEFAULT_BASELINE

    index = ModuleIndex(paths)
    only = args.checks.split(",") if args.checks else None
    try:
        findings = run_checks(index, only=only)
    except ValueError as exc:
        print(f"statlint: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(
            f"statlint: wrote {len(findings)} finding(s) to "
            f"{args.write_baseline}; fill in each entry's reason"
        )
        return 0

    try:
        baseline = load_baseline(baseline_path) if baseline_path else {}
    except (ValueError, KeyError, json.JSONDecodeError) as exc:
        print(f"statlint: bad baseline {baseline_path}: {exc}", file=sys.stderr)
        return 2

    if only:
        # a SCOPED run can only vouch for the checks it ran: entries of
        # unselected checks must not be reported stale (an operator
        # obeying "delete it" would break the full run)
        baseline = {
            fp: reason for fp, reason in baseline.items()
            if fp.split(":", 1)[0] in only
        }
    new, stale = apply_baseline(findings, baseline, baseline_path or "")
    reported: List = new + stale
    elapsed = time.monotonic() - t0

    if args.json:
        print(json.dumps({
            "modules": len(index.modules),
            "findings": [f.__dict__ for f in reported],
            "baselined": len(findings) - len(new),
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
    else:
        for f in reported:
            print(f.render())
        print(
            f"statlint: {len(index.modules)} modules, "
            f"{len(new)} finding(s), {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'}, "
            f"{len(findings) - len(new)} baselined, {elapsed:.2f}s"
        )
    return 1 if reported else 0


if __name__ == "__main__":
    sys.exit(main())
