"""Check: the env-knob convention.

Every ``DEEQU_TPU_*`` knob read must (a) go through the shared
``utils.env_number``/``env_str``/``env_flag`` parsers — the warn-once,
keep-the-default convention — or live in ``config.py``/``utils.py``
themselves, and (b) be documented in ``config.py``, the one place an
operator can discover every switch. Custom parsers with richer semantics
(the watchdog's derived deadline, tri-state probes) are deliberate and
carry baseline entries instead of silent exemptions.
"""

from __future__ import annotations

from typing import List

from ..core import Finding, ModuleIndex, iter_env_reads

CHECK = "env-knob"

PREFIX = "DEEQU_TPU_"

#: modules allowed to touch os.environ directly for DEEQU_TPU_* knobs
ALLOWED_SUFFIXES = ("deequ_tpu/config.py", "deequ_tpu/utils.py")


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    names_read = set()
    for module in index.modules:
        allowed = module.relpath.endswith(ALLOWED_SUFFIXES)
        for node, env_name, style in iter_env_reads(module):
            if env_name is None or not env_name.startswith(PREFIX):
                continue
            names_read.add(env_name)
            if style == "direct" and not allowed:
                findings.append(Finding(
                    check=CHECK, path=module.relpath, line=node.lineno,
                    message=(
                        f"direct os.environ read of {env_name}: go through "
                        "utils.env_number/env_str/env_flag (warn-once "
                        "convention) or baseline with a reason"
                    ),
                    key=f"direct:{env_name}",
                ))
    config = index.get("deequ_tpu/config.py")
    if config is not None:
        for env_name in sorted(names_read):
            if env_name not in config.source:
                findings.append(Finding(
                    check=CHECK, path=config.relpath, line=1,
                    message=(
                        f"{env_name} is read in the package but not "
                        "documented in config.py (every operator-facing "
                        "knob must be discoverable there)"
                    ),
                    key=f"undocumented:{env_name}",
                ))
    return findings
