"""Check: export-plane completeness.

Every ``deequ_service_*`` series the code can emit must carry a HELP
description (``ServiceMetrics.describe`` or the help argument of
``set_gauge_fn``) somewhere in the package. The Prometheus renderer
falls back to a generated placeholder for undescribed series, so this
never breaks a scrape — but a counter nobody can interpret is telemetry
debt, and ``promtool``-grade HELP text is cheap at authoring time and
impossible to reconstruct later.

Series names are collected as STRING LITERALS matching
``deequ_service_[a-z0-9_]+`` anywhere in the scanned tree (increments are
built through ``inc``, ``inc_many`` tuples, list-comps and batched-update
lists — chasing every shape is fragile; any mention of an undescribed
series is close enough to an emission to demand the description).
Histogram families (``ServiceMetrics.observe``) are series too:
``describe_histogram`` marks them described, same contract as counters.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..core import Finding, ModuleIndex, literal_str

CHECK = "export-help"

_SERIES_RE = re.compile(r"^deequ_service_[a-z0-9_]+$")


def run(index: ModuleIndex) -> List[Finding]:
    mentions: Dict[str, Tuple[str, int]] = {}
    described = set()
    for module in index.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if _SERIES_RE.match(node.value):
                    mentions.setdefault(
                        node.value, (module.relpath, node.lineno)
                    )
            elif isinstance(node, ast.Call):
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) else (
                    func.id if isinstance(func, ast.Name) else None
                )
                first = literal_str(node.args[0]) if node.args else None
                if first is None:
                    continue
                if name == "describe" and len(node.args) >= 2:
                    described.add(first)
                elif name == "describe_histogram" and (
                    len(node.args) >= 2
                    or any(k.arg == "help_text" for k in node.keywords)
                ):
                    described.add(first)
                elif name == "set_gauge_fn" and (
                    len(node.args) >= 3
                    or any(k.arg == "help_text" for k in node.keywords)
                ):
                    described.add(first)
    findings: List[Finding] = []
    for series, (path, line) in sorted(mentions.items()):
        if series not in described:
            findings.append(Finding(
                check=CHECK, path=path, line=line,
                message=(
                    f"series {series} is emitted but never described "
                    "(ServiceMetrics.describe / set_gauge_fn help text)"
                ),
                key=series,
            ))
    return findings
