"""Check: the typed-failure and fault-site registries.

The reliability story rests on two registries staying exhaustive:

1. **Typed exceptions.** Every exception class defined in the package
   must live in a registry module (``exceptions.py``, ``service/errors.py``,
   ``runners/exceptions.py``, ``reliability/faults.py``) or be listed in
   ``exceptions._SUBSYSTEM_EXCEPTIONS`` (the lazy re-export map) — a typed
   failure nobody can import from the taxonomy is not typed. Stale
   re-export entries (naming classes that moved/died) are flagged too.
2. **Fault sites.** Every ``fault_point(site, ...)`` probe must name a
   site in ``reliability/faults.KNOWN_FAULT_SITES``, and every registered
   site must still have a live probe — the chaos tooling targets sites by
   name, and a dangling name means a drill that silently exercises
   nothing (the docstring-table drift this check replaces).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Module, ModuleIndex, literal_str

CHECK = "failure-registry"

REGISTRY_SUFFIXES = (
    "deequ_tpu/exceptions.py",
    "deequ_tpu/service/errors.py",
    "deequ_tpu/runners/exceptions.py",
    "deequ_tpu/reliability/faults.py",
)

_EXC_BASE_NAMES = {
    "Exception", "BaseException", "RuntimeError", "ValueError",
    "TypeError", "KeyError", "OSError", "KeyboardInterrupt",
    "ImportError", "ArithmeticError", "StopIteration",
}

_EXC_NAME_SUFFIXES = (
    "Error", "Exception", "Failure", "Interrupt", "Crash", "Exceeded",
    "Overloaded", "Timeout", "Closed",
)

FAULT_SITES_NAME = "KNOWN_FAULT_SITES"
REEXPORT_NAME = "_SUBSYSTEM_EXCEPTIONS"


def _is_exception_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = base.id if isinstance(base, ast.Name) else (
            base.attr if isinstance(base, ast.Attribute) else None
        )
        if name is None:
            continue
        if name in _EXC_BASE_NAMES or name.endswith(_EXC_NAME_SUFFIXES):
            return True
    return False


def _find_assign(module: Module, target: str) -> Optional[ast.AST]:
    for node in module.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == target
        ):
            return node.value
    return None


def _fault_site_registry(index: ModuleIndex) -> Tuple[Optional[Set[str]], bool]:
    """(registered sites, registry_in_scan). Fixture scans fall back to
    the repo's live faults.py so unknown sites still resolve."""
    module = index.get("deequ_tpu/reliability/faults.py")
    in_scan = module is not None
    if module is None:
        module = index.side_load("deequ_tpu/reliability/faults.py")
    if module is None:
        return None, False
    value = _find_assign(module, FAULT_SITES_NAME)
    if value is None:
        return None, in_scan
    sites = {
        node.value
        for node in ast.walk(value)
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    }
    return sites, in_scan


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []

    # -- half 1: exception classes must be registry-importable -------------
    exceptions_mod = index.get("deequ_tpu/exceptions.py")
    reexports: Dict[str, str] = {}
    if exceptions_mod is not None:
        value = _find_assign(exceptions_mod, REEXPORT_NAME)
        if isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                key, val = literal_str(k), literal_str(v)
                if key and val:
                    reexports[key] = val
    defined: Dict[str, str] = {}  # class -> module relpath
    for module in index.modules:
        in_registry = module.relpath.endswith(REGISTRY_SUFFIXES)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if not _is_exception_class(node):
                continue
            defined[node.name] = module.relpath
            if in_registry or node.name in reexports:
                continue
            findings.append(Finding(
                check=CHECK, path=module.relpath, line=node.lineno,
                message=(
                    f"typed exception {node.name} is defined outside the "
                    "registry modules and not re-exported via "
                    f"exceptions.{REEXPORT_NAME}"
                ),
                key=f"exc-unregistered:{node.name}",
            ))
    if exceptions_mod is not None:
        for name, dotted in sorted(reexports.items()):
            relpath = dotted.replace(".", "/") + ".py"
            target = index.get(relpath)
            if target is None or not any(
                isinstance(n, ast.ClassDef) and n.name == name
                for n in ast.walk(target.tree)
            ):
                findings.append(Finding(
                    check=CHECK, path=exceptions_mod.relpath, line=1,
                    message=(
                        f"{REEXPORT_NAME} entry {name} -> {dotted} names a "
                        "class that does not exist there (stale registry)"
                    ),
                    key=f"exc-registry-stale:{name}",
                ))

    # -- half 2: fault_point sites <-> KNOWN_FAULT_SITES -------------------
    sites, registry_in_scan = _fault_site_registry(index)
    probed: Dict[str, Tuple[str, int]] = {}
    for module in index.modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None
            )
            if name != "fault_point" or not node.args:
                continue
            site = literal_str(node.args[0])
            if site is None:
                # line-free key (core.py's fingerprint contract): the
                # source expression itself is stable under edits above it
                expr = ast.unparse(node.args[0])
                findings.append(Finding(
                    check=CHECK, path=module.relpath, line=node.lineno,
                    message=(
                        "fault_point site is not a string literal — the "
                        "registry cannot vouch for dynamic site names"
                    ),
                    key=f"fault-site-dynamic:{expr}",
                ))
                continue
            probed.setdefault(site, (module.relpath, node.lineno))
            if sites is not None and site not in sites:
                findings.append(Finding(
                    check=CHECK, path=module.relpath, line=node.lineno,
                    message=(
                        f"fault_point site {site!r} is not registered in "
                        f"reliability/faults.{FAULT_SITES_NAME}"
                    ),
                    key=f"fault-site-unregistered:{site}",
                ))
    if sites is not None and registry_in_scan:
        for site in sorted(sites - set(probed)):
            findings.append(Finding(
                check=CHECK,
                path="deequ_tpu/reliability/faults.py", line=1,
                message=(
                    f"{FAULT_SITES_NAME} lists {site!r} but no live "
                    "fault_point probes it (dead registry entry)"
                ),
                key=f"fault-site-dead:{site}",
            ))
    return findings
