"""Check: the tuning-registry convention.

Every tunable routing constant lives in ``deequ_tpu/tuning/knobs.py``
(name, static default, bounds, substrate-sensitivity) and is read through
``knobs.value(...)`` so env overrides, boot-time calibration, and the
online controller move through ONE audited surface. Two drift shapes are
flagged:

(a) an env var REGISTERED in the knob registry read anywhere else — a
    module parsing a registered ``DEEQU_TPU_*`` override itself bypasses
    the tuned layer, so calibration silently stops applying to it;
(b) a new hand-coded routing threshold: a module-level numeric constant
    whose NAME says it is a routing/sizing cutoff (``*_MIN_ROWS``,
    ``*_MAX_DISTINCT``, ``*_THRESHOLD``, ``*_KNEE``, ``*_CROSSOVER``,
    ``*_PROBE_ROWS``, ...) used in a comparison — the exact pattern the
    registry exists to absorb. Deliberate non-tunable cutoffs carry
    baseline entries with reasons instead of silent exemptions.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..core import Finding, ModuleIndex, iter_env_reads

CHECK = "tuning-registry"

#: modules allowed to read registry env vars (knobs.py IS the reader;
#: config.py documents/re-exports; utils.py implements the parsers)
ALLOWED_SUFFIXES = (
    "deequ_tpu/tuning/knobs.py",
    "deequ_tpu/config.py",
    "deequ_tpu/utils.py",
)

#: module-level constant names that smell like hand-coded routing
#: thresholds (the shapes PRs 9-17 accumulated before the registry)
_THRESHOLD_NAME = re.compile(
    r"(_(MIN|MAX)_(ROWS|DISTINCT|WIDTH|DEPTH|ENTRIES|SLOTS|CARDINALITY)$)"
    r"|(_THRESHOLD$)|(_KNEE$)|(_CROSSOVER$)|(_PROBE_ROWS$)"
)

#: the threshold scan exempts the registry itself (whose static defaults
#: ARE the record) and config.py (documentation/re-export surface)
_SCAN_EXEMPT = ("deequ_tpu/tuning/", "deequ_tpu/config.py")


def _registered_envs(index: ModuleIndex) -> set:
    """Env names registered as knob overrides, parsed from knobs.py's AST
    (the string literals passed as the Knob constructor's env field)."""
    knobs = index.get("deequ_tpu/tuning/knobs.py")
    if knobs is None:
        return set()
    registered = set()
    for node in ast.walk(knobs.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "k"):
            continue
        env: Optional[str] = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            env = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "env" and isinstance(kw.value, ast.Constant):
                env = kw.value.value
        if isinstance(env, str):
            registered.add(env)
    return registered


def _const_number(node: ast.AST) -> Optional[float]:
    """Evaluate a constant numeric expression — including the package's
    idiomatic ``1 << 21`` / ``4 * 1024`` shapes ``ast.literal_eval``
    refuses — or None."""
    if isinstance(node, ast.Constant):
        value = node.value
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            return value
        return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_number(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.BinOp):
        left = _const_number(node.left)
        right = _const_number(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.LShift):
                return int(left) << int(right)
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Pow):
                return left ** right
        except (ValueError, OverflowError):
            return None
    return None


def _numeric_threshold_constants(module) -> List[ast.Assign]:
    out = []
    for node in module.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and _THRESHOLD_NAME.search(node.targets[0].id)
            and _const_number(node.value) is not None
        ):
            out.append(node)
    return out


def _compared_names(module) -> set:
    """Names appearing inside an ast.Compare anywhere in the module — a
    constant merely re-exported or passed as a parser default is not a
    routing decision; one something is compared AGAINST is."""
    names = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
    return names


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    registered = _registered_envs(index)
    for module in index.modules:
        if module.relpath.endswith(ALLOWED_SUFFIXES):
            continue
        for node, env_name, _style in iter_env_reads(module):
            if env_name in registered:
                findings.append(Finding(
                    check=CHECK, path=module.relpath, line=node.lineno,
                    message=(
                        f"{env_name} is registered in tuning/knobs.py but "
                        "read directly here: resolve through knobs.value() "
                        "so env overrides, calibration, and the online "
                        "controller stay on one surface"
                    ),
                    key=f"bypass:{env_name}",
                ))
        if module.relpath.startswith(_SCAN_EXEMPT):
            continue
        compared = _compared_names(module)
        for node in _numeric_threshold_constants(module):
            name = node.targets[0].id
            if name not in compared:
                continue
            findings.append(Finding(
                check=CHECK, path=module.relpath, line=node.lineno,
                message=(
                    f"hand-coded routing threshold {name}: register it as "
                    "a tuning knob (tuning/knobs.py) with the measured "
                    "value as its static default, or baseline with a "
                    "reason why it must stay fixed"
                ),
                key=f"threshold:{name}",
            ))
    return findings
