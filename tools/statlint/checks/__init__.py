"""Check plugins: one module per machine-checked contract. A check module
exposes ``CHECK`` (the id every finding carries) and ``run(index) ->
List[Finding]``; registering it here is all it takes to gate tier-1."""

from . import (  # noqa: F401
    dead_imports,
    env_knobs,
    export_help,
    failure_registry,
    lock_discipline,
    span_kinds,
    state_algebra,
    trace_purity,
    tuning_registry,
)

ALL_CHECKS = (
    trace_purity,
    lock_discipline,
    env_knobs,
    failure_registry,
    export_help,
    state_algebra,
    dead_imports,
    tuning_registry,
    span_kinds,
)
