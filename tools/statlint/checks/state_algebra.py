"""Check: the state-merge algebra.

Persisted analyzer states are mergeable BY CONSTRUCTION — that is what
makes incremental verification, mesh salvage and cross-session coalescing
correct. Two machine-checked halves:

1. every ``*State`` class must implement (or visibly inherit) ``merge``;
2. the identity-merge-transparency registry
   (``IDENTITY_TRANSPARENT_STATES``) may only name classes that exist in
   its module and that themselves define both ``init`` and ``merge`` —
   a stale registry entry would silently route a non-transparent state
   onto the host fast path, the exact class of bit-drift the registry
   exists to prevent.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, ModuleIndex

CHECK = "state-algebra"

REGISTRY_NAME = "IDENTITY_TRANSPARENT_STATES"


def _method_names(cls: ast.ClassDef) -> set:
    return {
        n.name for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    classes_by_module = {}
    for module in index.modules:
        classes = {
            node.name: node
            for node in ast.walk(module.tree)
            if isinstance(node, ast.ClassDef)
        }
        classes_by_module[module.relpath] = classes
        for name, cls in sorted(classes.items()):
            if not name.endswith("State"):
                continue
            if "merge" in _method_names(cls):
                continue
            # a base class within the same module may provide merge
            base_names = [
                b.id for b in cls.bases if isinstance(b, ast.Name)
            ]
            if any(
                base in classes and "merge" in _method_names(classes[base])
                for base in base_names
            ):
                continue
            if base_names and not all(b in classes for b in base_names):
                continue  # inherits from outside the module: not provable
            findings.append(Finding(
                check=CHECK, path=module.relpath, line=cls.lineno,
                message=(
                    f"state class {name} has no merge() — every *State "
                    "must be a semigroup (mergeable by construction)"
                ),
                key=f"no-merge:{name}",
            ))
    # registry entries must name real, fully-algebraic classes
    for module in index.modules:
        classes = classes_by_module[module.relpath]
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == REGISTRY_NAME
            ):
                continue
            for name_node in ast.walk(node.value):
                if not isinstance(name_node, ast.Name):
                    continue
                if name_node.id in ("frozenset", "set", REGISTRY_NAME):
                    continue
                cls = classes.get(name_node.id)
                if cls is None:
                    findings.append(Finding(
                        check=CHECK, path=module.relpath,
                        line=name_node.lineno,
                        message=(
                            f"{REGISTRY_NAME} names {name_node.id}, which "
                            "is not a class defined in this module"
                        ),
                        key=f"registry-unknown:{name_node.id}",
                    ))
                    continue
                missing = {"init", "merge"} - _method_names(cls)
                if missing:
                    findings.append(Finding(
                        check=CHECK, path=module.relpath,
                        line=name_node.lineno,
                        message=(
                            f"{REGISTRY_NAME} entry {name_node.id} lacks "
                            f"{sorted(missing)} — transparency claims "
                            "require the full init/merge algebra"
                        ),
                        key=f"registry-incomplete:{name_node.id}",
                    ))
    return findings
