"""Check: the span-kind registry.

Every span opened through the tracing API (``trace.span`` /
``trace.start_span`` and their ``_trace``-aliased forms) must carry a
``kind=`` drawn from :data:`deequ_tpu.observability.trace.SPAN_KINDS` —
the registry consumers key on (trace_summarize groups by kind, the
Chrome export uses it as the category, the fleetwatch series derive
from it). A kind invented at a call site renders fine and then silently
falls out of every kind-keyed view; the registry makes adding one a
one-line, reviewed change instead of a typo.

Matching is deliberately NARROW: only calls whose callee is ``span`` or
``start_span`` reached through a ``trace``/``_trace`` name (or bare,
when imported from the observability package) are considered — a
``kind=`` keyword on anything else (``np.argsort(kind="stable")``,
``np.sort``) is someone else's API, not ours. Non-literal kinds
(variables, f-strings) are skipped: this is a spelling gate, not a
dataflow analysis.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Finding, ModuleIndex, attr_chain, literal_str

CHECK = "span-kind-registry"

#: where the registry lives, parsed from source so the check needs no
#: package import (fixture scans run it against arbitrary files)
_REGISTRY_MODULE = "deequ_tpu/observability/trace.py"

_SPAN_FUNCS = {"span", "start_span"}
_TRACE_BASES = {"trace", "_trace"}


def _registry_kinds(index: ModuleIndex) -> Optional[Set[str]]:
    """The SPAN_KINDS literal from trace.py — from the scanned set when
    it is in scope, side-loaded from the repo tree otherwise (fixture
    mode). None when the registry cannot be resolved at all: better to
    skip than to flag every span in a tree that renamed the module."""
    module = index.get(_REGISTRY_MODULE) or index.side_load(_REGISTRY_MODULE)
    if module is None:
        return None
    for node in module.tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "SPAN_KINDS"
        ):
            continue
        kinds: Set[str] = set()
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                kinds.add(sub.value)
        return kinds or None
    return None


def _is_span_call(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    if chain is None or chain[-1] not in _SPAN_FUNCS:
        return False
    if len(chain) == 1:
        # bare span()/start_span(): the from-import idiom — still ours;
        # nothing else in the tree spells a callable that way
        return True
    return chain[-2] in _TRACE_BASES


def run(index: ModuleIndex) -> List[Finding]:
    kinds = _registry_kinds(index)
    if kinds is None:
        return []
    findings: List[Finding] = []
    for module in index.modules:
        if module.relpath.endswith(_REGISTRY_MODULE):
            continue  # the registry's own internals construct Spans freely
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and _is_span_call(node)):
                continue
            for kw in node.keywords:
                if kw.arg != "kind":
                    continue
                value = literal_str(kw.value)
                if value is None or value in kinds:
                    continue
                findings.append(Finding(
                    check=CHECK, path=module.relpath, line=node.lineno,
                    message=(
                        f"span kind {value!r} is not in the SPAN_KINDS "
                        "registry (deequ_tpu/observability/trace.py): "
                        "register it, or use an existing kind — unknown "
                        "kinds fall out of every kind-keyed view"
                    ),
                    key=f"kind:{value}",
                ))
    return findings
