"""Check: trace purity.

Functions that run INSIDE a jax trace — anything registered with
``jit``/``vmap``/``pjit``/``shard_map``/``lax.cond|scan|while_loop``, the
flax state dataclasses' ``merge``/``update``/``compacted``/``append_keys``
methods, and the analyzer ``update``/``from_host_partial`` fold bodies —
must be pure: no wall clock, no host randomness, no env reads, no
``.item()``/host materialization, no I/O. An impurity in a traced body is
the worst kind of bug: it executes once at TRACE time, bakes a stale value
into the compiled program, and then silently disagrees with every later
dispatch (or re-triggers a compile per call).

Reachability is a name-level over-approximation: calls resolve to
same-module functions (any nesting), ``self.``/``cls.`` methods of the
enclosing class, names imported from scanned modules, and — for the
state-method names above — every flax-struct state class's method of that
name. Over-approximation errs toward flagging; deliberate host-side
helpers caught in the net carry baseline entries with reasons.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Module, ModuleIndex, attr_chain

CHECK = "trace-purity"

#: APIs whose function-valued arguments execute inside a trace
TRACE_APIS = {
    "jit", "vmap", "pjit", "shard_map", "_shard_map", "pmap",
    "cond", "scan", "while_loop", "fori_loop", "switch", "checkpoint",
    "remat", "custom_vjp", "custom_jvp",
}

#: methods of flax.struct dataclasses (and analyzer fold protocols) that
#: are traced by construction
TRACED_METHOD_NAMES = {
    "merge", "update", "compacted", "append_keys", "from_host_partial",
}

#: banned attribute-chain prefixes inside traced bodies
_BANNED_PREFIXES = (
    (("time",), "wall-clock read"),
    (("np", "random"), "host randomness"),
    (("numpy", "random"), "host randomness"),
    (("random",), "host randomness"),
    (("os", "environ"), "env read mid-trace"),
    (("os", "getenv"), "env read mid-trace"),
    (("jax", "device_get"), "host materialization"),
)

_BANNED_METHODS = {"item": "host materialization (.item())"}
_BANNED_BUILTINS = {"open": "I/O", "print": "host I/O", "input": "host I/O"}


def _is_flax_struct(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        chain = attr_chain(dec) or (
            attr_chain(dec.func) if isinstance(dec, ast.Call) else None
        )
        if chain and chain[-1] == "dataclass" and any(
            "struct" in part for part in chain
        ):
            return True
    return False


def _is_scan_shareable(cls: ast.ClassDef) -> bool:
    """Classes whose fold methods ride the fused device program. Host-side
    accumulators (GroupingAnalyzer's pandas group-bys) also define
    ``update``/``merge`` but never enter a trace — only the ScanShareable
    hierarchy and flax state dataclasses do."""
    for base in cls.bases:
        node = base.value if isinstance(base, ast.Subscript) else base
        chain = attr_chain(node)
        if chain and "ScanShareable" in chain[-1]:
            return True
    return False


class _FuncInfo:
    __slots__ = ("module", "node", "qualname", "cls")

    def __init__(self, module: Module, node: ast.AST, qualname: str,
                 cls: Optional[ast.ClassDef]):
        self.module = module
        self.node = node
        self.qualname = qualname
        self.cls = cls


def _index_functions(index: ModuleIndex):
    """Tables: per-module name->funcs (any nesting), per-class methods,
    flax state classes, import links between scanned modules."""
    by_module: Dict[str, Dict[str, List[_FuncInfo]]] = {}
    methods: Dict[Tuple[str, str], Dict[str, _FuncInfo]] = {}
    state_methods: Dict[str, List[_FuncInfo]] = {}

    for module in index.modules:
        table: Dict[str, List[_FuncInfo]] = {}
        by_module[module.relpath] = table

        def visit(node, cls, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = _FuncInfo(
                        module, child, f"{prefix}{child.name}", cls
                    )
                    table.setdefault(child.name, []).append(info)
                    if cls is not None:
                        methods.setdefault(
                            (module.relpath, cls.name), {}
                        )[child.name] = info
                        if child.name in TRACED_METHOD_NAMES and (
                            _is_flax_struct(cls) or _is_scan_shareable(cls)
                        ):
                            state_methods.setdefault(
                                child.name, []
                            ).append(info)
                    visit(child, cls, f"{prefix}{child.name}.")
                elif isinstance(child, ast.ClassDef):
                    visit(child, child, f"{prefix}{child.name}.")
                else:
                    visit(child, cls, prefix)

        visit(module.tree, None, "")
    return by_module, methods, state_methods


def _roots(index: ModuleIndex, by_module, methods, state_methods):
    roots: List[_FuncInfo] = []
    # 1. every traced state/analyzer fold method
    for infos in state_methods.values():
        roots.extend(infos)
    # 2. functions registered with a tracing API (call args or decorators)
    for module in index.modules:
        table = by_module[module.relpath]
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if not chain or chain[-1] not in TRACE_APIS:
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in table:
                        roots.extend(table[arg.id])
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    chain = attr_chain(target)
                    if chain and chain[-1] in TRACE_APIS:
                        roots.extend(table.get(node.name, []))
    return roots


def _called_infos(info: _FuncInfo, by_module, methods, state_methods, index):
    """Resolve the call sites inside one function body."""
    out: List[_FuncInfo] = []
    module = info.module
    table = by_module[module.relpath]
    imports: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom) and node.level:
            # relative import within the package: resolve to a relpath
            base = module.relpath.rsplit("/", 1)[0]
            for _ in range(node.level - 1):
                base = base.rsplit("/", 1)[0]
            dotted = (node.module or "").replace(".", "/")
            target = f"{base}/{dotted}".rstrip("/")
            for alias in node.names:
                imports[alias.asname or alias.name] = (target, alias.name)
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in table:
                out.extend(table[func.id])
            elif func.id in imports:
                target_rel, original = imports[func.id]
                target = index.get(f"{target_rel}.py") or index.get(
                    f"{target_rel}/__init__.py"
                )
                if target is not None:
                    out.extend(
                        by_module[target.relpath].get(original, [])
                    )
        elif isinstance(func, ast.Attribute):
            chain = attr_chain(func)
            if chain and chain[0] in ("self", "cls") and len(chain) == 2:
                if info.cls is not None:
                    m = methods.get(
                        (module.relpath, info.cls.name), {}
                    ).get(chain[1])
                    if m is not None:
                        out.append(m)
            if func.attr in state_methods and not (
                chain and chain[0] in ("jnp", "np", "jax", "lax")
            ):
                # state-method dispatch: a.merge(b) on an unknown receiver
                out.extend(state_methods[func.attr])
    return out


def _impurities(info: _FuncInfo) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        chain = attr_chain(func)
        if chain:
            for prefix, why in _BANNED_PREFIXES:
                exact_call = prefix in (("os", "getenv"), ("jax", "device_get"))
                if tuple(chain[: len(prefix)]) == prefix and (
                    len(chain) > len(prefix) or exact_call
                ):
                    out.append((node.lineno, f"{'.'.join(chain)} ({why})"))
                    break
        if isinstance(func, ast.Attribute) and func.attr in _BANNED_METHODS:
            out.append(
                (node.lineno, _BANNED_METHODS[func.attr])
            )
        if isinstance(func, ast.Name) and func.id in _BANNED_BUILTINS:
            out.append(
                (node.lineno, f"{func.id}() ({_BANNED_BUILTINS[func.id]})")
            )
    return out


def run(index: ModuleIndex) -> List[Finding]:
    by_module, methods, state_methods = _index_functions(index)
    roots = _roots(index, by_module, methods, state_methods)

    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    visited: Set[int] = set()
    stack: List[Tuple[_FuncInfo, str]] = [
        (r, f"{r.module.relpath}:{r.qualname}") for r in roots
    ]
    while stack:
        info, origin = stack.pop()
        if id(info.node) in visited:
            continue
        visited.add(id(info.node))
        for line, what in _impurities(info):
            ident = (f"{info.module.relpath}:{info.qualname}", what)
            if ident in seen:
                continue
            seen.add(ident)
            findings.append(Finding(
                check=CHECK, path=info.module.relpath, line=line,
                message=(
                    f"{info.qualname} is reachable from traced code "
                    f"(root: {origin}) but calls {what}"
                ),
                key=f"{info.qualname}:{what}",
            ))
        for callee in _called_infos(
            info, by_module, methods, state_methods, index
        ):
            stack.append((callee, origin))
    return findings
