"""Check: lock discipline in the service plane.

The scheduler/coalescer/streaming/fleet contracts are enforced by hand-held
locks — exactly where the PR 12/13 flake hunt found real bugs. Three
machine-checked properties per class that owns ``threading`` locks:

1. **Unguarded shared writes** (the PR 13 cross-key commit-inversion
   shape): an instance attribute written (or mutated via
   ``append``/``pop``/...) both while holding the owning lock and on some
   path that provably does not hold it. A method documented "call me under
   the lock" counts as guarded when every same-class call site holds the
   lock; a method called both ways keeps its unguarded writes visible.
2. **Same-lock re-acquisition**: while holding a non-reentrant
   ``threading.Lock`` (or a Condition wrapping one), calling a same-class
   method that lexically acquires that same lock — a guaranteed deadlock.
   ``threading.Condition()`` with no argument wraps an RLock and is
   exempt; ``Condition(self._lock)`` aliases the wrapped lock.
3. **Acquisition-order cycles** across classes: an edge A→B is recorded
   when lock A is held while acquiring lock B (lexically, through a
   same-class method, or through a call into another scanned class —
   resolved by constructor-typed attributes or a package-unique method
   name). A cycle means two threads can deadlock by arriving in opposite
   orders.

All resolution is a name-level heuristic over the shared parse cache;
deliberate exceptions carry baseline entries with reasons.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Module, ModuleIndex, attr_chain

CHECK = "lock-discipline"

_MUTATORS = {
    "append", "appendleft", "add", "pop", "popleft", "popitem", "update",
    "discard", "remove", "clear", "insert", "extend", "setdefault",
}

#: attribute names assigned these literal types in __init__ are builtin
#: containers — calls through them never take a scanned class's lock
_BUILTIN_LITERALS = (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp)


class _LockInfo:
    __slots__ = ("name", "kind", "alias_of")

    def __init__(self, name: str, kind: str, alias_of: Optional[str] = None):
        self.name = name
        self.kind = kind        # "lock" | "rlock" | "cond-own"
        self.alias_of = alias_of


class _ClassModel:
    def __init__(self, module: Module, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.locks: Dict[str, _LockInfo] = {}
        #: attr -> constructor class name (self.x = ClassName(...))
        self.attr_types: Dict[str, str] = {}
        #: attrs assigned builtin container literals in __init__
        self.builtin_attrs: Set[str] = set()
        #: method name -> analysis
        self.methods: Dict[str, "_MethodModel"] = {}

    def canonical(self, lock_attr: str) -> str:
        seen = set()
        while True:
            info = self.locks.get(lock_attr)
            if info is None or info.alias_of is None or lock_attr in seen:
                return lock_attr
            seen.add(lock_attr)
            lock_attr = info.alias_of

    def kind(self, lock_attr: str) -> str:
        info = self.locks.get(self.canonical(lock_attr))
        return info.kind if info else "lock"


class _MethodModel:
    def __init__(self, name: str):
        self.name = name
        #: locks (canonical) acquired lexically anywhere inside
        self.acquires: Set[str] = set()
        #: (attr, held: bool, line)
        self.writes: List[Tuple[str, bool, int]] = []
        #: (method_name, frozenset held, line) same-class calls
        self.self_calls: List[Tuple[str, frozenset, int]] = []
        #: (receiver_attr_chain, method_name, frozenset held, line)
        self.foreign_calls: List[Tuple[Tuple[str, ...], str, frozenset, int]] = []


def _lock_ctor(node: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    """(kind, aliased_attr) when ``node`` constructs a threading primitive."""
    if not isinstance(node, ast.Call):
        return None
    chain = attr_chain(node.func)
    if not chain:
        return None
    leaf = chain[-1]
    if leaf == "Lock":
        return "lock", None
    if leaf == "RLock":
        return "rlock", None
    if leaf == "Condition":
        if node.args:
            arg_chain = attr_chain(node.args[0])
            if arg_chain and arg_chain[0] == "self" and len(arg_chain) == 2:
                return "cond", arg_chain[1]
            return "lock", None  # wraps something we can't see: assume Lock
        return "rlock", None  # bare Condition() wraps an RLock
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x`` (exactly one level)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _build_class(module: Module, node: ast.ClassDef,
                 class_names: Set[str]) -> _ClassModel:
    model = _ClassModel(module, node)
    # pass 1: lock attrs + constructor-typed attrs, from ANY method (some
    # classes create locks lazily outside __init__)
    for body_node in ast.walk(node):
        if not isinstance(body_node, ast.Assign):
            continue
        for target in body_node.targets:
            attr = _self_attr(target)
            if attr is None:
                continue
            ctor = _lock_ctor(body_node.value)
            if ctor is not None:
                kind, alias = ctor
                if alias is not None:
                    model.locks[attr] = _LockInfo(attr, "cond", alias_of=alias)
                else:
                    model.locks[attr] = _LockInfo(attr, kind)
                continue
            if isinstance(body_node.value, _BUILTIN_LITERALS) or (
                isinstance(body_node.value, ast.Call)
                and isinstance(body_node.value.func, ast.Name)
                and body_node.value.func.id in
                ("dict", "list", "set", "deque", "OrderedDict", "defaultdict")
            ):
                model.builtin_attrs.add(attr)
                continue
            for call in ast.walk(body_node.value):
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)
                    and call.func.id in class_names
                ):
                    model.attr_types[attr] = call.func.id
                    break
    # pass 2: per-method lock-flow analysis
    for child in node.body:
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            method = _MethodModel(child.name)
            _walk_method(model, method, child.body, frozenset())
            model.methods[child.name] = method
    return model


def _walk_method(model: _ClassModel, method: _MethodModel,
                 body, held: frozenset) -> None:
    for node in body:
        _walk_stmt(model, method, node, held)


def _walk_stmt(model: _ClassModel, method: _MethodModel,
               node: ast.AST, held: frozenset) -> None:
    if isinstance(node, ast.With):
        inner = held
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in model.locks:
                canonical = model.canonical(attr)
                method.acquires.add(canonical)
                inner = inner | {canonical}
        _walk_method(model, method, node.body, inner)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        # a nested function/closure runs LATER, possibly without the lock:
        # analyze its body with nothing held
        inner_body = node.body if isinstance(node.body, list) else [
            ast.Expr(value=node.body)
        ]
        _walk_method(model, method, inner_body, frozenset())
        return
    # expressions/targets at this level
    _scan_exprs(model, method, node, held)
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.expr, ast.keyword, ast.arguments)):
            continue  # handled by _scan_exprs on the parent
        _walk_stmt(model, method, child, held)


def _scan_exprs(model: _ClassModel, method: _MethodModel,
                node: ast.AST, held: frozenset) -> None:
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for target in targets:
            base = target
            while isinstance(base, (ast.Subscript, ast.Starred)):
                base = base.value
            attr = _self_attr(base)
            if attr is not None and attr not in model.locks:
                method.writes.append((attr, bool(held), node.lineno))
        value = getattr(node, "value", None)
        if value is not None:
            _scan_calls(model, method, value, held)
        return
    # statements that carry expressions (Expr, Return, If tests, etc.)
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.expr):
            _scan_calls(model, method, child, held)


def _scan_calls(model: _ClassModel, method: _MethodModel,
                node: ast.AST, held: frozenset) -> None:
    for call in ast.walk(node):
        if isinstance(call, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if not isinstance(call, ast.Call):
            continue
        chain = attr_chain(call.func)
        if not chain or chain[0] != "self":
            continue
        if len(chain) == 2:
            method.self_calls.append((chain[1], held, call.lineno))
        elif len(chain) >= 3:
            receiver = tuple(chain[1:-1])
            leaf = chain[-1]
            if receiver[0] in model.builtin_attrs:
                continue  # dict/list/deque method, takes no scanned lock
            if receiver[0] in model.locks:
                continue  # lock.acquire()/notify()/wait(): not a class call
            if leaf in _MUTATORS and receiver[-1] in model.builtin_attrs:
                continue
            # a mutator through a plain self attr is a WRITE to that attr
            if len(receiver) == 1 and leaf in _MUTATORS:
                method.writes.append((receiver[0], bool(held), call.lineno))
                continue
            method.foreign_calls.append((receiver, leaf, held, call.lineno))


def _collect_models(index: ModuleIndex) -> List[_ClassModel]:
    class_names: Set[str] = set()
    pending: List[Tuple[Module, ast.ClassDef]] = []
    for module in index.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                class_names.add(node.name)
                pending.append((module, node))
    return [
        _build_class(module, node, class_names) for module, node in pending
    ]


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    models = [m for m in _collect_models(index) if m.locks]
    by_name: Dict[str, List[_ClassModel]] = {}
    method_owner: Dict[str, List[_ClassModel]] = {}
    for model in models:
        by_name.setdefault(model.name, []).append(model)
        for name in model.methods:
            method_owner.setdefault(name, []).append(model)

    # ---- property 1: unguarded shared writes -----------------------------
    for model in models:
        # HELD-ONLY methods: take no lock themselves and every same-class
        # call site is lexically under a lock or inside another held-only
        # method (the `_foo_locked` helper convention) — computed as a
        # fixpoint so lock->helper->helper chains count
        call_sites: Dict[str, List[Tuple[str, bool]]] = {}
        for method in model.methods.values():
            for name, held, _ in method.self_calls:
                if name in model.methods:
                    call_sites.setdefault(name, []).append(
                        (method.name, bool(held))
                    )
        held_only: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for name, method in model.methods.items():
                if name in held_only or method.acquires:
                    continue
                sites = call_sites.get(name)
                if not sites:
                    continue
                if all(h or caller in held_only for caller, h in sites):
                    held_only.add(name)
                    changed = True
        write_map: Dict[str, Dict[bool, List[Tuple[str, int]]]] = {}
        for method in model.methods.values():
            if method.name == "__init__":
                continue
            for attr, held, line in method.writes:
                effective = held or method.name in held_only
                write_map.setdefault(attr, {}).setdefault(
                    effective, []
                ).append((method.name, line))
        for attr, contexts in sorted(write_map.items()):
            if True in contexts and False in contexts:
                guarded = sorted({m for m, _ in contexts[True]})
                naked = sorted({m for m, _ in contexts[False]})
                line = contexts[False][0][1]
                findings.append(Finding(
                    check=CHECK, path=model.module.relpath, line=line,
                    message=(
                        f"{model.name}.{attr} is written under a lock in "
                        f"{guarded} but without one in {naked} — the "
                        "PR 13 commit-inversion shape (shared-field write "
                        "reachable with and without the owning lock)"
                    ),
                    key=f"unguarded-write:{model.name}.{attr}",
                ))

    # ---- properties 2+3: re-acquisition and order cycles -----------------
    edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    edge_sites: Dict[Tuple[Tuple[str, str], Tuple[str, str]], Tuple[str, int]] = {}

    def resolve_foreign(model: _ClassModel, receiver: Tuple[str, ...],
                        leaf: str) -> Optional[_ClassModel]:
        cls_name = model.attr_types.get(receiver[0]) if len(receiver) == 1 else None
        if cls_name and cls_name in by_name and len(by_name[cls_name]) == 1:
            target = by_name[cls_name][0]
            if leaf in target.methods:
                return target
        owners = method_owner.get(leaf, [])
        if len(owners) == 1 and owners[0] is not model:
            return owners[0]
        return None

    for model in models:
        node_of = lambda lock: (model.name, lock)  # noqa: E731
        for method in model.methods.values():
            for name, held, line in method.self_calls:
                callee = model.methods.get(name)
                if callee is None or not held:
                    continue
                for lock in callee.acquires:
                    for held_lock in held:
                        if lock == held_lock:
                            if model.kind(lock) != "rlock":
                                findings.append(Finding(
                                    check=CHECK, path=model.module.relpath,
                                    line=line,
                                    message=(
                                        f"{model.name}.{method.name} holds "
                                        f"self.{held_lock} and calls "
                                        f"self.{name}() which re-acquires "
                                        "it — non-reentrant deadlock"
                                    ),
                                    key=(
                                        f"reacquire:{model.name}."
                                        f"{method.name}->{name}:{lock}"
                                    ),
                                ))
                        else:
                            a, b = node_of(held_lock), node_of(lock)
                            edges.setdefault(a, set()).add(b)
                            edge_sites.setdefault(
                                (a, b), (model.module.relpath, line)
                            )
            for receiver, leaf, held, line in method.foreign_calls:
                if not held:
                    continue
                target = resolve_foreign(model, receiver, leaf)
                if target is None:
                    continue
                callee = target.methods.get(leaf)
                if callee is None:
                    continue
                for lock in callee.acquires:
                    b = (target.name, lock)
                    for held_lock in held:
                        a = (model.name, held_lock)
                        if a == b:
                            continue
                        edges.setdefault(a, set()).add(b)
                        edge_sites.setdefault(
                            (a, b), (model.module.relpath, line)
                        )

    # lexical nested with-blocks: with self.A: ... with self.B: -> edge
    for model in models:
        for child in model.node.body:
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            _nested_with_edges(model, child, frozenset(), edges, edge_sites)

    # cycle detection over the acquisition graph
    reported: Set[frozenset] = set()
    for start in sorted(edges):
        cycle = _find_cycle(start, edges)
        if cycle is None:
            continue
        ident = frozenset(cycle)
        if ident in reported:
            continue
        reported.add(ident)
        pretty = " -> ".join(f"{c}.{l}" for c, l in cycle + [cycle[0]])
        path, line = edge_sites.get(
            (cycle[0], cycle[1 % len(cycle)]), ("", 0)
        )
        findings.append(Finding(
            check=CHECK, path=path or "statlint", line=line,
            message=(
                f"lock acquisition-order cycle: {pretty} — two threads "
                "arriving in opposite orders deadlock"
            ),
            key="cycle:" + "|".join(sorted(f"{c}.{l}" for c, l in cycle)),
        ))
    return findings


def _nested_with_edges(model, node, held, edges, edge_sites):
    if isinstance(node, ast.With):
        inner = held
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in model.locks:
                canonical = model.canonical(attr)
                for held_lock in inner:
                    if held_lock != canonical:
                        a = (model.name, held_lock)
                        b = (model.name, canonical)
                        edges.setdefault(a, set()).add(b)
                        edge_sites.setdefault(
                            (a, b), (model.module.relpath, node.lineno)
                        )
                inner = inner | {canonical}
        for child in node.body:
            _nested_with_edges(model, child, inner, edges, edge_sites)
        return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        body = node.body if isinstance(node.body, list) else []
        for child in body:
            _nested_with_edges(model, child, frozenset(), edges, edge_sites)
        return
    for child in ast.iter_child_nodes(node):
        _nested_with_edges(model, child, held, edges, edge_sites)


def _find_cycle(start, edges):
    """A simple DFS cycle through ``start``, or None."""
    stack = [(start, [start])]
    seen = set()
    while stack:
        node, path = stack.pop()
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                return path
            if nxt in seen or nxt in path:
                continue
            stack.append((nxt, path + [nxt]))
        seen.add(node)
    return None
