"""Check: dead imports.

Unused imports in the concurrency-critical trees (``service/``,
``parallel/``) are not just lint: they widen the import graph the lock
and purity checks must reason about, and they rot into false "this module
depends on X" signals for reviewers. Scope is deliberately narrow on the
default tree (the ISSUE-14 bound); explicit file scans (fixtures) check
everything they are given. ``# noqa`` on the import line and names listed
in ``__all__`` are honored (the config-style re-export idiom).
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import Finding, Module, ModuleIndex

CHECK = "dead-import"

SCOPES = ("deequ_tpu/service/", "deequ_tpu/parallel/")


def _used_names(module: Module) -> Set[str]:
    used: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # its base Name is walked separately
        elif isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if "__all__" in targets:
                for const in ast.walk(node.value):
                    if isinstance(const, ast.Constant) and isinstance(
                        const.value, str
                    ):
                        used.add(const.value)
    return used


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    for module in index.modules:
        if index.narrow and not any(s in module.relpath for s in SCOPES):
            continue
        used = _used_names(module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                bindings = [
                    (alias.asname or alias.name.split(".")[0], alias.name)
                    for alias in node.names
                ]
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                bindings = [
                    (alias.asname or alias.name, alias.name)
                    for alias in node.names
                    if alias.name != "*"
                ]
            else:
                continue
            if module.line_has_noqa(node):
                continue
            for bound, original in bindings:
                if bound not in used:
                    findings.append(Finding(
                        check=CHECK, path=module.relpath, line=node.lineno,
                        message=(
                            f"imported name {bound!r} is never used "
                            "(delete it, or `# noqa` a deliberate "
                            "re-export)"
                        ),
                        key=f"{bound}",
                    ))
    return findings
