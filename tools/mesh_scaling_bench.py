"""Multi-device scaling evidence on the virtual CPU mesh (VERDICT r3 ask #8).

Times, across mesh sizes, with a realistic 27-analyzer battery (HLL + KLL
sketch payloads included):

1. `collective_merge_states` — the butterfly (power-of-two meshes) vs the
   all-gather fallback (non-power-of-two), across shard counts;
2. `sharded_ingest_fold` — host-partial chunks folded over the mesh vs the
   equivalent single-device sequential fold.

Run it with N virtual CPU devices (no TPU pod needed — same GSPMD programs,
different interconnect constants):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/mesh_scaling_bench.py

CPU "collectives" are shared-memory copies, so absolute times model nothing;
what transfers to a v5e-8 is the SHAPE: program counts, collective rounds
(log2(n) for butterfly vs one fat all-gather), and the per-device fold work
(shards/n). See PERF.md "Multi-device scaling model" for the ICI arithmetic.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402


def battery():
    """27 analyzers with realistic state payloads: 2 KLL sketches (the fat
    states), 2 HLLs, and 23 scalar-state reductions."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLSketch,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
        Sum,
    )

    out = [Size()]
    for i in range(4):
        c = f"x{i}"
        out += [Completeness(c), Mean(c), Sum(c), Minimum(c), Maximum(c)]
    out += [StandardDeviation("x0"), StandardDeviation("x1")]
    out += [ApproxCountDistinct(c) for c in ("x0", "x1")]
    out += [KLLSketch("x0"), KLLSketch("x1")]
    assert len(out) == 27, len(out)
    return out


def build_shard_states(analyzers, n_shards: int, rows_per_shard: int = 1 << 12):
    """Per-shard states with REAL content (each shard updated on distinct
    data), stacked along a leading shard dim."""
    from deequ_tpu.runners.engine import ScanEngine

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(5)
    per_shard = []
    engine = ScanEngine(analyzers, placement="device")
    program = engine._update
    for s in range(n_shards):
        cols = {
            f"x{i}": rng.normal(10 * i + s, 3, rows_per_shard) for i in range(4)
        }
        batch = None
        for batch in Dataset.from_dict(cols).batches(
            rows_per_shard, columns=engine.required_columns()
        ):
            break
        features = engine._prepare(batch)
        states = program.unpack(program(program.init_carry(), features))
        per_shard.append(states)
    stacked = tuple(
        jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *[p[i] for p in per_shard])
        for i in range(len(analyzers))
    )
    jax.block_until_ready(stacked)
    return stacked


def time_merge(analyzers, mesh, stacked, repeats: int = 3) -> float:
    from deequ_tpu.parallel import collective_merge_states

    collective_merge_states(analyzers, mesh, stacked)  # compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = collective_merge_states(analyzers, mesh, stacked)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def time_sequential_fold(analyzers, stacked, repeats: int = 3) -> float:
    """Single-device baseline: lax.scan fold over the shard dim (the program
    merge_states_batched compiles)."""

    @jax.jit
    def fold(stacked):
        out = []
        for a, tree in zip(analyzers, stacked):
            first = jax.tree_util.tree_map(lambda x: x[0], tree)
            rest = jax.tree_util.tree_map(lambda x: x[1:], tree)
            out.append(
                jax.lax.scan(lambda acc, s, _a=a: (_a.merge(acc, s), None), first, rest)[0]
            )
        return tuple(out)

    jax.block_until_ready(fold(stacked))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fold(stacked)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def state_bytes(stacked) -> int:
    return sum(np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(stacked))


def time_ingest(analyzers, mesh, n_chunks: int = 5, chunk: int = 32) -> float:
    """sharded_ingest_fold over n_chunks chunks of chunk host partials."""
    from deequ_tpu.parallel import sharded_ingest_fold, stack_identity_states

    n_dev = int(mesh.devices.size) if mesh is not None else 1
    partials = build_shard_states(analyzers, chunk)
    flags = np.ones(chunk, dtype=bool)
    states = stack_identity_states(analyzers, n_dev)
    # compile
    states = sharded_ingest_fold(analyzers, mesh, states, partials, flags)
    jax.block_until_ready(states)
    t0 = time.perf_counter()
    for _ in range(n_chunks - 1):
        states = sharded_ingest_fold(analyzers, mesh, states, partials, flags)
    jax.block_until_ready(states)
    return (time.perf_counter() - t0) / (n_chunks - 1)


def scan_battery():
    """A lighter battery for end-to-end scan-throughput points (the full
    27-analyzer battery above stays for the merge timings)."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
        Sum,
    )

    return [
        Size(), Completeness("x0"), Mean("x0"), Sum("x0"), Minimum("x0"),
        Maximum("x1"), StandardDeviation("x1"), ApproxCountDistinct("x2"),
    ]


def scan_scaling(
    rows: int = 2_000_000,
    mesh_sizes=(1, 2, 4, 8),
    chaos: bool = True,
) -> dict:
    """ROADMAP item 2's acceptance artifact: end-to-end sharded-scan
    throughput at 1/2/4/8 devices (host-tier partials + mesh ingest fold +
    collective merge — the elastic path), plus a CHAOS point that kills
    one shard mid-stage and records the recovery wall-time and parity.

    Returns a JSON-able dict: ``points`` maps device count -> rows/s,
    ``chaos`` carries the kill-one-shard drill (recovery seconds = lossy
    minus clean wall time at the same mesh size; ``parity_ok`` asserts the
    degraded run's metrics equal the clean run's)."""
    import time as _time

    import numpy as np

    import jax

    from deequ_tpu.data import Dataset
    from deequ_tpu.parallel import make_mesh
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import RunMonitor

    rng = np.random.default_rng(7)
    data = Dataset.from_dict(
        {
            "x0": rng.normal(5, 2, rows),
            "x1": rng.normal(-3, 9, rows),
            "x2": rng.integers(0, 10_000, rows).astype(np.float64),
        }
    )
    from deequ_tpu.service.fleet import mesh_substrate

    analyzers = scan_battery()
    n_avail = len(jax.devices())
    batch = max(1 << 12, rows // 64)
    # the substrate rides every artifact: a CPU-virtual-device point must
    # never be misread as an accelerator point (r06's vs_baseline lesson)
    out: dict = {
        "rows": rows, "points": {}, "devices_available": n_avail,
        "mesh_substrate": mesh_substrate(),
    }
    clean_8 = None
    for n_dev in mesh_sizes:
        if n_dev > n_avail:
            continue
        mesh = make_mesh(n_dev)
        # warm (compile) pass, then the measured pass
        AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=batch, sharding=mesh,
            placement="host",
        )
        t0 = _time.perf_counter()
        ctx = AnalysisRunner.do_analysis_run(
            data, analyzers, batch_size=batch, sharding=mesh,
            placement="host",
        )
        seconds = _time.perf_counter() - t0
        out["points"][str(n_dev)] = rows / seconds
        if n_dev == max(s for s in mesh_sizes if s <= n_avail):
            clean_8 = (n_dev, seconds, ctx)
    if chaos and clean_8 is not None and clean_8[0] > 1:
        from deequ_tpu.reliability import FaultSpec, inject

        n_dev, clean_s, clean_ctx = clean_8
        mon = RunMonitor()
        t0 = _time.perf_counter()
        with inject(
            FaultSpec("sharded_fold", "mesh_loss", at=2, shard=n_dev - 1)
        ) as inj:
            lossy = AnalysisRunner.do_analysis_run(
                data, analyzers, batch_size=batch, sharding=make_mesh(n_dev),
                placement="host", monitor=mon,
            )
        lossy_s = _time.perf_counter() - t0
        parity_ok = True
        for a in analyzers:
            cv = clean_ctx.metric(a).value.get()
            lv = lossy.metric(a).value.get()
            if abs(cv - lv) > 1e-9 * max(1.0, abs(cv)):
                parity_ok = False
        out["chaos"] = {
            "mesh_devices": n_dev,
            "fault_fired": bool(inj.fired),
            "clean_s": round(clean_s, 3),
            "lossy_s": round(lossy_s, 3),
            "recovery_s": round(max(0.0, lossy_s - clean_s), 3),
            "shard_losses": mon.shard_losses,
            "mesh_reshards": mon.mesh_reshards,
            "salvaged_states": mon.salvaged_states,
            "parity_ok": parity_ok,
        }
    return out


def main() -> None:
    from deequ_tpu.parallel import make_mesh

    analyzers = battery()
    devices = jax.devices()
    print(f"{len(devices)} virtual devices, 27-analyzer battery")

    for n_shards in (8, 32, 96):
        stacked = build_shard_states(analyzers, n_shards)
        nbytes = state_bytes(stacked)
        seq = time_sequential_fold(analyzers, stacked)
        row = [f"shards={n_shards:4d} ({nbytes/1e6:6.1f}MB)  seq-fold {seq*1e3:7.1f}ms"]
        for n_dev in (2, 4, 8, 6):
            mesh = make_mesh(n_dev)
            t = time_merge(analyzers, mesh, stacked)
            kind = "butterfly" if (n_dev & (n_dev - 1)) == 0 else "all-gather"
            row.append(f"{n_dev}dev[{kind}] {t*1e3:7.1f}ms")
        print("  ".join(row))

    chunk = 32
    t1 = time_ingest(analyzers, make_mesh(1), chunk=chunk)
    t8 = time_ingest(analyzers, make_mesh(8), chunk=chunk)
    print(
        f"ingest-fold {chunk}-partial chunk: 1dev {t1*1e3:.1f}ms  8dev {t8*1e3:.1f}ms "
        f"(speedup {t1/t8:.2f}x)"
    )


if __name__ == "__main__":
    if "--stage-json" in sys.argv:
        # bench.py's mesh_scaling stage entry point: ONE parse-able JSON
        # line on stdout (scan-scaling points + the kill-one-shard chaos
        # drill), everything else on stderr
        import json

        idx = sys.argv.index("--stage-json")
        rows = (
            int(sys.argv[idx + 1])
            if len(sys.argv) > idx + 1 and sys.argv[idx + 1].isdigit()
            else 2_000_000
        )
        print(json.dumps(scan_scaling(rows)), flush=True)
    else:
        main()
