"""Summarize a trace artifact: critical path, top self-time spans,
degradation events.

Reads either a Chrome trace-event JSON (the ``/trace`` endpoint /
``bench.py`` per-stage artifacts) or a span JSONL journal (flight-recorder
dumps, ``/trace.jsonl``), rebuilds the span tree from the embedded
``span_id``/``parent_id`` refs, and prints the three things a post-mortem
opens with:

1. **Critical path** — from the longest root, the chain of child spans
   that dominates wall time (the "why was this run slow" answer);
2. **Top 5 spans by SELF time** — duration minus direct children, so a
   parent that merely waits on its children doesn't crowd out the phase
   actually burning the time;
3. **Degradation events** — every typed failure/failover/stall/drift/
   quarantine event in the artifact, in timestamp order (the "what went
   wrong, in what order" answer).

It also reports **span accounting**: root vs ORPHANED span counts (spans
whose ``parent_id`` names a span missing from the artifact). Orphans are
still summarized as effective roots, but a non-zero orphan count on a
merged multi-host artifact is the tell of a propagation regression — a
hop that dropped its trace context instead of carrying it.

Usage: ``python -m tools.trace_summarize ARTIFACT... [--top N]``. Each
ARTIFACT may be a Chrome trace JSON, a span JSONL file, or a DIRECTORY of
per-host ``spans-*.jsonl`` journals (merged onto one timeline via
:func:`deequ_tpu.observability.export.merge_journals`).
`tools/chaos_soak.py` runs this on the trace artifact every soak leaves
behind, so a chaos drill always ends with a readable incident summary.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Union

#: event names that mark a degradation (kept in sync with the emitting
#: sites in reliability/, service/ and the flight recorder)
DEGRADATION_EVENTS = frozenset(
    {
        "failure", "device_failover", "oom_bisect", "isolation_bisect",
        "analyzers_degraded", "scan_stall", "drift_degraded",
        "drift_repaired", "checkpoint_discarded", "repository_quarantined",
        "retry", "queued_past_deadline", "completed_late",
    }
)


def load_spans(path: str) -> List[Dict[str, Any]]:
    """Span dicts (trace.Span.to_dict shape) from any artifact format.
    A directory is a journal dir: every ``spans-*.jsonl`` inside is merged
    onto one rebased timeline first (cross-host clock skew matters for the
    degradation ordering). Both file formats open with "{", so detection
    parses: a single JSON document carrying ``traceEvents`` is a Chrome
    artifact; anything else is treated as one-record-per-line JSONL
    (journal or flight dump)."""
    if os.path.isdir(path):
        from deequ_tpu.observability.export import merge_journals

        journals = sorted(glob.glob(os.path.join(path, "spans-*.jsonl")))
        if not journals:
            return []
        return _spans_from_chrome(merge_journals(journals))
    with open(path) as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and "traceEvents" in doc:
        return _spans_from_chrome(doc)
    spans = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        record = json.loads(line)
        if record.get("flight_record"):
            continue  # dump header line
        spans.append(record)
    return spans


def _spans_from_chrome(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    spans: Dict[str, Dict[str, Any]] = {}
    pending_events: List[Dict[str, Any]] = []
    for ev in doc.get("traceEvents", ()):
        args = ev.get("args") or {}
        if ev.get("ph") == "X":
            span_id = args.get("span_id") or f"anon-{len(spans)}"
            attrs = {
                k: v for k, v in args.items()
                if k not in ("trace_id", "span_id", "parent_id", "status")
            }
            spans[span_id] = {
                "trace_id": args.get("trace_id"),
                "span_id": span_id,
                "parent_id": args.get("parent_id"),
                "name": ev.get("name", "?"),
                "kind": ev.get("cat", "span"),
                "start_ns": int(ev.get("ts", 0) * 1e3),
                "end_ns": int((ev.get("ts", 0) + ev.get("dur", 0)) * 1e3),
                "status": args.get("status", "ok"),
                "thread": ev.get("tid", 0),
                "attrs": attrs,
                "events": [],
            }
        elif ev.get("ph") == "i":
            pending_events.append(ev)
    for ev in pending_events:
        args = dict(ev.get("args") or {})
        owner = spans.get(args.pop("span_id", None))
        args.pop("trace_id", None)
        record = {
            "name": ev.get("name", "?"),
            "ts_ns": int(ev.get("ts", 0) * 1e3),
            "attrs": args,
        }
        if owner is not None:
            owner["events"].append(record)
        else:  # orphan instant event: synthesize a zero-length holder
            spans[f"orphan-{len(spans)}"] = {
                "trace_id": None, "span_id": f"orphan-{len(spans)}",
                "parent_id": None, "name": "(orphan events)",
                "kind": "event", "start_ns": record["ts_ns"],
                "end_ns": record["ts_ns"], "status": "ok", "thread": 0,
                "attrs": {}, "events": [record],
            }
    return list(spans.values())


def _dur_ns(span: Dict[str, Any]) -> int:
    end = span.get("end_ns")
    return max((end if end is not None else span["start_ns"]) - span["start_ns"], 0)


def _children_index(spans: List[Dict[str, Any]]) -> Dict[Optional[str], List[Dict[str, Any]]]:
    children: Dict[Optional[str], List[Dict[str, Any]]] = {}
    ids = {s["span_id"] for s in spans}
    for s in spans:
        parent = s.get("parent_id")
        # a parent outside the artifact (ring-evicted) makes this span an
        # effective root rather than an orphan
        key = parent if parent in ids else None
        children.setdefault(key, []).append(s)
    for group in children.values():
        group.sort(key=lambda s: s["start_ns"])
    return children


def critical_path(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The longest root, then greedily its longest child, recursively."""
    if not spans:
        return []
    children = _children_index(spans)
    roots = children.get(None, [])
    if not roots:
        return []
    path = [max(roots, key=_dur_ns)]
    while True:
        kids = children.get(path[-1]["span_id"], [])
        if not kids:
            return path
        path.append(max(kids, key=_dur_ns))


def self_times(spans: List[Dict[str, Any]]) -> List[tuple]:
    """(self_seconds, span) pairs, descending: duration minus direct
    children's durations (floored at 0 for overlapping children)."""
    children = _children_index(spans)
    out = []
    for s in spans:
        child_ns = sum(_dur_ns(c) for c in children.get(s["span_id"], ()))
        out.append((max(_dur_ns(s) - child_ns, 0) / 1e9, s))
    out.sort(key=lambda pair: -pair[0])
    return out


def span_accounting(spans: List[Dict[str, Any]]) -> Dict[str, int]:
    """Root / orphan / trace-id counts. An ORPHAN names a parent span the
    artifact doesn't contain — expected for a ring-evicted parent on one
    host, but on a merged multi-host artifact a systematic orphan count
    means a hop dropped its trace context (a propagation regression the
    tree rendering alone would hide, since orphans still render as
    roots)."""
    ids = {s["span_id"] for s in spans}
    roots = sum(1 for s in spans if s.get("parent_id") is None)
    orphans = sum(
        1 for s in spans
        if s.get("parent_id") is not None and s["parent_id"] not in ids
    )
    traces = {s.get("trace_id") for s in spans if s.get("trace_id")}
    return {
        "total": len(spans),
        "roots": roots,
        "orphans": orphans,
        "trace_ids": len(traces),
    }


def degradations(spans: List[Dict[str, Any]]) -> List[tuple]:
    """(ts_ns, owning span, event) for every degradation event, in order."""
    out = []
    for s in spans:
        for ev in s.get("events", ()):
            if ev.get("name") in DEGRADATION_EVENTS:
                out.append((ev.get("ts_ns", 0), s, ev))
    out.sort(key=lambda item: item[0])
    return out


def summarize(path: Union[str, Iterable[str]], top: int = 5) -> str:
    paths = [path] if isinstance(path, str) else list(path)
    spans: List[Dict[str, Any]] = []
    for p in paths:
        spans.extend(load_spans(p))
    lines = [f"trace summary: {', '.join(paths)} ({len(spans)} spans)"]
    if not spans:
        return "\n".join(lines + ["  (empty artifact)"])
    t0 = min(s["start_ns"] for s in spans)

    acct = span_accounting(spans)
    lines.append(
        f"span accounting: {acct['total']} spans, {acct['roots']} roots, "
        f"{acct['orphans']} orphaned (parent not in artifact), "
        f"{acct['trace_ids']} distinct trace_ids"
    )
    if acct["orphans"]:
        lines.append(
            "  WARNING: orphaned spans — a hop dropped its trace context "
            "or the parent was ring-evicted"
        )

    lines.append("critical path:")
    for depth, s in enumerate(critical_path(spans)):
        lines.append(
            f"  {'  ' * depth}{s['name']} [{s.get('kind', 'span')}] "
            f"{_dur_ns(s) / 1e9:.3f}s (status={s.get('status', 'ok')})"
        )

    lines.append(f"top {top} spans by self-time:")
    for self_s, s in self_times(spans)[:top]:
        lines.append(
            f"  {self_s:8.3f}s  {s['name']} [{s.get('kind', 'span')}] "
            f"trace={s.get('trace_id')}"
        )

    degrade = degradations(spans)
    lines.append(f"degradation events ({len(degrade)}):")
    if not degrade:
        lines.append("  (none — clean run)")
    for ts_ns, s, ev in degrade:
        attrs = ev.get("attrs") or {}
        detail = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        lines.append(
            f"  +{(ts_ns - t0) / 1e9:8.3f}s  {ev['name']} "
            f"(in {s['name']}) {detail}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "artifact", nargs="+",
        help="Chrome trace JSON, span JSONL, or a journal directory",
    )
    parser.add_argument("--top", type=int, default=5)
    args = parser.parse_args(argv)
    print(summarize(args.artifact, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
