"""Tenant-catalog soak: registered >> active tiering + live-edit drills
+ gated-vs-ungated throughput.

The ISSUE 17 acceptance drills, one invocation, one JSON line:

1. **Tiering soak** (``--registered R --active A``, R >> A): R tenants
   register suite documents in the catalog (cold tier: one versioned
   file each, no live state), then A of them go hot — sessions
   materialize from their documents on first ingest and stream
   micro-batches. The verdict pins that hot-tier cost tracks ACTIVE
   tenants (hot_count == A) while the registry holds all R, and that
   every fold succeeded.
2. **Mid-soak edit drill**: while the hot tenants stream, one tenant's
   document is re-registered with a different priority and a looser row
   gate. The next fold boundary must pick it up — no restart — pinned by
   the session's live priority, the reloads counter, and a frame that
   the OLD gate would have quarantined folding cleanly.
3. **Corrupt-edit drill**: a torn write lands as the same tenant's next
   version. The tenant must keep serving LAST-GOOD (folds keep
   succeeding, config unchanged) with EXACTLY one quarantine counter
   bump and the bad bytes preserved content-addressed in the
   ``.quarantine`` sidecar.
4. **Gated vs ungated throughput**: the same Arrow stream is folded
   through a session WITH a row gate (all rows conforming — the
   production steady state) and one WITHOUT; reports
   ``gated_throughput_fraction`` (gated MB/s / ungated MB/s — the
   bench_diff-gated scalar; acceptance floor 0.8) and pins the two
   sessions' cumulative metrics BIT-EXACT.

Exit code 0 iff every verdict holds, 1 on a failed verdict. ``--stage-
json`` is accepted for bench-stage symmetry (the JSON line is always
printed).

Usage::

    python -m tools.catalog_soak                     # CI-scaled defaults
    python -m tools.catalog_soak --registered 10000 --active 500
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict

DEFAULT_REGISTERED = 400
DEFAULT_ACTIVE = 24
DEFAULT_BATCHES = 3
DEFAULT_ROWS = 2048


def _doc(priority: str = "normal", max_len: int = 8) -> Dict:
    return {
        "checks": [{"name": "soak", "constraints": [
            {"kind": "complete", "column": "id"},
            {"kind": "min", "column": "v", "min": 0},
            {"kind": "size", "min": 1},
        ]}],
        "row_gate": {"columns": [
            {"name": "id", "type": "int", "nullable": False},
            {"name": "s", "type": "string", "max_length": max_len},
        ]},
        "priority": priority,
        "session": {"admission_block_s": 10.0},
    }


def _frame(rows: int, start: int = 0, s: str = "ok"):
    import numpy as np

    return {
        "id": np.arange(start, start + rows),
        "s": np.array([s] * rows),
        "v": np.ones(rows, dtype=np.float64),
    }


# ---------------------------------------------------------------------------
# drills 1-3: tiering + live edits over one service
# ---------------------------------------------------------------------------

def run_tiering_soak(
    registered: int = DEFAULT_REGISTERED,
    active: int = DEFAULT_ACTIVE,
    batches: int = DEFAULT_BATCHES,
    rows: int = DEFAULT_ROWS,
    workers: int = 4,
) -> Dict:
    import os
    import tempfile

    from deequ_tpu.service import TenantCatalog, VerificationService

    run_dir = tempfile.mkdtemp(prefix="catalog-soak-")
    catalog = TenantCatalog(os.path.join(run_dir, "catalog"))

    t0 = time.perf_counter()
    for i in range(registered):
        catalog.register(f"tenant-{i:06d}", _doc())
    register_s = time.perf_counter() - t0

    out: Dict = {
        "registered": registered,
        "active": active,
        "registers_per_s": round(registered / max(register_s, 1e-9), 1),
    }
    with VerificationService(
        workers=workers, max_queue_depth=max(64, active * 2),
        background_warm=False, catalog=catalog,
    ) as service:
        plane = service.catalog_plane
        plane.poll_s = 0.0  # fold boundaries poll every time: the edit
        #                     drills must not wait out a debounce window
        hot = [f"tenant-{i:06d}" for i in range(active)]
        t0 = time.perf_counter()
        sessions = {t: plane.ensure_session(t, "stream") for t in hot}
        folds_ok = 0
        for b in range(batches):
            for t in hot:
                r = sessions[t].ingest(_frame(rows, start=b * rows))
                folds_ok += r.status.name == "SUCCESS"
        soak_s = time.perf_counter() - t0
        out["sessions_per_s"] = round(
            active * batches / max(soak_s, 1e-9), 1
        )
        out["folds_ok"] = folds_ok
        out["hot_count"] = plane.hot_count()
        out["registered_count"] = catalog.registered_count()

        # -- drill 2: mid-soak edit, effective without restart ----------
        victim = hot[0]
        catalog.register(victim, _doc(priority="low", max_len=64))
        plane.on_fold_boundary(sessions[victim])
        long_frame = _frame(rows, start=batches * rows, s="x" * 32)
        edit_result = sessions[victim].ingest(long_frame)
        from deequ_tpu.service.scheduler import Priority

        out["edit_drill"] = {
            "priority_live": sessions[victim].priority is Priority.LOW,
            "loosened_gate_live": edit_result.status.name == "SUCCESS"
            and sessions[victim].rows_ingested
            == (batches + 1) * rows,
            "reloads": service.metrics.counter_value(
                "deequ_service_catalog_reloads_total", tenant=victim
            ),
        }
        out["edit_drill"]["ok"] = (
            out["edit_drill"]["priority_live"]
            and out["edit_drill"]["loosened_gate_live"]
            and out["edit_drill"]["reloads"] == 1
        )

        # -- drill 3: corrupt edit -> last-good, one quarantine bump ----
        tdir = os.path.join(
            catalog.path, f"t-{victim}"
        )
        torn = os.path.join(tdir, "v00000099.json")
        with open(torn, "w") as fh:
            fh.write('{"torn": tru')
        before = service.metrics.counter_value(
            "deequ_service_catalog_quarantined_total", tenant=victim
        )
        for _ in range(3):  # repeated boundaries must not re-quarantine
            plane.on_fold_boundary(sessions[victim])
        corrupt_result = sessions[victim].ingest(
            _frame(rows, start=(batches + 1) * rows, s="x" * 32)
        )
        bumps = service.metrics.counter_value(
            "deequ_service_catalog_quarantined_total", tenant=victim
        ) - before
        qdir = catalog.path + ".quarantine"
        preserved = [
            n for n in (os.listdir(qdir) if os.path.isdir(qdir) else [])
            if n.startswith("v00000099.json-")
        ]
        out["corrupt_drill"] = {
            "still_serving": corrupt_result.status.name == "SUCCESS",
            "config_kept": sessions[victim].priority is Priority.LOW,
            "quarantine_bumps": bumps,
            "preserved": len(preserved),
        }
        out["corrupt_drill"]["ok"] = (
            out["corrupt_drill"]["still_serving"]
            and out["corrupt_drill"]["config_kept"]
            and bumps == 1 and len(preserved) == 1
        )
    out["tiering_ok"] = (
        out["folds_ok"] == active * batches
        and out["hot_count"] == active
        and out["registered_count"] == registered
    )
    out["ok"] = bool(
        out["tiering_ok"] and out["edit_drill"]["ok"]
        and out["corrupt_drill"]["ok"]
    )
    return out


# ---------------------------------------------------------------------------
# drill 4: gated vs ungated throughput, bit-exact
# ---------------------------------------------------------------------------

def run_gate_throughput(
    batches: int = 24, rows: int = 65_536,
) -> Dict:
    """Fold the SAME clean Arrow stream through a gated and an ungated
    session; the fraction is the row gate's steady-state cost (every row
    conforming — the mask always runs, the split never does), and the
    cumulative metrics must be bit-exact between the two."""
    import numpy as np
    import pyarrow as pa

    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.ingest import RowGate, fold_stream, encode_ipc_stream
    from deequ_tpu.schema import RowLevelSchema
    from deequ_tpu.service import VerificationService

    rng = np.random.default_rng(11)
    payloads = [
        encode_ipc_stream(pa.table({
            "id": pa.array(np.arange(b * rows, (b + 1) * rows)),
            "v": pa.array(rng.normal(10.0, 2.0, size=rows)),
        }))
        for b in range(batches)
    ]
    total_mb = sum(len(p) for p in payloads) / 2**20

    def checks():
        return [Check(CheckLevel.ERROR, "gate-throughput")
                .has_size(lambda n: n > 0)
                .is_complete("id")
                .has_mean("v", lambda m: 0.0 < m < 20.0)
                .has_sum("v", lambda s: s > 0)]

    schema = RowLevelSchema().with_int_column("id", is_nullable=False)
    out: Dict = {"mb": round(total_mb, 1), "batches": batches}
    with VerificationService(workers=2, background_warm=False) as svc:
        gate = RowGate(schema, metrics=svc.metrics)
        timings = {}
        metrics = {}
        for name, kw in (
            ("ungated", {}), ("gated", {"row_gate": gate}),
        ):
            session = svc.session("tp", name, checks(), **kw)
            t0 = time.perf_counter()
            for payload in payloads:
                fold_stream(session, payload, source=name)
            timings[name] = time.perf_counter() - t0
            metrics[name] = {
                repr(a): m.value.get()
                for a, m in session.current().metrics.items()
                if m.value.is_success
            }
        out["ungated_mb_per_s"] = round(total_mb / timings["ungated"], 1)
        out["gated_mb_per_s"] = round(total_mb / timings["gated"], 1)
        out["gated_throughput_fraction"] = round(
            timings["ungated"] / timings["gated"], 3
        )
        out["bit_exact"] = metrics["gated"] == metrics["ungated"]
        out["gate_rows"] = svc.metrics.counter_value(
            "deequ_service_rowgate_rows_total", tenant="tp", dataset="gated"
        )
    out["ok"] = bool(
        out["bit_exact"] and out["gate_rows"] == batches * rows
    )
    return out


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--registered", type=int, default=DEFAULT_REGISTERED)
    parser.add_argument("--active", type=int, default=DEFAULT_ACTIVE)
    parser.add_argument("--batches", type=int, default=DEFAULT_BATCHES)
    parser.add_argument("--rows", type=int, default=DEFAULT_ROWS)
    parser.add_argument("--gate-batches", type=int, default=24)
    parser.add_argument("--gate-rows", type=int, default=65_536)
    parser.add_argument("--fraction-floor", type=float, default=0.8,
                        help="acceptance floor for gated/ungated MB/s "
                             "(0 disables; timing floors are meaningless "
                             "at toy sizes)")
    parser.add_argument("--stage-json", action="store_true",
                        help="bench-stage symmetry; JSON always prints")
    args = parser.parse_args(argv)

    t0 = time.perf_counter()
    summary: Dict = {
        "soak": run_tiering_soak(
            registered=args.registered, active=args.active,
            batches=args.batches, rows=args.rows,
        ),
        "gate": run_gate_throughput(
            batches=args.gate_batches, rows=args.gate_rows,
        ),
    }
    summary["gated_throughput_fraction"] = (
        summary["gate"]["gated_throughput_fraction"]
    )
    summary["seconds"] = round(time.perf_counter() - t0, 2)
    summary["ok"] = bool(
        summary["soak"]["ok"] and summary["gate"]["ok"]
        and summary["gated_throughput_fraction"] >= args.fraction_floor
    )
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
