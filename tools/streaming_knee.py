"""Streaming-knee bench: sessions/s with and without fold coalescing.

PR 9's soak found the streaming plane's ceiling at ~65 sessions/s — a
~50ms/fold FIXED cost (scheduler dispatch, state load→merge→persist, one
device program launch per session), not bandwidth. The coalescing plane
(`deequ_tpu.service.coalesce`) exists to kill that knee; this tool is its
acceptance instrument: the PR 9 soak workload re-measured at a grid of
{session count} x {micro-batch rows}, coalescing ON vs OFF, with a
metric-parity gate between the two runs of every point.

Usage::

    python -m tools.streaming_knee                       # full grid
    python -m tools.streaming_knee --stage-json          # bench-stage mode
    python -m tools.streaming_knee --sessions 100 --rows 4096

Each point drives `tools.ingest_soak.run_concurrency_soak` (the PR 9
instrument, unchanged: 8 workers, queue 256, bounded-admission
backpressure) against a fresh VerificationService; the coalescing knob is
flipped via ``DEEQU_TPU_COALESCE`` exactly as an operator would. The
parity gate folds one session per mode OUTSIDE the timing and compares
its cumulative metrics — coalesced and serial must agree bit-exactly on
the soak battery (identity-transparent states; the documented contract).
Exit code 0 iff every point completed with 0 sheds and parity held.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _parity_probe(rows: int, batches: int = 3) -> Dict:
    """Fold the same batches through one session with coalescing ON and
    one with it OFF; the cumulative metric maps must be IDENTICAL (the
    soak battery's states are identity-merge transparent, so the fast
    path's numpy merge reproduces the compiled merge bit-for-bit)."""
    import numpy as np

    from deequ_tpu.service import VerificationService
    from tools.ingest_soak import _build_table, _checks

    def run(coalesce: str) -> Dict[str, float]:
        os.environ["DEEQU_TPU_COALESCE"] = coalesce
        try:
            with VerificationService(
                workers=2, background_warm=False
            ) as svc:
                session = svc.session("parity", "knee", _checks())
                table = _build_table(rows * batches, seed=23)
                for b in range(batches):
                    session.ingest(table.slice(b * rows, rows))
                cum = session.current()
                return {
                    repr(a): m.value.get()
                    for a, m in cum.metrics.items()
                    if m.value.is_success
                }
        finally:
            os.environ.pop("DEEQU_TPU_COALESCE", None)

    on, off = run("1"), run("0")
    mismatches = sorted(k for k in on if on.get(k) != off.get(k))
    return {
        "metrics_compared": len(on),
        "bit_exact": not mismatches and set(on) == set(off),
        "mismatches": mismatches[:8],
    }


def run_knee_point(
    sessions: int,
    rows: int,
    coalesce: bool,
    *,
    batches: int = 2,
    workers: int = 8,
    queue_depth: int = 256,
    repeats: int = 1,
) -> Dict:
    """One soak point; ``repeats > 1`` reports the MEDIAN sessions/s run
    (the bench's house convention for jitter-prone wall-clock points —
    the coalesced legs finish in a few seconds each, so the median costs
    little; the serial legs take minutes at ~65 sessions/s and match the
    PR 9 published number single-shot)."""
    from tools.ingest_soak import run_concurrency_soak

    os.environ["DEEQU_TPU_COALESCE"] = "1" if coalesce else "0"
    runs = []
    try:
        for _ in range(max(1, repeats)):
            runs.append(run_concurrency_soak(
                sessions=sessions, batches=batches, rows=rows,
                workers=workers, queue_depth=queue_depth,
            ))
    finally:
        os.environ.pop("DEEQU_TPU_COALESCE", None)
    runs.sort(key=lambda r: r["sessions_per_s"])
    soak = runs[len(runs) // 2]
    return {
        "sessions": sessions,
        "rows": rows,
        "coalesce": coalesce,
        "sessions_per_s": soak["sessions_per_s"],
        # the min/max SPREAD across repeats, not just the median: the
        # coalesced plane has a known bimodal scheduling mode (~650-840
        # vs ~1100-1300 sessions/s, PR 10) and committed artifacts must
        # show it rather than leaving it folklore
        "sessions_per_s_min": runs[0]["sessions_per_s"],
        "sessions_per_s_max": runs[-1]["sessions_per_s"],
        "folds_per_s": soak["folds_per_s"],
        "shed": sum(r["shed"] for r in runs),
        "failed_folds": sum(r["failed_folds"] for r in runs),
        "ok": all(r["ok"] for r in runs)
        and all(r["shed"] == 0 for r in runs),
    }


def _subprocess_point(
    sessions: int, rows: int, coalesce: bool, repeats: int,
    batches: int, workers: int, queue_depth: int,
) -> Dict:
    """One soak point in a FRESH subprocess: a point's numbers must not
    depend on how much garbage (sessions, jobs, spans, jit caches) the
    previous points left in the interpreter — measured drift was tens of
    percent by the fourth in-process point. Same isolation discipline as
    the bench's grouping/mesh subprocess points."""
    import subprocess

    runs = []
    for _ in range(max(1, repeats)):
        argv = [
            sys.executable, "-m", "tools.streaming_knee", "--point",
            str(sessions), str(rows), "1" if coalesce else "0", "1",
            "--batches", str(batches), "--workers", str(workers),
            "--queue-depth", str(queue_depth),
        ]
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"knee point subprocess rc={proc.returncode}: "
                f"{proc.stderr[-400:]}"
            )
        runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    runs.sort(key=lambda r: r["sessions_per_s"])
    point = dict(runs[len(runs) // 2])  # fully-isolated median
    # spread across the isolated repeats (see run_knee_point: the bimodal
    # scheduling mode must be visible in committed artifacts)
    point["sessions_per_s_min"] = runs[0]["sessions_per_s"]
    point["sessions_per_s_max"] = runs[-1]["sessions_per_s"]
    point["shed"] = sum(r["shed"] for r in runs)
    point["ok"] = all(r["ok"] for r in runs)
    return point


def run_grid(
    session_counts=(100, 1000),
    row_counts=(4096, 65536),
    *,
    batches: int = 2,
    workers: int = 8,
    queue_depth: int = 256,
) -> Dict:
    """The ISSUE-10 acceptance grid; every point measures in a fresh
    subprocess (serial single-shot — it matches the PR 9 published
    number; coalesced median-of-3)."""
    points: List[Dict] = []
    for rows in row_counts:
        for sessions in session_counts:
            serial = _subprocess_point(
                sessions, rows, False, 1, batches, workers, queue_depth
            )
            coalesced = _subprocess_point(
                sessions, rows, True, 3, batches, workers, queue_depth
            )
            speedup = (
                coalesced["sessions_per_s"] / serial["sessions_per_s"]
                if serial["sessions_per_s"] else float("inf")
            )
            points.append({
                "sessions": sessions, "rows": rows,
                "serial_sessions_per_s": serial["sessions_per_s"],
                "coalesced_sessions_per_s": coalesced["sessions_per_s"],
                "coalesced_sessions_per_s_min":
                    coalesced["sessions_per_s_min"],
                "coalesced_sessions_per_s_max":
                    coalesced["sessions_per_s_max"],
                "speedup": round(speedup, 2),
                "shed": serial["shed"] + coalesced["shed"],
                "ok": serial["ok"] and coalesced["ok"],
            })
    parity = _parity_probe(rows=4096)
    # the acceptance cell: 1000 sessions x 4096-row micro-batches
    headline = next(
        (p for p in points if p["sessions"] == max(session_counts)
         and p["rows"] == min(row_counts)), points[-1],
    )
    return {
        "points": points,
        "parity": parity,
        "headline_sessions_per_s": headline["coalesced_sessions_per_s"],
        "headline_speedup": headline["speedup"],
        "ok": all(p["ok"] for p in points) and parity["bit_exact"],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--sessions", type=int, nargs="*",
                        default=[100, 1000])
    parser.add_argument("--rows", type=int, nargs="*",
                        default=[4096, 65536])
    parser.add_argument("--batches", type=int, default=2)
    parser.add_argument("--workers", type=int, default=8)
    parser.add_argument("--queue-depth", type=int, default=256)
    parser.add_argument("--stage-json", action="store_true",
                        help="emit ONLY the stage JSON on the last stdout "
                             "line (the bench subprocess protocol)")
    parser.add_argument("--point", nargs=4, metavar=("S", "R", "C", "N"),
                        help="internal: run ONE point (sessions rows "
                             "coalesce repeats) and print its JSON")
    args = parser.parse_args(argv)
    if args.point:
        sessions, rows, coalesce, repeats = (int(x) for x in args.point)
        point = run_knee_point(
            sessions, rows, bool(coalesce), batches=args.batches,
            workers=args.workers, queue_depth=args.queue_depth,
            repeats=repeats,
        )
        print(json.dumps(point), flush=True)
        return 0 if point["ok"] else 1
    summary = run_grid(
        tuple(args.sessions), tuple(args.rows),
        batches=args.batches, workers=args.workers,
        queue_depth=args.queue_depth,
    )
    print(json.dumps(summary), flush=True)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
