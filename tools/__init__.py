"""Operator tooling: benchmarks, scaling sweeps, chaos drills, DCN smoke.

Each module is a one-shot ``python -m tools.<name>`` entry point; see the
module docstrings for what they measure and emit.
"""
