"""Host-tier worker scaling sweep (VERDICT r5 ask #7).

Measures the host ingest tier's partial-computation throughput as a
function of its thread-pool size: the config-2-shaped scan battery
(moments + completeness + HLL + 2 KLL sketches over 4 numeric columns)
runs with ``DEEQU_TPU_HOST_TIER_WORKERS`` forced to each sweep point, so
the pool size is driven by measurement instead of ``os.cpu_count()``
faith. Emits a human table on stderr and one JSON line on stdout;
PERF.md's "Host-tier worker scaling" table records a run of this tool.

Run: ``python -m tools.host_tier_sweep [rows] [--workers 1,2,4,8]``
"""

from __future__ import annotations

import json
import os
import sys
import time


def battery():
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        KLLParameters,
        KLLSketch,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
        Sum,
    )

    analyzers = []
    for i in range(4):
        column = f"x{i}"
        analyzers += [
            Completeness(column), Mean(column), Sum(column),
            Minimum(column), Maximum(column), StandardDeviation(column),
        ]
    analyzers.append(ApproxCountDistinct("cat"))
    analyzers += [
        KLLSketch("x0", KLLParameters(2048, 0.64, 100)),
        KLLSketch("x1", KLLParameters(2048, 0.64, 100)),
    ]
    return analyzers


def build_data(rows: int):
    import numpy as np
    import pyarrow as pa

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(42)
    cols = {}
    for i in range(4):
        values = rng.normal(100 * i, 10, rows)
        cols[f"x{i}"] = pa.array(values, mask=rng.random(rows) < 0.05)
    cols["cat"] = pa.array(rng.integers(0, 100_000, rows))
    return Dataset.from_arrow(pa.table(cols))


def sweep(rows: int, workers_list, batch_size: int = 1 << 18) -> dict:
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import HOST_TIER_WORKERS_ENV, RunMonitor

    data = build_data(rows)
    analyzers = battery()
    results = {}
    prior = os.environ.get(HOST_TIER_WORKERS_ENV)
    try:
        for workers in workers_list:
            os.environ[HOST_TIER_WORKERS_ENV] = str(workers)
            # warm pass compiles the ingest-fold programs so the timed run
            # measures partial-computation scaling, not XLA compile
            AnalysisRunner.do_analysis_run(
                data, analyzers, batch_size=batch_size, placement="host"
            )
            monitor = RunMonitor()
            t0 = time.perf_counter()
            AnalysisRunner.do_analysis_run(
                data, analyzers, batch_size=batch_size, placement="host",
                monitor=monitor,
            )
            elapsed = time.perf_counter() - t0
            phases = {
                k: round(v, 2) for k, v in sorted(monitor.phase_seconds.items())
            }
            results[workers] = {
                "seconds": round(elapsed, 2),
                "rows_per_sec": round(rows / elapsed, 1),
                "phases": phases,
            }
            print(
                f"[sweep] workers={workers}: {elapsed:.2f}s "
                f"({rows / elapsed / 1e6:.2f}M rows/s) phases={phases}",
                file=sys.stderr, flush=True,
            )
    finally:
        if prior is None:
            os.environ.pop(HOST_TIER_WORKERS_ENV, None)
        else:
            os.environ[HOST_TIER_WORKERS_ENV] = prior
    base = results[workers_list[0]]["rows_per_sec"]
    for workers, row in results.items():
        row["speedup_vs_first"] = round(row["rows_per_sec"] / base, 2)
    return {
        "rows": rows, "batch_size": batch_size,
        "analyzers": len(analyzers), "sweep": results,
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    workers_list = [1, 2, 4, 8]
    if "--workers" in argv:
        i = argv.index("--workers")
        workers_list = [int(w) for w in argv[i + 1].split(",")]
        del argv[i:i + 2]
    rows = int(argv[0]) if argv else 4_000_000
    out = sweep(rows, workers_list)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
