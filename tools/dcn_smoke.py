"""Two-process DCN smoke test: the cross-host leg of SURVEY §2.9.

``parallel/__init__.py`` claims the sharded ingest fold and the collective
state merge run unchanged under ``jax.distributed`` with mesh axes spanning
hosts. This tool makes that claim executable on one machine (VERDICT r5
ask #8): it spawns TWO OS processes, each owning one CPU device,
``jax.distributed.initialize``s them into a single 2-device global mesh
(collectives ride the gloo cross-process backend — the DCN stand-in), runs

    ``sharded_ingest_fold``  ->  ``collective_merge_states``

over seeded host partials, and asserts both processes' merged metrics
equal the single-process host-tier fold of the same data.

Run: ``python -m tools.dcn_smoke`` (exit 0 = parity; 2 = environment
cannot run multi-process CPU collectives, reported as skipped).
The slow-marked ``tests/test_dcn_smoke.py`` drives this entry point.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

ROWS = 16_384
BATCHES = 8
SEED = 11


def _battery():
    from deequ_tpu.analyzers import (
        Completeness,
        Maximum,
        Mean,
        Size,
        StandardDeviation,
        Sum,
    )

    return [
        Size(), Completeness("x"), Mean("x"), Sum("x"), Maximum("x"),
        StandardDeviation("x"),
    ]


def _data(rows: int):
    import numpy as np

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(SEED)
    values = rng.normal(3.0, 2.0, size=rows)
    mask = rng.random(rows) < 0.1
    import pyarrow as pa

    return Dataset.from_arrow(pa.table({"x": pa.array(values, mask=mask)}))


def _metric_values(analyzers, states) -> dict:
    import jax

    out = {}
    for analyzer, state in zip(analyzers, states):
        metric = analyzer.compute_metric_from(jax.device_get(state))
        out[str(analyzer)] = float(metric.value.get())
    return out


def single_process_expected() -> dict:
    """The oracle: the ordinary single-process host-tier fold."""
    from deequ_tpu.runners import AnalysisRunner

    analyzers = _battery()
    ctx = AnalysisRunner.do_analysis_run(
        _data(ROWS), analyzers, batch_size=ROWS // BATCHES, placement="host"
    )
    return {
        str(a): float(ctx.metric_map[a].value.get()) for a in analyzers
    }


def worker(process_id: int, port: int) -> None:
    """One of the two distributed processes. Prints a JSON result line."""
    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=process_id,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.device_count()

    import numpy as np

    from deequ_tpu.analyzers.base import HostBatchContext
    from deequ_tpu.parallel import (
        collective_merge_states,
        make_mesh,
        sharded_ingest_fold,
        stack_identity_states,
    )

    analyzers = _battery()
    data = _data(ROWS)
    partials = []
    for index, batch in enumerate(
        data.batches(ROWS // BATCHES, pad_to_batch_size=False)
    ):
        ctx = HostBatchContext(batch, batch_index=index)
        partials.append(tuple(a.host_partial(ctx) for a in analyzers))
    stacked = tuple(
        jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[p[i] for p in partials],
        )
        for i in range(len(analyzers))
    )
    flags = np.ones(len(partials), dtype=bool)

    mesh = make_mesh()  # ALL global devices: one per process -> DCN axis
    states = stack_identity_states(analyzers, mesh.devices.size)
    folded = sharded_ingest_fold(analyzers, mesh, states, stacked, flags)
    merged = collective_merge_states(analyzers, mesh, folded)
    print(
        json.dumps(
            {
                "process": process_id,
                "devices": jax.device_count(),
                "values": _metric_values(analyzers, merged),
            }
        ),
        flush=True,
    )


def main() -> int:
    if "--worker" in sys.argv:
        worker(
            int(sys.argv[sys.argv.index("--worker") + 1]),
            int(sys.argv[sys.argv.index("--port") + 1]),
        )
        return 0

    expected = single_process_expected()
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # one CPU device per process: the mesh axis then SPANS processes, so
    # every collective crosses the process boundary — the DCN path
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tools.dcn_smoke", "--worker", str(i),
             "--port", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    results, errors = [], []
    for proc in procs:
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        if proc.returncode == 0:
            results.append(json.loads(out.decode().strip().splitlines()[-1]))
        else:
            errors.append(err.decode()[-500:])
    if errors or len(results) != 2:
        reason = (errors or ["missing worker output"])[0]
        print(json.dumps({"ok": False, "skipped": True, "reason": reason}))
        return 2
    tol = 1e-9
    mismatches = []
    for result in results:
        for key, want in expected.items():
            got = result["values"][key]
            if abs(got - want) > tol * max(1.0, abs(want)):
                mismatches.append((result["process"], key, got, want))
    ok = not mismatches
    print(
        json.dumps(
            {
                "ok": ok,
                "skipped": False,
                "processes": 2,
                "analyzers": len(expected),
                "mismatches": mismatches,
                "expected": expected,
            }
        )
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
