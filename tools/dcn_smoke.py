"""Two-process DCN smoke test: the cross-host leg of SURVEY §2.9.

``parallel/__init__.py`` claims the sharded ingest fold and the collective
state merge run unchanged under ``jax.distributed`` with mesh axes spanning
hosts. This tool makes that claim executable on one machine (VERDICT r5
ask #8): it spawns TWO OS processes, each owning one CPU device,
``jax.distributed.initialize``s them into a single 2-device global mesh
(collectives ride the gloo cross-process backend — the DCN stand-in), runs

    ``sharded_ingest_fold``  ->  ``collective_merge_states``

over seeded host partials, and asserts both processes' merged metrics
equal the single-process host-tier fold of the same data.

Run: ``python -m tools.dcn_smoke`` (exit 0 = parity; 2 = environment
cannot run multi-process CPU collectives, reported as skipped).
The slow-marked ``tests/test_dcn_smoke.py`` drives this entry point.

Kill-one-process drill (``python -m tools.dcn_smoke --drill kill-one``):
the PROCESS-loss leg of the elastic mesh contract. Both workers fold the
first half of the batches over the 2-device DCN mesh, then the parent
SIGKILLs worker 1 mid-fold. The survivor detects the dead peer (its next
cross-process step fails or exceeds a deadline), salvages its OWN shard's
folded state (the peer's shard died with the peer), replays exactly the
batch slices the dead shard owned from its local data copy — eager
host-side semigroup folds, no collectives, because the mesh is gone — and
completes the fold. Exit 0 iff the survivor's salvaged metrics equal the
single-process oracle to 1e-9 relative (the same parity bar as the main
smoke).

This CLI is a THIN wrapper: the worker-side mechanics (bring-up env,
partial stacking, deadline-guarded folds, salvage + replay) live in
``deequ_tpu.parallel.dcn`` — the library the cluster tier composes — and
this module only wires them to the spawn/barrier/JSON protocol.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

ROWS = 16_384
BATCHES = 8
SEED = 11


def _battery():
    from deequ_tpu.analyzers import (
        Completeness,
        Maximum,
        Mean,
        Size,
        StandardDeviation,
        Sum,
    )

    return [
        Size(), Completeness("x"), Mean("x"), Sum("x"), Maximum("x"),
        StandardDeviation("x"),
    ]


def _data(rows: int):
    import numpy as np

    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(SEED)
    values = rng.normal(3.0, 2.0, size=rows)
    mask = rng.random(rows) < 0.1
    import pyarrow as pa

    return Dataset.from_arrow(pa.table({"x": pa.array(values, mask=mask)}))


def _metric_values(analyzers, states) -> dict:
    import jax

    out = {}
    for analyzer, state in zip(analyzers, states):
        metric = analyzer.compute_metric_from(jax.device_get(state))
        out[str(analyzer)] = float(metric.value.get())
    return out


def single_process_expected() -> dict:
    """The oracle: the ordinary single-process host-tier fold."""
    from deequ_tpu.runners import AnalysisRunner

    analyzers = _battery()
    ctx = AnalysisRunner.do_analysis_run(
        _data(ROWS), analyzers, batch_size=ROWS // BATCHES, placement="host"
    )
    return {
        str(a): float(ctx.metric_map[a].value.get()) for a in analyzers
    }


def worker(process_id: int, port: int) -> None:
    """One of the two distributed processes. Prints a JSON result line."""
    import jax

    from deequ_tpu.parallel import (
        collective_merge_states,
        make_mesh,
        stack_identity_states,
    )
    from deequ_tpu.parallel.dcn import (
        fold_partials,
        host_partials,
        initialize_dcn,
    )

    initialize_dcn(f"127.0.0.1:{port}", num_processes=2,
                   process_id=process_id)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.device_count()

    analyzers = _battery()
    partials = host_partials(analyzers, _data(ROWS), ROWS // BATCHES)

    mesh = make_mesh()  # ALL global devices: one per process -> DCN axis
    states = stack_identity_states(analyzers, mesh.devices.size)
    folded = fold_partials(analyzers, mesh, states, partials)
    merged = collective_merge_states(analyzers, mesh, folded)
    print(
        json.dumps(
            {
                "process": process_id,
                "devices": jax.device_count(),
                "values": _metric_values(analyzers, merged),
            }
        ),
        flush=True,
    )


def drill_worker(process_id: int, port: int, barrier_dir: str) -> None:
    """One worker of the kill-one drill. Worker 1 is SIGKILLed by the
    parent after the first chunk folds; worker 0 survives, salvages and
    finishes. Prints a JSON result line (worker 0 only)."""
    import time

    import jax

    from deequ_tpu.parallel import (
        collective_merge_states,
        make_mesh,
        stack_identity_states,
    )
    from deequ_tpu.parallel.dcn import (
        DEFAULT_DCN_DEADLINE_S,
        fold_partials,
        host_partials,
        initialize_dcn,
        replay_partials,
        salvage_local_states,
        with_deadline,
    )

    initialize_dcn(f"127.0.0.1:{port}", num_processes=2,
                   process_id=process_id)

    analyzers = _battery()
    partials = host_partials(analyzers, _data(ROWS), ROWS // BATCHES)

    half = len(partials) // 2
    mesh = make_mesh()
    n_dev = int(mesh.devices.size)  # 2: one device per process
    local = half // n_dev
    states = stack_identity_states(analyzers, n_dev)

    # chunk 1 folds on the healthy mesh
    states = fold_partials(analyzers, mesh, states, partials[:half])
    #: batch indices THIS process's device (shard = process_id) folded
    owned = set(range(process_id * local, (process_id + 1) * local))
    open(os.path.join(barrier_dir, f"w{process_id}-fold1"), "w").write("ok")

    if process_id == 1:
        time.sleep(120)  # the parent SIGKILLs us here
        os._exit(3)  # noqa: SLF001 - never reached in the drill

    # worker 0: wait until the parent confirms the kill, then proceed
    killed = os.path.join(barrier_dir, "killed")
    for _ in range(600):
        if os.path.exists(killed):
            break
        time.sleep(0.1)

    # attempt chunk 2 + the collective merge against the dead peer: either
    # step failing (or hanging past the deadline) IS the loss signal
    salvage_reason = None

    folded2, err, timed_out = with_deadline(
        lambda: fold_partials(analyzers, mesh, states, partials[half:]),
        DEFAULT_DCN_DEADLINE_S,
    )
    if folded2 is not None:
        states = folded2
        owned |= set(range(half + 0 * local, half + local))
        merged, err, timed_out = with_deadline(
            lambda: collective_merge_states(analyzers, mesh, states),
            DEFAULT_DCN_DEADLINE_S,
        )
        if merged is not None:
            # the dead peer did not block the merge (environment folded it
            # locally) — still a pass, but record that no salvage was needed
            print(json.dumps({
                "process": 0, "salvaged": False,
                "values": _metric_values(analyzers, merged),
            }), flush=True)
            os._exit(0)  # noqa: SLF001 - skip wedged distributed teardown
        salvage_reason = (
            "merge timed out" if timed_out else f"merge failed: {err}"
        )
    else:
        salvage_reason = (
            "fold timed out" if timed_out else f"fold failed: {err}"
        )

    # SALVAGE: this process's addressable shard of the folded states is the
    # surviving state; every batch it does NOT cover replays from the local
    # data copy with eager host-side semigroup folds (the mesh is gone)
    salvaged = salvage_local_states(states)
    replay = [i for i in range(len(partials)) if i not in owned]
    finished = replay_partials(analyzers, salvaged, partials, replay)
    print(json.dumps({
        "process": 0, "salvaged": True, "salvage_reason": salvage_reason,
        "replayed_batches": len(replay),
        "values": _metric_values(analyzers, finished),
    }), flush=True)
    os._exit(0)  # noqa: SLF001 - the distributed runtime lost its peer;
    # a normal exit would hang in teardown barriers


def _free_port() -> int:
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        return probe.getsockname()[1]


def _spawn_workers(port: int, extra_argv=()) -> list:
    from deequ_tpu.parallel.dcn import dcn_worker_env

    return [
        subprocess.Popen(
            [sys.executable, "-m", "tools.dcn_smoke", "--worker", str(i),
             "--port", str(port), *extra_argv],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=dcn_worker_env(),
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]


def run_kill_one_drill() -> int:
    """Parent side of the kill-one drill (see module docstring)."""
    import signal
    import tempfile
    import time

    expected = single_process_expected()
    port = _free_port()
    barrier_dir = tempfile.mkdtemp(prefix="dcn-drill-")
    procs = _spawn_workers(
        port, ["--drill", "kill-one", "--barrier", barrier_dir]
    )
    # wait for worker 1's first fold, then SIGKILL it mid-fold
    w1_folded = os.path.join(barrier_dir, "w1-fold1")
    deadline = time.monotonic() + 240
    while not os.path.exists(w1_folded):
        if time.monotonic() > deadline or any(
            p.poll() is not None for p in procs
        ):
            for p in procs:
                p.kill()
            errs = [p.communicate()[1].decode()[-400:] for p in procs]
            print(json.dumps({
                "ok": False, "skipped": True, "drill": "kill-one",
                "reason": f"workers never reached fold 1: {errs}",
            }))
            return 2
        time.sleep(0.1)
    procs[1].send_signal(signal.SIGKILL)
    procs[1].wait()
    open(os.path.join(barrier_dir, "killed"), "w").write("ok")

    try:
        out, err = procs[0].communicate(timeout=300)
    except subprocess.TimeoutExpired:
        procs[0].kill()
        out, err = procs[0].communicate()
    if procs[0].returncode != 0:
        print(json.dumps({
            "ok": False, "skipped": True, "drill": "kill-one",
            "reason": f"survivor rc={procs[0].returncode}: "
                      f"{err.decode()[-400:]}",
        }))
        return 2
    result = json.loads(out.decode().strip().splitlines()[-1])
    tol = 1e-9
    mismatches = [
        (key, result["values"][key], want)
        for key, want in expected.items()
        if abs(result["values"][key] - want) > tol * max(1.0, abs(want))
    ]
    ok = not mismatches
    print(json.dumps({
        "ok": ok, "skipped": False, "drill": "kill-one",
        "salvaged": result.get("salvaged"),
        "salvage_reason": result.get("salvage_reason"),
        "replayed_batches": result.get("replayed_batches"),
        "mismatches": mismatches, "expected": expected,
    }))
    return 0 if ok else 1


def main() -> int:
    if "--worker" in sys.argv:
        if "--drill" in sys.argv:
            drill_worker(
                int(sys.argv[sys.argv.index("--worker") + 1]),
                int(sys.argv[sys.argv.index("--port") + 1]),
                sys.argv[sys.argv.index("--barrier") + 1],
            )
            return 0
        worker(
            int(sys.argv[sys.argv.index("--worker") + 1]),
            int(sys.argv[sys.argv.index("--port") + 1]),
        )
        return 0
    if "--drill" in sys.argv:
        return run_kill_one_drill()

    expected = single_process_expected()
    procs = _spawn_workers(_free_port())
    results, errors = [], []
    for proc in procs:
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        if proc.returncode == 0:
            results.append(json.loads(out.decode().strip().splitlines()[-1]))
        else:
            errors.append(err.decode()[-500:])
    if errors or len(results) != 2:
        reason = (errors or ["missing worker output"])[0]
        print(json.dumps({"ok": False, "skipped": True, "reason": reason}))
        return 2
    tol = 1e-9
    mismatches = []
    for result in results:
        for key, want in expected.items():
            got = result["values"][key]
            if abs(got - want) > tol * max(1.0, abs(want)):
                mismatches.append((result["process"], key, got, want))
    ok = not mismatches
    print(
        json.dumps(
            {
                "ok": ok,
                "skipped": False,
                "processes": 2,
                "analyzers": len(expected),
                "mismatches": mismatches,
                "expected": expected,
            }
        )
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
