"""Render the self-tuning plane's state: the active substrate profile
plus the live controller's experiments and decision history.

Two consumers:

- operators: ``python -m tools.tuning_report`` loads THIS substrate's
  persisted calibration profile (the same loader the service boots
  through, including checksum verification) and prints probes + derived
  knob values against their static defaults;
- chaos_soak: ``controller_report(service)`` renders the in-process
  controller — incumbent vs candidate rates per experiment, the
  promotion/demotion history, the ``deequ_service_tuning_*`` counters —
  into the soak summary, so every soak run documents what the tuner did
  to it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def profile_report(directory: Optional[str] = None) -> Dict[str, Any]:
    """This substrate's profile as a report dict (CorruptStateError
    surfaces as a quarantine note, exactly like the service boot)."""
    from deequ_tpu.exceptions import CorruptStateError
    from deequ_tpu.tuning import knobs, profile as prof

    out: Dict[str, Any] = {
        "substrate": prof.substrate_key(),
        "fingerprint": prof.substrate_fingerprint(),
        "profile_dir": directory or prof.profile_dir(),
    }
    try:
        loaded = prof.load_profile(directory)
    except CorruptStateError as exc:
        out["profile"] = None
        out["quarantined"] = str(exc)
        return out
    if loaded is None:
        out["profile"] = None
        return out
    out["profile"] = {
        "created_at": loaded.created_at,
        "calibration_wall_s": loaded.calibration_wall_s,
        "probes": loaded.probes,
        "knobs": {
            name: {"calibrated": value, "static": knobs.static_value(name)}
            for name, value in sorted(loaded.knob_values.items())
            if name in knobs.REGISTRY
        },
    }
    return out


def controller_report(service) -> Dict[str, Any]:
    """The live controller's decisions + the tuning export series of one
    in-process VerificationService (chaos_soak's summary hook)."""
    controller = getattr(service, "tuning_controller", None)
    metrics = getattr(service, "metrics", None)
    out: Dict[str, Any] = {"enabled": controller is not None}
    if metrics is not None:
        out["series"] = {
            name: metrics.counter_value(name)
            for name in (
                "deequ_service_tuning_proposals_total",
                "deequ_service_tuning_promotions_total",
                "deequ_service_tuning_demotions_total",
                "deequ_service_tuning_shadow_folds_total",
            )
        }
    if controller is not None:
        out.update(controller.snapshot())
    return out


def bench_point(sessions: int = 96, rows: int = 4096,
                group_rows: int = 1 << 19,
                group_cardinality: int = 1 << 10) -> Dict[str, Any]:
    """One tuned-vs-static comparison point under the CURRENT env:
    streaming sessions/s (the knee workload's shape: N sessions x one
    micro-batch) and grouping rows/s (a warm Uniqueness run). bench.py's
    calibration stage runs this twice in detached subprocesses —
    DEEQU_TPU_AUTOTUNE=0 vs the calibrated profile — and bench_diff
    gates tuned >= static within the band."""
    import os
    import time

    import numpy as np
    import pyarrow as pa

    from deequ_tpu.analyzers import Uniqueness
    from deequ_tpu.checks import Check, CheckLevel
    from deequ_tpu.data import Dataset
    from deequ_tpu.runners.analysis_runner import AnalysisRunner
    from deequ_tpu.service import VerificationService
    from deequ_tpu.tuning import knobs

    rng = np.random.default_rng(0xBE9C4)
    checks = [
        Check(CheckLevel.ERROR, "tuning point")
        .is_complete("x")
        .has_mean("y", lambda m: -5.0 < m < 5.0)
    ]
    table = pa.table({
        "x": rng.normal(size=rows),
        "y": rng.normal(size=rows),
    })
    with VerificationService(background_warm=False) as service:
        warm = service.session("tuning-point-warm", "stream", checks)
        # Warm BOTH fold routes before timing (forced via the override
        # knob, saved/restored): the arms may settle on different routes
        # — the calibrated router flips to device as soon as the host
        # EWMA absorbs its first-fold setup cost, the static 20ms fixed
        # seed never does — and whichever route the timed loop takes
        # must not pay its one-time program compile inside the window.
        route_env = "DEEQU_TPU_FAST_PATH_MAX_ROWS"
        saved = os.environ.get(route_env)
        try:
            os.environ[route_env] = "0"  # force the device route
            warm.ingest(table, timeout=120)
            os.environ[route_env] = str(1 << 30)  # force the host route
            warm.ingest(table, timeout=120)
        finally:
            if saved is None:
                os.environ.pop(route_env, None)
            else:
                os.environ[route_env] = saved
        warm.ingest(table, timeout=120)  # settle the model's own route
        t0 = time.perf_counter()
        for i in range(sessions):
            s = service.session(f"tuning-point-{i}", "stream", checks)
            s.ingest(table, timeout=120)
        streaming = sessions / (time.perf_counter() - t0)

    gdata = Dataset.from_dict({
        "k": rng.integers(0, group_cardinality, size=group_rows),
    })
    analyzers = [Uniqueness(["k"])]
    AnalysisRunner.do_analysis_run(gdata, analyzers)  # warm
    t0 = time.perf_counter()
    AnalysisRunner.do_analysis_run(gdata, analyzers)
    grouping = group_rows / (time.perf_counter() - t0)

    return {
        "sessions": sessions,
        "rows": rows,
        "group_rows": group_rows,
        "sessions_per_s": streaming,
        "grouping_rows_per_s": grouping,
        "autotune": knobs.autotune_enabled(),
        "tuned_knobs": sorted(knobs.tuned_snapshot()),
    }


def _fmt(value: Any) -> str:
    if isinstance(value, float):
        return f"{value:.4g}"
    return str(value)


def render_text(report: Dict[str, Any]) -> str:
    lines: List[str] = []
    sub = report["substrate"]
    lines.append(
        f"substrate {report['fingerprint']}: {sub['backend']} "
        f"{sub['device_kind']} x{sub['chip_count']} on {sub['host']}"
    )
    lines.append(f"profile dir: {report['profile_dir']}")
    if report.get("quarantined"):
        lines.append(f"PROFILE QUARANTINED: {report['quarantined']}")
        return "\n".join(lines)
    profile = report.get("profile")
    if profile is None:
        lines.append("no profile for this substrate "
                     "(run python -m deequ_tpu.tuning.calibrate)")
        return "\n".join(lines)
    lines.append(
        f"calibrated in {profile['calibration_wall_s']:.2f}s; "
        f"{len(profile['probes'])} probes"
    )
    lines.append(f"{'knob':34s} {'calibrated':>14s} {'static':>14s}")
    for name, row in profile["knobs"].items():
        lines.append(
            f"{name:34s} {_fmt(row['calibrated']):>14s} "
            f"{_fmt(row['static']):>14s}"
        )
    lines.append("probes:")
    for name, value in sorted(profile["probes"].items()):
        lines.append(f"  {name:34s} {_fmt(value):>14s}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.tuning_report",
        description=(
            "Show this substrate's calibration profile (and, via "
            "--snapshot, a serialized controller state)"
        ),
    )
    parser.add_argument("--dir", default=None,
                        help="profile directory (default: beside XLA cache)")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON")
    parser.add_argument("--snapshot", default=None,
                        help="also render a controller snapshot JSON file "
                             "(as written by chaos_soak)")
    parser.add_argument("--bench-point", action="store_true",
                        help="measure one streaming+grouping throughput "
                             "point under the current env and print it as "
                             "a JSON line (bench.py's tuned-vs-static probe)")
    parser.add_argument("--sessions", type=int, default=96,
                        help="streaming sessions for --bench-point")
    parser.add_argument("--group-rows", type=int, default=1 << 19,
                        help="grouping rows for --bench-point")
    args = parser.parse_args(argv)

    if args.bench_point:
        point = bench_point(sessions=args.sessions,
                            group_rows=args.group_rows)
        print(json.dumps(point, sort_keys=True))
        return 0

    report = profile_report(args.dir)
    if args.snapshot:
        with open(args.snapshot, "r", encoding="utf-8") as fh:
            report["controller"] = json.load(fh)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True, default=str))
    else:
        print(render_text(report))
        controller = report.get("controller")
        if controller:
            print(f"controller: {len(controller.get('decisions', []))} "
                  f"decision(s), {len(controller.get('tuned', {}))} tuned "
                  "knob(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
