"""Grouping-engine sweep: device frequency table vs host group-by/spill.

Measures rows/s, peak RSS and metric parity for the grouping analyzers
(Uniqueness / CountDistinct / Entropy) across distinct-key counts — the
before/after evidence for ROADMAP item 3 (the PERF.md "Grouping engine"
table and the bench ``grouping`` stage both come from here).

Every measured point runs in a FRESH subprocess so ``ru_maxrss`` is the
point's own peak, not the sweep driver's high-water mark; the parent
compares the two engines' metric JSON for bit-exact equality (python
float repr round-trips exactly through json).

Usage:
  python -m tools.grouping_sweep                      # default sweep
  python -m tools.grouping_sweep --rows 25000000 --distinct 3571428
  python -m tools.grouping_sweep --markdown           # PERF.md rows
  python -m tools.grouping_sweep --point --rows N --distinct D \
      --engine device|host                            # one in-process point
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BATTERY_COLS = ["k"]


def measure_point(rows: int, distinct: int, engine: str, seed: int = 1) -> dict:
    """One in-process measurement. ``engine="device"`` routes through the
    device frequency table (placement=device); ``engine="host"`` pins the
    pre-engine default: host group-by accumulator (+ _SpillStore when the
    budget forces it), placement=host."""
    if engine == "device":
        os.environ.pop("DEEQU_TPU_DEVICE_FREQ", None)
        # measure the raw table curve: without this, low-cardinality
        # points would be silently re-routed to the host group-by by the
        # pre-routing probe and the sweep would compare host against host
        os.environ["DEEQU_TPU_FREQ_HOST_ROUTE"] = "0"
        placement = "device"
    elif engine == "host":
        os.environ["DEEQU_TPU_DEVICE_FREQ"] = "0"
        placement = "host"
    else:
        raise SystemExit(f"unknown engine {engine!r}")

    import numpy as np

    from deequ_tpu.analyzers import CountDistinct, Entropy, Uniqueness
    from deequ_tpu.data import Dataset
    from deequ_tpu.runners import AnalysisRunner
    from deequ_tpu.runners.engine import RunMonitor

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, distinct, rows)
    data = Dataset.from_dict({"k": keys})
    battery = [Uniqueness(["k"]), CountDistinct(["k"]), Entropy("k")]

    # compile warm-up, then measure the warm rate (the bench convention
    # for device stages). The device path warms on the FULL dataset: the
    # frequency-table state shapes (slots, buffer) are sized from the run's
    # row count, so a smaller warm-up would compile the wrong program and
    # the timed run would measure XLA compile, not throughput. The host
    # path has no shape-dependent compile — a small slice warms its
    # allocator pools.
    if engine == "device":
        warm = data
    else:
        warm = Dataset.from_dict({"k": keys[: min(rows, 1 << 20)]})
    AnalysisRunner.do_analysis_run(
        warm, battery, batch_size=1 << 20, placement=placement
    )

    mon = RunMonitor()
    t0 = time.perf_counter()
    ctx = AnalysisRunner.do_analysis_run(
        data, battery, batch_size=1 << 20, placement=placement, monitor=mon
    )
    elapsed = time.perf_counter() - t0
    peak_rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    metrics = {a.name: ctx.metric(a).value.get() for a in battery}
    return {
        "engine": engine,
        "rows": rows,
        "distinct_requested": distinct,
        "distinct": metrics["CountDistinct"],
        "seconds": round(elapsed, 3),
        "rows_per_sec": round(rows / elapsed, 1),
        "peak_rss_gb": round(peak_rss_gb, 3),
        "device_freq_sets": mon.device_freq_sets,
        "freq_overflow_fallbacks": mon.freq_overflow_fallbacks,
        "metrics": metrics,
    }


def subprocess_point(
    rows: int, distinct: int, engine: str, seed: int = 1,
    timeout: float = 900.0, extra_env: dict = None,
) -> dict:
    """Measure one point in a fresh process (clean ru_maxrss). THE one copy
    of the point-subprocess protocol — bench.py's grouping stage calls this
    too, so CLI flags / output format can never drift between the two."""
    cmd = [
        sys.executable, "-m", "tools.grouping_sweep", "--point",
        "--rows", str(rows), "--distinct", str(distinct),
        "--engine", engine, "--seed", str(seed),
    ]
    env = dict(os.environ)
    env.pop("DEEQU_TPU_DEVICE_FREQ", None)
    env.update(extra_env or {})
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"grouping point {engine} rows={rows} distinct={distinct} "
            f"failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def sweep(rows: int, distincts: list, markdown: bool, seed: int) -> None:
    points = []
    for d in distincts:
        dev = subprocess_point(rows, d, "device", seed)
        host = subprocess_point(rows, d, "host", seed)
        exact = dev["metrics"] == host["metrics"]
        points.append((d, dev, host, exact))
        print(
            f"distinct={d:>9,}  device {dev['rows_per_sec']/1e6:7.2f}M rows/s "
            f"rss {dev['peak_rss_gb']:5.2f}GB (fallbacks="
            f"{dev['freq_overflow_fallbacks']})  |  host "
            f"{host['rows_per_sec']/1e6:7.2f}M rows/s rss "
            f"{host['peak_rss_gb']:5.2f}GB  |  x"
            f"{dev['rows_per_sec']/host['rows_per_sec']:.1f} "
            f"{'bit-exact' if exact else 'METRIC MISMATCH!'}",
            file=sys.stderr, flush=True,
        )
        if not exact:
            raise SystemExit(f"metric mismatch at distinct={d}: {dev['metrics']} != {host['metrics']}")
    if markdown:
        print("| distinct keys | device rows/s | device peak RSS | host rows/s | host peak RSS | speedup |")
        print("|--------------:|--------------:|----------------:|------------:|--------------:|--------:|")
        for d, dev, host, _ in points:
            print(
                f"| {d:,} | {dev['rows_per_sec']/1e6:.1f}M | "
                f"{dev['peak_rss_gb']:.2f}GB | {host['rows_per_sec']/1e6:.2f}M | "
                f"{host['peak_rss_gb']:.2f}GB | "
                f"{dev['rows_per_sec']/host['rows_per_sec']:.1f}x |"
            )
    else:
        print(json.dumps({
            "rows": rows,
            "points": [
                {"distinct": d, "device": dev, "host": host, "bit_exact": exact}
                for d, dev, host, exact in points
            ],
        }))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=25_000_000)
    ap.add_argument("--distinct", type=str, default="100,10000,1000000,3571428,5000000")
    ap.add_argument("--engine", choices=["device", "host"], default="device")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--point", action="store_true", help="one in-process point (internal)")
    ap.add_argument("--markdown", action="store_true", help="emit the PERF.md table")
    args = ap.parse_args()
    if args.point:
        distinct = int(args.distinct.split(",")[0])
        print(json.dumps(measure_point(args.rows, distinct, args.engine, args.seed)), flush=True)
        return
    sweep(args.rows, [int(d) for d in args.distinct.split(",")], args.markdown, args.seed)


if __name__ == "__main__":
    main()
