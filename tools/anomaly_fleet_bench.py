"""Fleet-watch anomaly-scoring bench: 10k tenants' metric histories,
serial vs batched, parity-gated (ISSUE 15 acceptance; ROADMAP item 5).

Measures the scoring core the fleet watch runs every harvest: N ragged
metric series (per-series newest-point search intervals) scored by

- **serial**: one ``strategy.detect`` call per series — the pre-batching
  per-tenant loop;
- **batched**: ONE ``strategy.detect_batch`` call over the whole fleet
  tensor (the ``DEEQU_TPU_FLEETWATCH_BUNDLE`` shape).

Flagged indices AND anomaly messages must match element-for-element
(``parity`` in the output JSON; the bench stage hard-fails otherwise).

``--window-load`` additionally measures the repository half of the plane:
a year of daily per-run history written through the legacy one-file
``FileSystemMetricsRepository`` versus the time-partitioned
``PartitionedMetricsRepository``, querying one month — wall time and
entries deserialized per query (the O(all history) -> O(queried window)
PERF.md table).

Usage::

    python -m tools.anomaly_fleet_bench --series 10000
    python -m tools.anomaly_fleet_bench --window-load

Emits one JSON line on stdout (the bench stage parses the last line).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_fleet(n_series: int, seed: int = 17):
    """N ragged series shaped like daily metric histories (60-120 points,
    mild drift + noise), ~1 in 8 with an anomalous newest point."""
    import numpy as np

    rng = np.random.default_rng(seed)
    fleet = []
    for i in range(n_series):
        n = int(rng.integers(60, 120))
        base = 50.0 + float(rng.normal(0, 10))
        s = base + 0.02 * np.arange(n) + rng.normal(0, 1.0, n)
        if i % 8 == 0:
            s[-1] += float(rng.choice([-1, 1])) * 25.0
        fleet.append(s.tolist())
    return fleet


def run_scoring(n_series: int, seed: int = 17) -> dict:
    from deequ_tpu.anomalydetection import OnlineNormalStrategy

    strategy = OnlineNormalStrategy()
    fleet = build_fleet(n_series, seed)
    intervals = [(len(s) - 1, len(s)) for s in fleet]

    t0 = time.perf_counter()
    batched = strategy.detect_batch(fleet, intervals)
    batched_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    serial = [strategy.detect(s, iv) for s, iv in zip(fleet, intervals)]
    serial_s = time.perf_counter() - t0

    parity = True
    for got, want in zip(batched, serial):
        if [i for i, _ in got] != [i for i, _ in want]:
            parity = False
            break
        for (_, ga), (_, wa) in zip(got, want):
            if float(ga.value) != float(wa.value) or ga.detail != wa.detail:
                parity = False
                break
    flagged = sum(len(rows) for rows in batched)
    return {
        "series": n_series,
        "points_total": sum(len(s) for s in fleet),
        "batched_seconds": round(batched_s, 4),
        "series_per_s": round(n_series / batched_s, 1),
        "serial_seconds": round(serial_s, 4),
        "serial_series_per_s": round(n_series / serial_s, 1),
        "speedup": round(serial_s / batched_s, 2),
        "detect_calls": 1,
        "flagged": flagged,
        "parity": parity,
    }


def run_window_load(days: int = 365, window_days: int = 30) -> dict:
    """A year of daily history, one-month query: legacy one-file layout
    vs the time-partitioned buckets (median-of-3 query walls; entry
    deserialization counts pin the asymptotics)."""
    import os
    import shutil
    import statistics
    import tempfile

    from deequ_tpu.analyzers import Completeness, Mean, Size
    from deequ_tpu.data import Dataset
    from deequ_tpu.repository import (
        FileSystemMetricsRepository,
        PartitionedMetricsRepository,
        ResultKey,
    )
    from deequ_tpu.runners import AnalysisRunner

    import numpy as np

    data = Dataset.from_dict(
        {"x": np.random.default_rng(0).normal(10, 2, 512)}
    )
    ctx = AnalysisRunner.do_analysis_run(
        data, [Size(), Completeness("x"), Mean("x")]
    )
    day_ms = 86_400_000
    base = 1_735_689_600_000  # 2025-01-01T00:00Z
    root = tempfile.mkdtemp(prefix="anomaly-window-bench-")
    out = {}
    try:
        legacy = FileSystemMetricsRepository(os.path.join(root, "legacy.json"))
        parted = PartitionedMetricsRepository(os.path.join(root, "parted"))
        t0 = time.perf_counter()
        for d in range(days):
            legacy.save(ResultKey(base + d * day_ms), ctx)
        out["legacy_populate_s"] = round(time.perf_counter() - t0, 2)
        t0 = time.perf_counter()
        for d in range(days):
            parted.save(ResultKey(base + d * day_ms), ctx)
        out["partitioned_populate_s"] = round(time.perf_counter() - t0, 2)

        lo = base + (days - window_days) * day_ms
        hi = base + days * day_ms

        def timed(repo):
            walls = []
            for _ in range(3):
                repo.entries_deserialized = 0
                t = time.perf_counter()
                got = repo.load().after(lo).before(hi).get()
                walls.append(time.perf_counter() - t)
            return statistics.median(walls), len(got), repo.entries_deserialized

        legacy_s, legacy_n, legacy_deser = timed(legacy)
        parted_s, parted_n, parted_deser = timed(parted)
        assert legacy_n == parted_n == window_days, (legacy_n, parted_n)
        # the pre-fix cost model: a windowed query used to deserialize the
        # WHOLE history and filter afterwards — an unbounded load measures
        # exactly that work
        legacy.entries_deserialized = 0
        t = time.perf_counter()
        full = legacy.load().get()
        out["legacy_unwindowed_query_s"] = round(time.perf_counter() - t, 4)
        out["legacy_unwindowed_entries_deserialized"] = (
            legacy.entries_deserialized
        )
        assert len(full) == days
        out.update({
            "days": days,
            "window_days": window_days,
            "legacy_query_s": round(legacy_s, 4),
            "legacy_entries_deserialized": legacy_deser,
            "partitioned_query_s": round(parted_s, 4),
            "partitioned_entries_deserialized": parted_deser,
            "query_speedup": round(legacy_s / parted_s, 2),
        })
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--series", type=int, default=10_000)
    parser.add_argument("--seed", type=int, default=17)
    parser.add_argument("--window-load", action="store_true",
                        help="measure the windowed-history-load half "
                             "instead of scoring")
    args = parser.parse_args(argv)
    if args.window_load:
        out = run_window_load()
    else:
        out = run_scoring(args.series, args.seed)
    print(json.dumps(out), flush=True)
    if not out.get("parity", True):
        print("PARITY MISMATCH serial vs batched scoring", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
