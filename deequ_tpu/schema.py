"""Row-level schema enforcement: declarative column definitions, one
vectorized boolean conformance mask, valid/invalid row split with casting
(reference `schema/RowLevelSchemaValidator.scala:25-223`).

Row-level string validation is host work by nature; the masks are computed
with vectorized pandas/pyarrow ops (the reference builds one CNF Column
expression — same idea, Spark codegen swapped for numpy vectorization).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np
import pandas as pd

from .data import Dataset


@dataclass(frozen=True)
class ColumnDefinition:
    name: str
    is_nullable: bool = True


@dataclass(frozen=True)
class StringColumnDefinition(ColumnDefinition):
    min_length: Optional[int] = None
    max_length: Optional[int] = None
    matches: Optional[str] = None


@dataclass(frozen=True)
class IntColumnDefinition(ColumnDefinition):
    min_value: Optional[int] = None
    max_value: Optional[int] = None


@dataclass(frozen=True)
class DecimalColumnDefinition(ColumnDefinition):
    precision: int = 10
    scale: int = 0


@dataclass(frozen=True)
class TimestampColumnDefinition(ColumnDefinition):
    mask: str = "yyyy-MM-dd HH:mm:ss"


@dataclass(frozen=True)
class RowLevelSchema:
    """Fluent builder (reference `RowLevelSchemaValidator.scala:25-69`)."""

    column_definitions: tuple = ()

    def with_string_column(
        self, name, is_nullable=True, min_length=None, max_length=None, matches=None
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + (StringColumnDefinition(name, is_nullable, min_length, max_length, matches),)
        )

    def with_int_column(
        self, name, is_nullable=True, min_value=None, max_value=None
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + (IntColumnDefinition(name, is_nullable, min_value, max_value),)
        )

    def with_decimal_column(
        self, name, precision, scale, is_nullable=True
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + (DecimalColumnDefinition(name, is_nullable, precision, scale),)
        )

    def with_timestamp_column(self, name, mask, is_nullable=True) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions + (TimestampColumnDefinition(name, is_nullable, mask),)
        )


@dataclass
class RowLevelSchemaValidationResult:
    """(reference `RowLevelSchemaValidator.scala:169-175`)."""

    valid_rows: Dataset
    num_valid_rows: int
    invalid_rows: Dataset
    num_invalid_rows: int


_JAVA_TO_STRPTIME = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
    ("HH", "%H"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"),
]


def _java_mask_to_strptime(mask: str) -> str:
    out = mask
    for java, py in _JAVA_TO_STRPTIME:
        out = out.replace(java, py)
    return out


def _parse_int(series: pd.Series) -> pd.Series:
    """Spark cast-to-int semantics: numeric strings parse, everything else
    (incl. fractional strings) becomes null."""
    def parse(v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return None
        if isinstance(v, (float, np.floating)):
            # a numeric value (incl. an int column pandas widened to float64
            # because of nulls): Spark's numeric->int cast truncates; inf
            # cannot cast and marks the row invalid, it must not raise
            return int(v) if np.isfinite(v) else None
        try:
            return int(str(v).strip())
        except ValueError:
            return None

    return series.map(parse)


def _parse_decimal(series: pd.Series, precision: int, scale: int) -> pd.Series:
    """Castability to DECIMAL(precision, scale): value parses as a number
    and its integer part fits precision - scale digits."""
    max_abs = 10 ** (precision - scale)

    def parse(v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return None
        try:
            f = float(str(v).strip())
        except ValueError:
            return None
        if abs(f) >= max_abs:
            return None
        return round(f, scale)

    return series.map(parse)


def _parse_timestamp(series: pd.Series, mask: str) -> pd.Series:
    fmt = _java_mask_to_strptime(mask)
    return pd.to_datetime(series, format=fmt, errors="coerce")


MATCHES_COLUMN = "__deequ__matches__schema"


class RowLevelSchemaValidator:
    @staticmethod
    def validate(data: Dataset, schema: RowLevelSchema) -> RowLevelSchemaValidationResult:
        """(reference `RowLevelSchemaValidator.validate`, `:183-206`)."""
        df = data.to_pandas()
        n = len(df)
        matches = np.ones(n, dtype=bool)
        casted: dict = {}
        for cd in schema.column_definitions:
            col = df[cd.name] if cd.name in df.columns else pd.Series([None] * n)
            is_null = col.isna().to_numpy()
            if not cd.is_nullable:
                matches &= ~is_null
            if isinstance(cd, IntColumnDefinition):
                parsed = _parse_int(col)
                ok = is_null | parsed.notna().to_numpy()
                matches &= ok
                # DOCUMENTED DIVERGENCE: nulls pass the min bound here, as
                # they do the max bound. The reference's min-bound CNF reads
                # `colIsNull.isNull.or(colAsInt.geq(value))`
                # (`RowLevelSchemaValidator.scala:246`) — `colIsNull.isNull`
                # is constant-false (isNull of a non-null boolean expr), so
                # there a NULL row FAILS minValue while PASSING maxValue
                # (`:250` uses the plain `colIsNull.or(...)`). That asymmetry
                # is an apparent typo, not a semantic choice; this build uses
                # the symmetric nullable semantics for both bounds, with
                # non-nullability enforced separately via `is_nullable`.
                if cd.min_value is not None:
                    ge = parsed.map(lambda v: v is not None and v >= cd.min_value)
                    matches &= is_null | ge.to_numpy()
                if cd.max_value is not None:
                    le = parsed.map(lambda v: v is not None and v <= cd.max_value)
                    matches &= is_null | le.to_numpy()
                casted[cd.name] = parsed
            elif isinstance(cd, DecimalColumnDefinition):
                parsed = _parse_decimal(col, cd.precision, cd.scale)
                matches &= is_null | parsed.notna().to_numpy()
                casted[cd.name] = parsed
            elif isinstance(cd, TimestampColumnDefinition):
                parsed = _parse_timestamp(col, cd.mask)
                matches &= is_null | parsed.notna().to_numpy()
                casted[cd.name] = parsed
            elif isinstance(cd, StringColumnDefinition):
                as_str = col.map(lambda v: None if v is None else str(v))
                lengths = as_str.map(lambda v: len(v) if v is not None else -1).to_numpy()
                if cd.min_length is not None:
                    matches &= is_null | (lengths >= cd.min_length)
                if cd.max_length is not None:
                    matches &= is_null | (lengths <= cd.max_length)
                if cd.matches is not None:
                    pattern = re.compile(cd.matches)
                    hit = as_str.map(
                        lambda v: v is not None and pattern.search(v) is not None
                    ).to_numpy()
                    matches &= is_null | hit
        valid_df = df[matches].copy()
        for name, series in casted.items():
            out = series[matches]
            if isinstance(
                next(cd for cd in schema.column_definitions if cd.name == name),
                IntColumnDefinition,
            ):
                out = out.astype("Int64")  # keeps integer type despite nulls
            valid_df[name] = out
        invalid_df = df[~matches]
        return RowLevelSchemaValidationResult(
            valid_rows=Dataset.from_pandas(valid_df.reset_index(drop=True)),
            num_valid_rows=int(matches.sum()),
            invalid_rows=Dataset.from_pandas(invalid_df.reset_index(drop=True)),
            num_invalid_rows=int(n - matches.sum()),
        )
