"""Row-level schema enforcement: declarative column definitions, one
vectorized boolean conformance mask, valid/invalid row split with casting
(reference `schema/RowLevelSchemaValidator.scala:25-223`).

Row-level string validation is host work by nature; the masks are computed
with vectorized pandas/pyarrow ops (the reference builds one CNF Column
expression — same idea, Spark codegen swapped for numpy vectorization).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np
import pandas as pd

from .data import Dataset


@dataclass(frozen=True)
class ColumnDefinition:
    name: str
    is_nullable: bool = True


@dataclass(frozen=True)
class StringColumnDefinition(ColumnDefinition):
    min_length: Optional[int] = None
    max_length: Optional[int] = None
    matches: Optional[str] = None


@dataclass(frozen=True)
class IntColumnDefinition(ColumnDefinition):
    min_value: Optional[int] = None
    max_value: Optional[int] = None


@dataclass(frozen=True)
class DecimalColumnDefinition(ColumnDefinition):
    precision: int = 10
    scale: int = 0


@dataclass(frozen=True)
class TimestampColumnDefinition(ColumnDefinition):
    mask: str = "yyyy-MM-dd HH:mm:ss"


@dataclass(frozen=True)
class RowLevelSchema:
    """Fluent builder (reference `RowLevelSchemaValidator.scala:25-69`)."""

    column_definitions: tuple = ()

    def with_string_column(
        self, name, is_nullable=True, min_length=None, max_length=None, matches=None
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + (StringColumnDefinition(name, is_nullable, min_length, max_length, matches),)
        )

    def with_int_column(
        self, name, is_nullable=True, min_value=None, max_value=None
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + (IntColumnDefinition(name, is_nullable, min_value, max_value),)
        )

    def with_decimal_column(
        self, name, precision, scale, is_nullable=True
    ) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions
            + (DecimalColumnDefinition(name, is_nullable, precision, scale),)
        )

    def with_timestamp_column(self, name, mask, is_nullable=True) -> "RowLevelSchema":
        return RowLevelSchema(
            self.column_definitions + (TimestampColumnDefinition(name, is_nullable, mask),)
        )


@dataclass
class RowLevelSchemaValidationResult:
    """(reference `RowLevelSchemaValidator.scala:169-175`)."""

    valid_rows: Dataset
    num_valid_rows: int
    invalid_rows: Dataset
    num_invalid_rows: int


_JAVA_TO_STRPTIME = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
    ("HH", "%H"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"),
]


def _java_mask_to_strptime(mask: str) -> str:
    out = mask
    for java, py in _JAVA_TO_STRPTIME:
        out = out.replace(java, py)
    return out


def _parse_int(series: pd.Series) -> pd.Series:
    """Spark cast-to-int semantics: numeric strings parse, everything else
    (incl. fractional strings) becomes null. Dtype-dispatched: a column
    that is ALREADY integral (the streaming gate's steady state — typed
    Arrow frames, not CSV strings) passes through without touching a
    single value, and float columns vectorize; only object/string columns
    pay the per-value parse."""
    if pd.api.types.is_integer_dtype(series.dtype):
        # every value already casts (incl. nullable Int64 — its NAs stay
        # NAs, which is exactly the null-passthrough the parse encodes)
        return series
    if pd.api.types.is_float_dtype(series.dtype):
        # a numeric column (incl. an int column pandas widened to float64
        # because of nulls): Spark's numeric->int cast truncates; inf
        # cannot cast and marks the row invalid, it must not raise
        arr = series.to_numpy()
        out = pd.Series(np.trunc(arr), index=series.index, dtype="object")
        out[~np.isfinite(arr)] = None
        return out

    def parse(v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return None
        if isinstance(v, (float, np.floating)):
            return int(v) if np.isfinite(v) else None
        try:
            return int(str(v).strip())
        except ValueError:
            return None

    return series.map(parse)


def _parse_decimal(series: pd.Series, precision: int, scale: int) -> pd.Series:
    """Castability to DECIMAL(precision, scale): value parses as a number
    and its integer part fits precision - scale digits."""
    max_abs = 10 ** (precision - scale)

    def parse(v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return None
        try:
            f = float(str(v).strip())
        except ValueError:
            return None
        if abs(f) >= max_abs:
            return None
        return round(f, scale)

    return series.map(parse)


def _parse_timestamp(series: pd.Series, mask: str) -> pd.Series:
    fmt = _java_mask_to_strptime(mask)
    return pd.to_datetime(series, format=fmt, errors="coerce")


MATCHES_COLUMN = "__deequ__matches__schema"


def compute_conformance(df, schema: RowLevelSchema, num_rows=None):
    """The vectorized conformance pass shared by the batch validator and
    the streaming row gate (`deequ_tpu.ingest.rowgate`): one boolean
    ``matches`` mask over ``df`` plus the per-column casted series for
    the rows that will survive. Factored out so the two paths can NEVER
    diverge on a verdict — the gate's accept/reject split is this exact
    mask, by construction.

    ``df`` is a DataFrame or a plain mapping of column name -> Series
    (with ``num_rows`` passed explicitly): the gate hands over bare
    per-column Series so its per-frame hot path never pays DataFrame /
    block-manager construction. ``name in df`` and ``df[name]`` mean the
    same thing for both shapes."""
    n = len(df) if num_rows is None else num_rows
    matches = np.ones(n, dtype=bool)
    casted: dict = {}
    for cd in schema.column_definitions:
        col = df[cd.name] if cd.name in df else pd.Series([None] * n)
        is_null = col.isna().to_numpy()
        if not cd.is_nullable:
            matches &= ~is_null
        if isinstance(cd, IntColumnDefinition):
            parsed = _parse_int(col)
            if parsed is not col:
                # an already-integral column passes through _parse_int
                # identically — every non-null value casts by
                # construction, so the castability pass is a no-op
                matches &= is_null | parsed.notna().to_numpy()
            # DOCUMENTED DIVERGENCE: nulls pass the min bound here, as
            # they do the max bound. The reference's min-bound CNF reads
            # `colIsNull.isNull.or(colAsInt.geq(value))`
            # (`RowLevelSchemaValidator.scala:246`) — `colIsNull.isNull`
            # is constant-false (isNull of a non-null boolean expr), so
            # there a NULL row FAILS minValue while PASSING maxValue
            # (`:250` uses the plain `colIsNull.or(...)`). That asymmetry
            # is an apparent typo, not a semantic choice; this build uses
            # the symmetric nullable semantics for both bounds, with
            # non-nullability enforced separately via `is_nullable`.
            if cd.min_value is not None or cd.max_value is not None:
                # vectorized bounds: NaN (unparseable or null) compares
                # False on both sides, the exact `v is not None and ...`
                # semantics of the per-value form
                pv = pd.to_numeric(parsed, errors="coerce").to_numpy(
                    dtype=float, na_value=np.nan
                )
                if cd.min_value is not None:
                    matches &= is_null | (pv >= cd.min_value)
                if cd.max_value is not None:
                    matches &= is_null | (pv <= cd.max_value)
            casted[cd.name] = parsed
        elif isinstance(cd, DecimalColumnDefinition):
            parsed = _parse_decimal(col, cd.precision, cd.scale)
            matches &= is_null | parsed.notna().to_numpy()
            casted[cd.name] = parsed
        elif isinstance(cd, TimestampColumnDefinition):
            parsed = _parse_timestamp(col, cd.mask)
            matches &= is_null | parsed.notna().to_numpy()
            casted[cd.name] = parsed
        elif isinstance(cd, StringColumnDefinition):
            # astype("string") is the vectorized str(v)-or-null: non-str
            # values stringify, nulls stay NA — the per-value semantics,
            # at C speed for the Arrow-string steady state
            as_str = col.astype("string")
            if cd.min_length is not None or cd.max_length is not None:
                lengths = as_str.str.len().to_numpy(
                    dtype=float, na_value=-1.0
                )
                if cd.min_length is not None:
                    matches &= is_null | (lengths >= cd.min_length)
                if cd.max_length is not None:
                    matches &= is_null | (lengths <= cd.max_length)
            if cd.matches is not None:
                hit = as_str.str.contains(
                    cd.matches, regex=True
                ).to_numpy(dtype=bool, na_value=False)
                matches &= is_null | hit
    return matches, casted


class RowLevelSchemaValidator:
    @staticmethod
    def validate(data: Dataset, schema: RowLevelSchema) -> RowLevelSchemaValidationResult:
        """(reference `RowLevelSchemaValidator.validate`, `:183-206`)."""
        df = data.to_pandas()
        n = len(df)
        matches, casted = compute_conformance(df, schema)
        valid_df = df[matches].copy()
        for name, series in casted.items():
            out = series[matches]
            if isinstance(
                next(cd for cd in schema.column_definitions if cd.name == name),
                IntColumnDefinition,
            ):
                out = out.astype("Int64")  # keeps integer type despite nulls
            valid_df[name] = out
        invalid_df = df[~matches]
        return RowLevelSchemaValidationResult(
            valid_rows=Dataset.from_pandas(valid_df.reset_index(drop=True)),
            num_valid_rows=int(matches.sum()),
            invalid_rows=Dataset.from_pandas(invalid_df.reset_index(drop=True)),
            num_invalid_rows=int(n - matches.sum()),
        )
