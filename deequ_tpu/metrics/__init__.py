"""Metric datamodel.

Mirrors the reference datamodel (deequ `metrics/Metric.scala:21-68`,
`metrics/HistogramMetric.scala:21-61`, `metrics/KLLMetric.scala:24-40`):
a metric is (entity, name, instance, value) where value is a Try-like
Success/Failure wrapper so that analyzer errors become *data*, not aborts
(`analyzers/Analyzer.scala:94-103`).
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, List, Optional, Sequence, TypeVar

T = TypeVar("T")
U = TypeVar("U")


class Entity(enum.Enum):
    """What a metric is about (reference `metrics/Metric.scala:21-26`)."""

    DATASET = "Dataset"
    COLUMN = "Column"
    MULTICOLUMN = "Multicolumn"


class Try(Generic[T]):
    """Success-or-Failure result wrapper (Scala Try analog)."""

    __slots__ = ()

    @property
    def is_success(self) -> bool:
        return isinstance(self, Success)

    @property
    def is_failure(self) -> bool:
        return not self.is_success

    def get(self) -> T:
        raise NotImplementedError

    def get_or_else(self, default: U) -> T | U:
        return self.get() if self.is_success else default

    def map(self, fn: Callable[[T], U]) -> "Try[U]":
        if self.is_success:
            try:
                return Success(fn(self.get()))
            except Exception as exc:  # noqa: BLE001 - mirror Try semantics
                return Failure(exc)
        return self  # type: ignore[return-value]


@dataclass(frozen=True)
class Success(Try[T]):
    value: T

    def get(self) -> T:
        return self.value

    def __repr__(self) -> str:
        return f"Success({self.value!r})"


@dataclass(frozen=True)
class Failure(Try[Any]):
    exception: BaseException

    def get(self) -> Any:
        raise self.exception

    def __repr__(self) -> str:
        return f"Failure({self.exception!r})"


@dataclass(frozen=True)
class Metric(Generic[T]):
    """Base metric record (reference `metrics/Metric.scala:28-44`)."""

    entity: Entity
    name: str
    instance: str
    value: Try[T]

    def flatten(self) -> Sequence["DoubleMetric"]:
        raise NotImplementedError


@dataclass(frozen=True)
class DoubleMetric(Metric[float]):
    def flatten(self) -> Sequence["DoubleMetric"]:
        return (self,)


@dataclass(frozen=True)
class KeyedDoubleMetric(Metric[Dict[str, float]]):
    """Many named doubles under one metric, e.g. ApproxQuantiles
    (reference `metrics/Metric.scala:54-68`)."""

    def flatten(self) -> Sequence[DoubleMetric]:
        if self.value.is_success:
            return tuple(
                DoubleMetric(self.entity, f"{self.name}-{k}", self.instance, Success(v))
                for k, v in self.value.get().items()
            )
        return (DoubleMetric(self.entity, self.name, self.instance, self.value),)


@dataclass(frozen=True)
class DistributionValue:
    absolute: int
    ratio: float


@dataclass(frozen=True)
class Distribution:
    """Categorical distribution: label -> (absolute count, ratio); the metric
    payload of Histogram/DataType (reference `metrics/HistogramMetric.scala:21-40`)."""

    values: Dict[str, DistributionValue]
    number_of_bins: int

    def __getitem__(self, key: str) -> DistributionValue:
        return self.values[key]

    def argmax(self) -> str:
        return max(self.values, key=lambda k: self.values[k].absolute)


@dataclass(frozen=True)
class HistogramMetric(Metric[Distribution]):
    column: str = ""

    def flatten(self) -> Sequence[DoubleMetric]:
        """Flatten to bins + per-bin abs/ratio metrics
        (reference `metrics/HistogramMetric.scala:31-61`)."""
        if self.value.is_failure:
            return (
                DoubleMetric(self.entity, f"{self.name}.bins", self.instance, self.value),
            )
        dist = self.value.get()
        out: List[DoubleMetric] = [
            DoubleMetric(
                self.entity, f"{self.name}.bins", self.instance, Success(float(dist.number_of_bins))
            )
        ]
        for key, dv in dist.values.items():
            out.append(
                DoubleMetric(
                    self.entity,
                    f"{self.name}.abs.{key}",
                    self.instance,
                    Success(float(dv.absolute)),
                )
            )
            out.append(
                DoubleMetric(
                    self.entity, f"{self.name}.ratio.{key}", self.instance, Success(dv.ratio)
                )
            )
        return tuple(out)


@dataclass(frozen=True)
class BucketValue:
    low_value: float
    high_value: float
    count: int


@dataclass(frozen=True)
class BucketDistribution:
    """Equi-width bucketed view of a KLL sketch plus the raw sketch parameters
    and data, so percentiles can be re-derived later
    (reference `metrics/KLLMetric.scala` / `analyzers/KLLSketch.scala:125-160`)."""

    buckets: List[BucketValue]
    parameters: List[float]  # [shrinking_factor, sketch_size]
    data: List[List[float]]  # per-level compactor buffers (weights 2^level)

    def compute_percentiles(self) -> List[float]:
        """Re-materialize the sketch from raw buffers and query 1..100th
        percentiles (reference `metrics/KLLMetric.scala:24-40`)."""
        from ..ops.kll_host import HostKLL

        sketch = HostKLL.from_buffers(self.data, int(self.parameters[1]), self.parameters[0])
        return [sketch.quantile(p / 100.0) for p in range(1, 101)]

    def argmax(self) -> int:
        return max(range(len(self.buckets)), key=lambda i: self.buckets[i].count)


@dataclass(frozen=True)
class KLLMetric(Metric[BucketDistribution]):
    def flatten(self) -> Sequence[DoubleMetric]:
        if self.value.is_failure:
            return (
                DoubleMetric(self.entity, f"{self.name}.buckets", self.instance, self.value),
            )
        dist = self.value.get()
        out: List[DoubleMetric] = [
            DoubleMetric(
                self.entity,
                f"{self.name}.buckets",
                self.instance,
                Success(float(len(dist.buckets))),
            )
        ]
        for i, b in enumerate(dist.buckets):
            out.append(
                DoubleMetric(
                    self.entity, f"{self.name}.bucket.{i}.count", self.instance, Success(float(b.count))
                )
            )
        return tuple(out)


def metric_from_value(value: float, name: str, instance: str, entity: Entity) -> DoubleMetric:
    if value is None:
        return metric_from_failure(
            ValueError(f"metric {name} on {instance} produced no value"), name, instance, entity
        )
    # NaN is a legitimate successful value (Spark: max/sum/avg over data
    # containing NaN, corr at zero variance); emptiness/failure is decided
    # by the caller, never inferred from the value here
    return DoubleMetric(entity, name, instance, Success(float(value)))


def metric_from_failure(
    exception: BaseException, name: str, instance: str, entity: Entity
) -> DoubleMetric:
    return DoubleMetric(entity, name, instance, Failure(exception))


def metric_from_empty(name: str, instance: str, entity: Entity) -> DoubleMetric:
    from ..exceptions import EmptyStateException

    return metric_from_failure(
        EmptyStateException(f"Empty state for analyzer {name} on {instance}, all input values were NULL."),
        name,
        instance,
        entity,
    )
