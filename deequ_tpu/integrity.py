"""Content checksums for the data plane's persisted payloads.

Every durable artifact this engine writes — v2 state blobs, FS repository
entries, ingest-checkpoint meta records — carries an xxhash64 content
checksum (the same hash the HLL registers already use, `ops/hashing.py`),
verified on load. The threat model is NOT an adversary (the state registry
already refuses code execution on load); it is the mundane reality of
long-lived storage under a service that runs for weeks: torn writes,
bit rot, partial uploads, concurrent writers on eventually-consistent
stores. A mismatch raises a typed
:class:`~deequ_tpu.exceptions.CorruptStateError` that every consumer
treats as recoverable (quarantine / fall back / degrade), never as a
crash — the reference pins its state serde byte layouts for the same
reason (`StateProvider.scala:187-311`): garbled state is assumed, not
hypothetical.

Checksums are hex strings (16 lowercase hex chars of the 64-bit digest) so
they embed in JSON and .npz string fields without byte-order concerns.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from .exceptions import CorruptStateError
from .ops.hashing import xxhash64_bytes, xxhash64_u64

#: seed distinguishing integrity checksums from the HLL row-hash domain —
#: a payload that happens to contain row hashes can never alias its own
#: checksum
CHECKSUM_SEED = 0x5EED

#: payloads below this size hash through the canonical scalar xxhash64
#: (cheap at this scale); above it, the vectorized block checksum applies
_VECTOR_THRESHOLD = 1 << 10

#: position-tag multiplier for the block checksum (xxhash64's own prime 1)
_POS_PRIME = np.uint64(11400714785074694791)


#: warn-once latches per blob family: a store written by a pre-checksum
#: build floods neither the log nor the operator — one line per process
#: per family, then silence
_LEGACY_WARNED: Dict[str, bool] = {}


def warn_once_unchecksummed(kind: str, source: str) -> None:
    """Log (once per process per ``kind``) that a legacy artifact without a
    content checksum was loaded unverified."""
    import logging

    if not _LEGACY_WARNED.get(kind):
        _LEGACY_WARNED[kind] = True
        logging.getLogger(__name__).warning(
            "loading legacy %s without a content checksum (first seen: %s); "
            "integrity verification is skipped for unchecksummed payloads — "
            "re-persist to upgrade them",
            kind, source,
        )


def checksum_bytes(payload) -> str:
    """Content checksum of raw bytes (or any buffer-protocol object —
    memoryview, arrow buffer — hashed IN PLACE), as 16 hex chars.

    Small payloads (< 1 KiB: meta records, repository entries) use the
    canonical scalar xxhash64. Large payloads (state blobs — KLL item
    buffers run to megabytes) use a VECTORIZED construction over the same
    primitive: the payload's little-endian u64 words are position-tagged
    (``word ^ index*prime`` — so transposed regions change the digest),
    hashed per-word with the numpy ``xxhash64_u64`` kernel, XOR-combined,
    and finalized with a scalar xxhash64 over (combined, byte tail,
    length). The pure-Python byte-stream loop measures ~10 MB/s — it would
    cost more than the persist it protects — while the block construction
    runs at memory bandwidth; its collision behavior is equivalent for the
    bit-rot/torn-write faults this layer exists to catch. The digest
    definition is internal (both sides of every verify call this one
    function) and pinned by tests."""
    n = len(payload)
    if n < _VECTOR_THRESHOLD:
        if not isinstance(payload, bytes):
            payload = bytes(payload)  # sub-KiB: the copy is trivial
        return f"{xxhash64_bytes(payload, CHECKSUM_SEED):016x}"
    # accepts any buffer-protocol object (bytes, memoryview, arrow
    # buffer): np.frombuffer reads in place, so hashing a gigabyte ingest
    # payload never materializes a second copy of it
    words = np.frombuffer(payload, dtype="<u8", count=n // 8)
    with np.errstate(over="ignore"):
        tagged = words ^ (
            np.arange(words.size, dtype=np.uint64) * _POS_PRIME
        )
        combined = np.bitwise_xor.reduce(xxhash64_u64(tagged, CHECKSUM_SEED))
    tail = bytes(memoryview(payload)[(n // 8) * 8:])
    final = xxhash64_bytes(
        int(combined).to_bytes(8, "little") + tail + n.to_bytes(8, "little"),
        CHECKSUM_SEED,
    )
    return f"{final:016x}"


def checksum_json(obj: Dict[str, Any]) -> str:
    """Checksum of a JSON-able dict under a CANONICAL encoding (sorted
    keys, no whitespace) so semantically-equal payloads always hash alike
    regardless of who serialized them."""
    return checksum_bytes(
        json.dumps(obj, sort_keys=True, separators=(",", ":")).encode("utf-8")
    )


def _raise_corrupt(kind: str, source: str, detail: str) -> None:
    """Build, flight-record and raise the typed corruption error: every
    checksum trip leaves a ``failure`` event on the current trace span and
    a flight-recorder dump request, so a quarantine is explainable from the
    trace artifact alone (see ``deequ_tpu.observability``)."""
    exc = CorruptStateError(kind, source, detail)
    from .observability import record_failure

    record_failure(exc)
    raise exc


def verify_checksum(
    payload: bytes, expected: str, kind: str, source: str
) -> None:
    """Raise :class:`CorruptStateError` unless ``payload`` hashes to
    ``expected``. ``kind``/``source`` feed the error's operator-facing
    identity ("what artifact, where")."""
    actual = checksum_bytes(payload)
    if actual != str(expected):
        _raise_corrupt(
            kind, source,
            f"checksum mismatch (stored {expected}, computed {actual})",
        )


def verify_json_checksum(
    obj: Dict[str, Any], expected: str, kind: str, source: str
) -> None:
    actual = checksum_json(obj)
    if actual != str(expected):
        _raise_corrupt(
            kind, source,
            f"checksum mismatch (stored {expected}, computed {actual})",
        )
