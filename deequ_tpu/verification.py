"""VerificationSuite: the main orchestration façade.

``VerificationSuite.on_data(data).add_check(check).run()`` collects the
analyzers every check needs, delegates metric computation to the
AnalysisRunner (one fused pass), evaluates checks against the resulting
AnalyzerContext and reports an overall status
(reference `VerificationSuite.scala:42-315`, `VerificationRunBuilder.scala:
28-341`, `VerificationResult.scala:33-119`).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from .analyzers import Analyzer
from .analyzers.state_provider import StateLoader, StatePersister
from .checks import Check, CheckLevel, CheckResult, CheckStatus
from .data import Dataset, Schema
from .metrics import Metric
from .runners.analysis_runner import AnalysisRunner
from .runners.context import AnalyzerContext


class VerificationResult:
    """(reference `VerificationResult.scala:33-119`)."""

    def __init__(
        self,
        status: CheckStatus,
        check_results: Dict[Check, CheckResult],
        metrics: Dict[Analyzer, Metric],
        cost_by_analyzer: Optional[Dict[str, float]] = None,
    ):
        self.status = status
        self.check_results = check_results
        self.metrics = metrics
        #: per-analyzer cost attribution (seconds, keyed by repr(analyzer))
        #: harvested from the run's RunMonitor: each signature bundle's
        #: measured compile+dispatch time split across its slots. Empty for
        #: state-only runs (`run_on_aggregated_states`) and when the caller
        #: evaluated checks against a pre-built context.
        self.cost_by_analyzer: Dict[str, float] = dict(cost_by_analyzer or {})

    def cost_by_analyzer_as_json(self) -> str:
        """The attribution table as JSON (sorted most-expensive first):
        ``[{"analyzer": ..., "seconds": ...}, ...]`` — round-trips through
        ``json.loads`` back to the table's contents."""
        rows = sorted(
            self.cost_by_analyzer.items(), key=lambda kv: -kv[1]
        )
        return json.dumps(
            [{"analyzer": k, "seconds": v} for k, v in rows]
        )

    def success_metrics_as_data_frame(self, for_analyzers: Sequence[Analyzer] = ()):
        return AnalyzerContext(self.metrics).success_metrics_as_dataframe(for_analyzers)

    def success_metrics_as_json(self, for_analyzers: Sequence[Analyzer] = ()) -> str:
        return AnalyzerContext(self.metrics).success_metrics_as_json(for_analyzers)

    def check_results_as_data_frame(self):
        import pandas as pd

        rows = []
        for check, result in self.check_results.items():
            for cr in result.constraint_results:
                rows.append(
                    {
                        "check": check.description,
                        "check_level": check.level.value,
                        "check_status": result.status.value,
                        "constraint": str(cr.constraint),
                        "constraint_status": cr.status.value,
                        "constraint_message": cr.message or "",
                    }
                )
        return pd.DataFrame(
            rows,
            columns=[
                "check",
                "check_level",
                "check_status",
                "constraint",
                "constraint_status",
                "constraint_message",
            ],
        )

    def check_results_as_json(self) -> str:
        df = self.check_results_as_data_frame()
        return json.dumps(df.to_dict(orient="records"))


class IncrementalVerificationResult(VerificationResult):
    """A :class:`VerificationResult` plus the incremental run's delta-plan
    report (``incremental``: an
    :class:`~deequ_tpu.runners.incremental.IncrementalRunReport` —
    scan/reuse/invalidated/dropped partition lists, rows scanned vs total,
    reuse ratio)."""

    def __init__(self, result: VerificationResult, report):
        super().__init__(
            result.status, result.check_results, result.metrics,
            result.cost_by_analyzer,
        )
        self.incremental = report


class VerificationSuite:
    """(reference `VerificationSuite.scala:42-315`)."""

    @staticmethod
    def on_data(data: Dataset) -> "VerificationRunBuilder":
        return VerificationRunBuilder(data)

    # ------------------------------------------------------------------

    @staticmethod
    def do_verification_run(
        data: Dataset,
        checks: Sequence[Check],
        required_analyzers: Sequence[Analyzer] = (),
        *,
        aggregate_with: Optional[StateLoader] = None,
        save_states_with: Optional[StatePersister] = None,
        metrics_repository: Optional[Any] = None,
        reuse_existing_results_for_key: Optional[Any] = None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key: Optional[Any] = None,
        batch_size: Optional[int] = None,
        monitor: Optional[Any] = None,
        sharding: Optional[Any] = None,
        placement: Optional[str] = None,
        checkpointer: Optional[Any] = None,
    ) -> VerificationResult:
        from .observability import trace as _trace
        from .runners.analysis_runner import collect_required_analyzers
        from .runners.engine import RunMonitor

        checks = list(checks)  # evaluate() walks them again after the run
        analyzers = collect_required_analyzers(checks, required_analyzers)
        # a monitor always exists so per-analyzer cost attribution reaches
        # the result even when the caller did not ask for one
        monitor = monitor if monitor is not None else RunMonitor()

        with _trace.span(
            "verification_run", kind="verification",
            checks=len(checks), analyzers=len(analyzers),
        ):
            analysis_results = AnalysisRunner.do_analysis_run(
                data,
                analyzers,
                aggregate_with=aggregate_with,
                save_states_with=save_states_with,
                metrics_repository=metrics_repository,
                reuse_existing_results_for_key=reuse_existing_results_for_key,
                fail_if_results_missing=fail_if_results_missing,
                # save AFTER evaluation (below), so anomaly checks never see
                # the current point in their own history (reference
                # `VerificationSuite.scala:121-139`)
                save_or_append_results_with_key=None,
                batch_size=batch_size,
                monitor=monitor,
                sharding=sharding,
                placement=placement,
                checkpointer=checkpointer,
            )
            with _trace.span("constraint_evaluation", kind="phase"):
                result = VerificationSuite.evaluate(checks, analysis_results)
            result.cost_by_analyzer = dict(monitor.cost_by_analyzer)
            if metrics_repository is not None and save_or_append_results_with_key is not None:
                from .runners.analysis_runner import _save_or_append

                _save_or_append(
                    metrics_repository, save_or_append_results_with_key,
                    analysis_results,
                )
            return result

    @staticmethod
    def verify_partitioned(
        store,
        dataset_name: str,
        partitions,
        checks: Sequence[Check],
        required_analyzers: Sequence[Analyzer] = (),
        *,
        checksums=None,
        batch_size: Optional[int] = None,
        monitor: Optional[Any] = None,
        sharding: Optional[Any] = None,
        placement: Optional[str] = None,
        metrics_repository: Optional[Any] = None,
        save_or_append_results_with_key: Optional[Any] = None,
        delete_dropped: bool = False,
    ) -> "IncrementalVerificationResult":
        """Partition-aware incremental verification (ROADMAP item 4): diff
        the incoming partition set against ``store`` (a
        :class:`~deequ_tpu.repository.partition_store.PartitionStateStore`),
        scan ONLY new/changed partitions (persisting their per-partition
        algebraic states), load unchanged partitions' states with zero
        data touched, and evaluate ``checks`` against the merge — a table
        that grew 1% verifies at ~1% of a full scan. The returned
        result's ``incremental`` report carries the delta plan and the
        rows-touched accounting."""
        from .observability import trace as _trace
        from .runners.analysis_runner import collect_required_analyzers
        from .runners.engine import RunMonitor
        from .runners.incremental import run_incremental

        checks = list(checks)
        analyzers = collect_required_analyzers(checks, required_analyzers)
        monitor = monitor if monitor is not None else RunMonitor()
        with _trace.span(
            "incremental_verification", kind="verification",
            dataset=str(dataset_name), checks=len(checks),
        ):
            context, report = run_incremental(
                store, dataset_name, partitions, analyzers,
                checksums=checksums, batch_size=batch_size,
                monitor=monitor, sharding=sharding, placement=placement,
                metrics_repository=metrics_repository,
                save_or_append_results_with_key=save_or_append_results_with_key,
                delete_dropped=delete_dropped,
            )
            with _trace.span("constraint_evaluation", kind="phase"):
                result = VerificationSuite.evaluate(checks, context)
            result.cost_by_analyzer = dict(monitor.cost_by_analyzer)
        return IncrementalVerificationResult(result, report)

    @staticmethod
    def on_partitions(
        store, dataset_name: str, partitions, checksums=None
    ) -> "PartitionedVerificationRunBuilder":
        """Fluent entry point of :meth:`verify_partitioned`."""
        return PartitionedVerificationRunBuilder(
            store, dataset_name, partitions, checksums
        )

    @staticmethod
    def run_on_aggregated_states(
        schema: Schema,
        checks: Sequence[Check],
        state_loaders: Sequence[StateLoader],
        *,
        required_analyzers: Sequence[Analyzer] = (),
        save_states_with: Optional[StatePersister] = None,
        metrics_repository: Optional[Any] = None,
        save_or_append_results_with_key: Optional[Any] = None,
    ) -> VerificationResult:
        """Verification from merged persisted states, no data pass
        (reference `VerificationSuite.scala:208-229`)."""
        from .runners.analysis_runner import collect_required_analyzers

        checks = list(checks)  # evaluate() walks them again after the run
        analyzers = collect_required_analyzers(checks, required_analyzers)
        context = AnalysisRunner.run_on_aggregated_states(
            schema,
            analyzers,
            state_loaders,
            save_states_with=save_states_with,
            metrics_repository=metrics_repository,
            save_or_append_results_with_key=save_or_append_results_with_key,
        )
        return VerificationSuite.evaluate(checks, context)

    @staticmethod
    def evaluate(checks: Sequence[Check], context: AnalyzerContext) -> VerificationResult:
        """(reference `VerificationSuite.scala:263-281`)."""
        check_results = {check: check.evaluate(context) for check in checks}
        if not check_results:
            status = CheckStatus.SUCCESS
        else:
            status = max(
                (r.status for r in check_results.values()), key=lambda s: s.severity
            )
        return VerificationResult(status, check_results, dict(context.metric_map))


@dataclass(frozen=True)
class AnomalyCheckConfig:
    """(reference `VerificationRunBuilder.scala:336`)."""

    level: CheckLevel
    description: str
    with_tag_values: Dict[str, str] = field(default_factory=dict)
    after_date: Optional[int] = None
    before_date: Optional[int] = None


class VerificationRunBuilder:
    """Fluent run configuration (reference `VerificationRunBuilder.scala:
    28-163`)."""

    def __init__(self, data: Dataset):
        self.data = data
        self.checks: List[Check] = []
        self.required_analyzers: List[Analyzer] = []
        self._aggregate_with: Optional[StateLoader] = None
        self._save_states_with: Optional[StatePersister] = None
        self._metrics_repository = None
        self._reuse_key = None
        self._fail_if_results_missing = False
        self._save_key = None
        self._batch_size: Optional[int] = None
        self._monitor = None
        self._sharding = None
        self._placement: Optional[str] = None
        self._checkpointer = None
        self._check_results_path: Optional[str] = None
        self._success_metrics_path: Optional[str] = None

    def add_check(self, check: Check) -> "VerificationRunBuilder":
        self.checks.append(check)
        return self

    def add_checks(self, checks: Sequence[Check]) -> "VerificationRunBuilder":
        self.checks.extend(checks)
        return self

    def add_required_analyzer(self, analyzer: Analyzer) -> "VerificationRunBuilder":
        self.required_analyzers.append(analyzer)
        return self

    def add_required_analyzers(self, analyzers: Sequence[Analyzer]) -> "VerificationRunBuilder":
        self.required_analyzers.extend(analyzers)
        return self

    def aggregate_with(self, state_loader: StateLoader) -> "VerificationRunBuilder":
        self._aggregate_with = state_loader
        return self

    def save_states_with(self, state_persister: StatePersister) -> "VerificationRunBuilder":
        self._save_states_with = state_persister
        return self

    def with_batch_size(self, batch_size: int) -> "VerificationRunBuilder":
        self._batch_size = batch_size
        return self

    def with_monitor(self, monitor) -> "VerificationRunBuilder":
        self._monitor = monitor
        return self

    def with_sharding(self, sharding) -> "VerificationRunBuilder":
        self._sharding = sharding
        return self

    def with_placement(self, placement: str) -> "VerificationRunBuilder":
        """Force the ingest tier: "device", "host", or "auto" (the service's
        cache-aware router uses this to keep cold compiles off the queue)."""
        self._placement = placement
        return self

    def checkpoint_with(self, checkpointer) -> "VerificationRunBuilder":
        """Make the multi-batch ingest resumable: a
        `reliability.IngestCheckpointer` persists algebraic states every K
        batches through its StatePersister, and an interrupted run invoked
        again with the same checkpointer resumes from the last checkpoint
        with metrics equal to the uninterrupted run (see README "Failure
        semantics")."""
        self._checkpointer = checkpointer
        return self

    def save_check_results_json_to_path(self, path: str) -> "VerificationRunBuilder":
        self._check_results_path = path
        return self

    def save_success_metrics_json_to_path(self, path: str) -> "VerificationRunBuilder":
        self._success_metrics_path = path
        return self

    def use_repository(self, repository) -> "VerificationRunBuilderWithRepository":
        return VerificationRunBuilderWithRepository(self, repository)

    def run(self) -> VerificationResult:
        result = VerificationSuite.do_verification_run(
            self.data,
            self.checks,
            self.required_analyzers,
            aggregate_with=self._aggregate_with,
            save_states_with=self._save_states_with,
            metrics_repository=self._metrics_repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_or_append_results_with_key=self._save_key,
            batch_size=self._batch_size,
            monitor=self._monitor,
            sharding=self._sharding,
            placement=self._placement,
            checkpointer=self._checkpointer,
        )
        # URI-aware sinks (reference writes these through Hadoop FileSystem,
        # `VerificationSuite.scala:146-172` / `io/DfsUtils.scala:24-85`)
        from . import io as dio

        if self._check_results_path is not None:
            dio.write_text_atomic(
                self._check_results_path, result.check_results_as_json()
            )
        if self._success_metrics_path is not None:
            dio.write_text_atomic(
                self._success_metrics_path, result.success_metrics_as_json()
            )
        return result


class PartitionedVerificationRunBuilder:
    """Fluent configuration for partition-aware incremental verification
    (``VerificationSuite.on_partitions(store, name, partitions)``): the
    check-building half of :class:`VerificationRunBuilder`, running
    through the delta planner instead of a single data pass."""

    def __init__(self, store, dataset_name: str, partitions, checksums=None):
        self.store = store
        self.dataset_name = dataset_name
        self.partitions = partitions
        self.checksums = checksums
        self.checks: List[Check] = []
        self.required_analyzers: List[Analyzer] = []
        self._batch_size: Optional[int] = None
        self._monitor = None
        self._sharding = None
        self._placement: Optional[str] = None
        self._metrics_repository = None
        self._save_key = None
        self._delete_dropped = False

    def add_check(self, check: Check) -> "PartitionedVerificationRunBuilder":
        self.checks.append(check)
        return self

    def add_checks(self, checks: Sequence[Check]) -> "PartitionedVerificationRunBuilder":
        self.checks.extend(checks)
        return self

    def add_required_analyzer(self, analyzer: Analyzer) -> "PartitionedVerificationRunBuilder":
        self.required_analyzers.append(analyzer)
        return self

    def add_required_analyzers(self, analyzers: Sequence[Analyzer]) -> "PartitionedVerificationRunBuilder":
        self.required_analyzers.extend(analyzers)
        return self

    def with_batch_size(self, batch_size: int) -> "PartitionedVerificationRunBuilder":
        self._batch_size = batch_size
        return self

    def with_monitor(self, monitor) -> "PartitionedVerificationRunBuilder":
        self._monitor = monitor
        return self

    def with_sharding(self, sharding) -> "PartitionedVerificationRunBuilder":
        self._sharding = sharding
        return self

    def with_placement(self, placement: str) -> "PartitionedVerificationRunBuilder":
        self._placement = placement
        return self

    def use_repository(self, repository) -> "PartitionedVerificationRunBuilder":
        self._metrics_repository = repository
        return self

    def save_or_append_result(self, key) -> "PartitionedVerificationRunBuilder":
        self._save_key = key
        return self

    def delete_dropped_partitions(self) -> "PartitionedVerificationRunBuilder":
        """Retention: partitions absent from the incoming set are DELETED
        from the store after the merge (they were already excluded from
        the metrics by re-merge semantics)."""
        self._delete_dropped = True
        return self

    def run(self) -> "IncrementalVerificationResult":
        return VerificationSuite.verify_partitioned(
            self.store,
            self.dataset_name,
            self.partitions,
            self.checks,
            self.required_analyzers,
            checksums=self.checksums,
            batch_size=self._batch_size,
            monitor=self._monitor,
            sharding=self._sharding,
            placement=self._placement,
            metrics_repository=self._metrics_repository,
            save_or_append_results_with_key=self._save_key,
            delete_dropped=self._delete_dropped,
        )


class VerificationRunBuilderWithRepository(VerificationRunBuilder):
    """(reference `VerificationRunBuilder.scala:196-341`)."""

    def __init__(self, parent: VerificationRunBuilder, repository):
        self.__dict__.update(parent.__dict__)
        self._metrics_repository = repository

    def reuse_existing_results_for_key(
        self, key, fail_if_results_missing: bool = False
    ) -> "VerificationRunBuilderWithRepository":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key) -> "VerificationRunBuilderWithRepository":
        self._save_key = key
        return self

    def add_anomaly_check(
        self, anomaly_detection_strategy, analyzer: Analyzer, anomaly_check_config=None
    ) -> "VerificationRunBuilderWithRepository":
        """(reference `VerificationRunBuilder.scala:227-244`)."""
        description = f"Anomaly check for {analyzer}"
        config = anomaly_check_config or AnomalyCheckConfig(CheckLevel.WARNING, description)
        check = Check(config.level, config.description).is_newest_point_non_anomalous(
            self._metrics_repository,
            anomaly_detection_strategy,
            analyzer,
            config.with_tag_values,
            config.after_date,
            config.before_date,
        )
        self.checks.append(check)
        return self
