"""Safe vectorized predicate expressions over column batches.

The reference embeds SQL predicate strings into Catalyst expressions
(`Compliance`, reference `analyzers/Compliance.scala:37-53`; `where` filters
via `conditionalSelection`, `analyzers/Analyzer.scala:409-432`). Here
predicates are Python-syntax strings evaluated vectorized over numpy columns
with a whitelisted AST interpreter — no Spark, no eval().

Supported syntax::

    "att1 > 3"
    "att1 >= 2 and att2 < 10"          # elementwise and/or/not
    "att1 in ('a', 'b')"
    "att1 is not None"                  # null checks
    "notnull(att1) | (att2 == 0)"
    "length(att1) >= 3"
    "matches(att1, '^[A-Z]+$')"

Null semantics follow SQL-ish 3-valued logic collapsed to False: any
comparison against a null value yields False.
"""

from __future__ import annotations

import ast
import functools as _functools
import re
from typing import Callable, Dict, List, Tuple, Union

import numpy as np

Predicate = Union[str, Callable]


class DictColumn:
    """Lazy dictionary-encoded column operand: ``entries`` holds the
    DISTINCT values (object array with a trailing ``None`` sentinel for
    null/invalid rows) and ``codes`` indexes rows into it. Single-column
    ops against literals evaluate on the ENTRIES and gather by code —
    an `x in [...]` membership over 1M rows of a 40-category column costs
    one 41-element isin plus a gather instead of a 1M-row object hash pass.
    Anything the entry-level fast paths don't cover materializes via
    ``to_object`` (cached) and takes the ordinary numpy path."""

    __slots__ = ("entries", "codes", "_obj")

    def __init__(self, entries: np.ndarray, codes: np.ndarray):
        self.entries = entries  # object[num_entries + 1], [-1] is None
        self.codes = codes  # int32[rows], sentinel = len(entries) - 1
        self._obj = None

    def gather(self, per_entry: np.ndarray) -> np.ndarray:
        return per_entry[self.codes]

    def to_object(self) -> np.ndarray:
        if self._obj is None:
            self._obj = self.entries[self.codes]
        return self._obj


def _materialize(x):
    return x.to_object() if isinstance(x, DictColumn) else x


def _is_literal(x) -> bool:
    if x is None or isinstance(x, (str, bytes, bool, int, float, np.generic)):
        return True
    if isinstance(x, (list, tuple, set)):
        return all(_is_literal(v) for v in x)
    return False


class ExpressionError(ValueError):
    pass


def _as_bool(x) -> np.ndarray:
    if isinstance(x, DictColumn):
        x = x.to_object()
    arr = np.asarray(x)
    if arr.dtype == bool:
        return arr
    if arr.dtype == object:
        return np.array([bool(v) if v is not None else False for v in arr], dtype=bool)
    if np.issubdtype(arr.dtype, np.floating):
        return np.nan_to_num(arr, nan=0.0) != 0
    return arr != 0


def _null_mask(x) -> np.ndarray:
    arr = np.asarray(x)
    if arr.dtype == object:
        return np.array([v is None for v in arr], dtype=bool)
    if np.issubdtype(arr.dtype, np.floating):
        return np.isnan(arr)
    return np.zeros(arr.shape, dtype=bool)


_FUNCTIONS: Dict[str, Callable] = {
    "abs": np.abs,
    "length": lambda x: np.array(
        [len(v) if v is not None else np.nan for v in np.asarray(x, dtype=object)],
        dtype=np.float64,  # NaN at nulls so comparisons yield False
    ),
    "isnull": _null_mask,
    "notnull": lambda x: ~_null_mask(x),
    "startswith": lambda x, p: np.array(
        [v.startswith(p) if isinstance(v, str) else False for v in np.asarray(x, dtype=object)]
    ),
    "endswith": lambda x, p: np.array(
        [v.endswith(p) if isinstance(v, str) else False for v in np.asarray(x, dtype=object)]
    ),
    "contains": lambda x, p: np.array(
        [p in v if isinstance(v, str) else False for v in np.asarray(x, dtype=object)]
    ),
    "matches": lambda x, p: np.array(
        [bool(re.search(p, v)) if isinstance(v, str) else False for v in np.asarray(x, dtype=object)]
    ),
    "floor": np.floor,
    "ceil": np.ceil,
    "sqrt": np.sqrt,
    # SQL COALESCE(col, default): nulls (None / NaN) replaced by the
    # default — the form the reference's isNonNegative/isPositive emit
    # (`checks/Check.scala:734,751`)
    "coalesce": lambda x, v: np.where(_null_mask(x), v, np.asarray(x)),
}

def _neq(a, b) -> np.ndarray:
    # null on either side -> False (3-valued logic collapsed), like NotIn
    a_arr, b_arr = np.asarray(a), np.asarray(b)
    # implicit-cast path: uncastable strings behave as null (False), same
    # as the == / < / > coercion
    if a_arr.dtype == object and b_arr.shape == () and _is_number(b_arr.item()):
        c = _coerce_object_numeric(a_arr)
        with np.errstate(invalid="ignore"):
            return np.not_equal(c, b_arr) & ~np.isnan(c)
    if b_arr.dtype == object and a_arr.shape == () and _is_number(a_arr.item()):
        c = _coerce_object_numeric(b_arr)
        with np.errstate(invalid="ignore"):
            return np.not_equal(a_arr, c) & ~np.isnan(c)
    return ~_eq(a, b) & ~_null_mask(a) & ~_null_mask(b)


_CMP = {
    ast.Eq: lambda a, b: _eq(a, b),
    ast.NotEq: _neq,
    ast.Lt: lambda a, b: _num_cmp(a, b, np.less),
    ast.LtE: lambda a, b: _num_cmp(a, b, np.less_equal),
    ast.Gt: lambda a, b: _num_cmp(a, b, np.greater),
    ast.GtE: lambda a, b: _num_cmp(a, b, np.greater_equal),
}

_BIN = {
    ast.Add: np.add,
    ast.Sub: np.subtract,
    ast.Mult: np.multiply,
    ast.Div: np.divide,
    ast.Mod: np.mod,
    ast.Pow: np.power,
    ast.FloorDiv: np.floor_divide,
}


def _coerce_object_numeric(a_arr: np.ndarray):
    """SQL implicit cast of a string column for a numeric comparison:
    parse to float64, unparseable/null -> NaN (behaves as null)."""
    import pandas as pd

    return pd.to_numeric(pd.Series(a_arr), errors="coerce").to_numpy(dtype=np.float64)


def _is_number(x) -> bool:
    return isinstance(x, (int, float, np.integer, np.floating)) and not isinstance(x, bool)


def _eq(a, b) -> np.ndarray:
    a_arr, b_arr = np.asarray(a), np.asarray(b)
    # SQL implicit cast: object column vs numeric scalar ('5' = 5 holds)
    if a_arr.dtype == object and b_arr.shape == () and _is_number(b_arr.item()):
        with np.errstate(invalid="ignore"):
            return np.equal(_coerce_object_numeric(a_arr), b_arr)
    if b_arr.dtype == object and a_arr.shape == () and _is_number(a_arr.item()):
        with np.errstate(invalid="ignore"):
            return np.equal(a_arr, _coerce_object_numeric(b_arr))
    if a_arr.dtype == object or b_arr.dtype == object:
        out = a_arr == b_arr
        return _as_bool(out) & ~_null_mask(a) & ~_null_mask(b if b_arr.shape else a)
    with np.errstate(invalid="ignore"):
        return np.equal(a, b)


def _num_cmp(a, b, op) -> np.ndarray:
    a_arr, b_arr = np.asarray(a), np.asarray(b)
    # vectorized SQL implicit cast for object column vs numeric scalar
    if a_arr.dtype == object and b_arr.shape == () and _is_number(b_arr.item()):
        with np.errstate(invalid="ignore"):
            return op(_coerce_object_numeric(a_arr), b_arr)
    if b_arr.dtype == object and a_arr.shape == () and _is_number(a_arr.item()):
        with np.errstate(invalid="ignore"):
            return op(a_arr, _coerce_object_numeric(b_arr))
    if a_arr.dtype == object or b_arr.dtype == object:
        null = _null_mask(a_arr) | _null_mask(b_arr)
        a_f = np.where(null, None, a_arr) if a_arr.dtype == object else a_arr
        out = np.zeros(np.broadcast_shapes(a_arr.shape, np.shape(b_arr)), dtype=bool)
        a_b = np.broadcast_to(a_arr, out.shape)
        b_b = np.broadcast_to(b_arr, out.shape)
        for i in np.ndindex(out.shape):
            av, bv = a_b[i], b_b[i]
            if av is None or bv is None:
                continue
            try:
                out[i] = op(av, bv)
            except TypeError:
                # SQL implicit cast: string vs number comparison coerces the
                # string side ("5" >= 0 is true in Spark); uncastable
                # strings behave as null (False)
                try:
                    out[i] = op(float(av), float(bv))
                except (TypeError, ValueError):
                    pass
        return out
    with np.errstate(invalid="ignore"):
        return op(a, b)


class _Evaluator(ast.NodeVisitor):
    def __init__(self, columns: Dict[str, np.ndarray]):
        self.columns = columns

    def visit(self, node):  # noqa: D102
        method = "visit_" + node.__class__.__name__
        visitor = getattr(self, method, None)
        if visitor is None:
            raise ExpressionError(f"unsupported syntax: {node.__class__.__name__}")
        return visitor(node)

    def visit_Expression(self, node):
        return self.visit(node.body)

    def visit_Name(self, node):
        if node.id in self.columns:
            return self.columns[node.id]
        if node.id in ("None", "null"):
            return None
        raise ExpressionError(f"unknown column: {node.id}")

    def visit_Constant(self, node):
        return node.value

    def _one_compare(self, left, op, right) -> np.ndarray:
        # dictionary-encoded operand vs literal: evaluate on the DISTINCT
        # entries (incl. the None sentinel, which every path maps to False)
        # and gather per row — O(entries + rows) instead of per-row object
        # work
        if isinstance(left, DictColumn) and _is_literal(right):
            return left.gather(self._one_compare(left.entries, op, right))
        if isinstance(right, DictColumn) and _is_literal(left):
            return right.gather(self._one_compare(left, op, right.entries))
        left = _materialize(left)
        right = _materialize(right)
        if isinstance(op, (ast.In, ast.NotIn)):
            if isinstance(right, (str, int, float)) and not isinstance(right, bool):
                # `x in ('abc')`: Python collapses 1-element parens to a
                # scalar, but in the SQL dialect this is a 1-element IN
                # list (there is no substring-membership in this grammar)
                right = [right]
            if not isinstance(right, (list, tuple, set)):
                raise ExpressionError("`in` requires a literal list/tuple")
            left_arr = np.asarray(left)
            if left_arr.dtype == object:
                # np.isin on object dtype degrades to O(n*k) elementwise
                # comparison; pandas isin is one C hash pass (an
                # is_contained_in over 1M rows x 100 categories is 50x+
                # faster this way)
                import pandas as pd

                part = pd.Series(left_arr).isin(list(right)).to_numpy()
            else:
                part = np.isin(left_arr, list(right))
            if isinstance(op, ast.NotIn):
                part = ~part & ~_null_mask(left)
            return part
        if isinstance(op, (ast.Is, ast.IsNot)):
            if right is not None:
                raise ExpressionError("`is` only supports None")
            part = _null_mask(left)
            if isinstance(op, ast.IsNot):
                part = ~part
            return part
        return _CMP[type(op)](left, right)

    def visit_Compare(self, node):
        left = self.visit(node.left)
        result = None
        for op, comparator in zip(node.ops, node.comparators):
            right = self.visit(comparator)
            part = _as_bool(self._one_compare(left, op, right))
            result = part if result is None else (result & part)
            left = right
        return result

    def visit_BoolOp(self, node):
        parts = [_as_bool(self.visit(v)) for v in node.values]
        out = parts[0]
        for p in parts[1:]:
            out = (out & p) if isinstance(node.op, ast.And) else (out | p)
        return out

    def visit_UnaryOp(self, node):
        val = self.visit(node.operand)
        if isinstance(node.op, ast.Not):
            return ~_as_bool(val)
        if isinstance(node.op, ast.USub):
            return np.negative(val)
        if isinstance(node.op, ast.UAdd):
            return val
        raise ExpressionError("unsupported unary op")

    def visit_BinOp(self, node):
        op = _BIN.get(type(node.op))
        if op is None:
            raise ExpressionError("unsupported binary op")
        with np.errstate(invalid="ignore", divide="ignore"):
            return op(
                _materialize(self.visit(node.left)),
                _materialize(self.visit(node.right)),
            )

    def visit_Call(self, node):
        # case-insensitive lookup: SQL spellings (COALESCE, LENGTH) parse
        # as ordinary Python calls and must resolve too
        fn = None
        if isinstance(node.func, ast.Name):
            fn = _FUNCTIONS.get(node.func.id) or _FUNCTIONS.get(node.func.id.lower())
        if fn is None:
            raise ExpressionError("only whitelisted functions allowed")
        args = [self.visit(a) for a in node.args]
        if (
            args
            and isinstance(args[0], DictColumn)
            and all(_is_literal(a) for a in args[1:])
        ):
            # string functions (length/matches/startswith/...) evaluate per
            # DISTINCT entry and gather; the None sentinel flows through each
            # function's own null handling (NaN length, False matches)
            return args[0].gather(fn(args[0].entries, *args[1:]))
        return fn(*[_materialize(a) for a in args])

    def visit_Tuple(self, node):
        return tuple(self.visit(e) for e in node.elts)

    def visit_List(self, node):
        return [self.visit(e) for e in node.elts]


#: SQL keywords the translator maps to the Python grammar (case-insensitive)
_SQL_WORD_MAP = {"and": "and", "or": "or", "not": "not", "null": "None",
                 "true": "True", "false": "False"}


def _translate_sql_predicate(src: str) -> str:
    """Translate the Spark-SQL predicate subset the reference emits into
    the Python-syntax grammar: `=`/`<>` comparisons, AND/OR/NOT, IN
    lists, IS (NOT) NULL, backquoted identifiers, ''-escaped string
    literals, and SQL function names (reference `checks/Check.scala:
    786-799,734,751,913,942`; `examples/BasicExample.scala`). Keywords
    match case-insensitively, as Spark's parser does."""
    tokens: List[Tuple[str, str]] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c.isspace():
            i += 1
        elif c in ("'", '"'):
            # Spark accepts single- OR double-quoted string literals, with
            # a doubled quote char as the escape
            q = c
            j, buf = i + 1, []
            while j < n:
                if src[j] == q:
                    if j + 1 < n and src[j + 1] == q:
                        buf.append(q)
                        j += 2
                        continue
                    break
                buf.append(src[j])
                j += 1
            if j >= n:
                raise ExpressionError(f"unterminated string literal in {src!r}")
            tokens.append(("str", "".join(buf)))
            i = j + 1
        elif c == "`":
            j = src.find("`", i + 1)
            if j < 0:
                raise ExpressionError(f"unterminated `identifier` in {src!r}")
            name = src[i + 1 : j]
            if not name.isidentifier():
                raise ExpressionError(
                    f"column name {name!r} is not expressible in predicates "
                    "(rename the column to a valid identifier)"
                )
            tokens.append(("name", name))
            i = j + 1
        elif c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            tokens.append(("word", src[i:j]))
            i = j
        elif c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            while j < n and (src[j].isdigit() or src[j] in ".eE" or (
                src[j] in "+-" and src[j - 1] in "eE"
            )):
                j += 1
            tokens.append(("num", src[i:j]))
            i = j
        elif src[i : i + 2] in ("<=", ">=", "!=", "=="):
            tokens.append(("op", src[i : i + 2]))
            i += 2
        elif src[i : i + 2] == "<>":
            tokens.append(("op", "!="))
            i += 2
        elif c == "=":
            tokens.append(("op", "=="))
            i += 1
        else:
            tokens.append(("op", c))
            i += 1

    out: List[str] = []
    k = 0
    while k < len(tokens):
        kind, text = tokens[k]
        low = text.lower() if kind == "word" else None
        if kind == "str":
            out.append(repr(text))
        elif kind == "name":
            out.append(text)
        elif kind == "word" and low == "is":
            # IS [NOT] NULL
            if k + 2 < len(tokens) and tokens[k + 1][1].lower() == "not" and tokens[k + 2][1].lower() == "null":
                out.append("is not None")
                k += 2
            elif k + 1 < len(tokens) and tokens[k + 1][1].lower() == "null":
                out.append("is None")
                k += 1
            else:
                raise ExpressionError(f"IS must be followed by [NOT] NULL in {src!r}")
        elif kind == "word" and low == "in":
            # IN ( a, b, ... ) -> in [a, b, ...] (a 1-element SQL list must
            # not become a Python scalar paren-expression)
            if k + 1 >= len(tokens) or tokens[k + 1][1] != "(":
                raise ExpressionError(f"IN must be followed by a value list in {src!r}")
            out.append("in [")
            depth = 1
            k += 1  # consume the opening paren
            closed = False
            while k + 1 < len(tokens):
                k += 1
                tk, tt = tokens[k]
                if tk == "op" and tt == "(":
                    depth += 1
                elif tk == "op" and tt == ")":
                    depth -= 1
                    if depth == 0:
                        out.append("]")
                        closed = True
                        break
                out.append(repr(tt) if tk == "str" else tt)
            if not closed:
                raise ExpressionError(f"unbalanced IN list in {src!r}")
        elif kind == "word" and low in _SQL_WORD_MAP:
            out.append(_SQL_WORD_MAP[low])
        elif (
            kind == "word"
            and low in _FUNCTIONS
            and k + 1 < len(tokens)
            and tokens[k + 1] == ("op", "(")
        ):
            # a whitelisted function name is only a function when CALLED;
            # Spark resolves a bare `Length`/`Matches` as a column identifier
            out.append(low)
        else:
            out.append(text)
        k += 1
    return " ".join(out)


@_functools.lru_cache(maxsize=512)
def _parse_predicate(src: str) -> ast.AST:
    """Predicates re-evaluate once per batch per pass; ast.parse is pure,
    so the parses cache (thread-safe via lru_cache). Strings that are not
    valid Python expressions get one shot through the Spark-SQL
    translator, so reference check definitions run verbatim."""
    try:
        return ast.parse(src, mode="eval")
    except SyntaxError as py_exc:
        try:
            return ast.parse(_translate_sql_predicate(src), mode="eval")
        except (SyntaxError, ExpressionError) as sql_exc:
            raise ExpressionError(
                f"predicate {src!r} is neither a valid Python expression "
                f"({py_exc}) nor translatable SQL ({sql_exc})"
            ) from None


def evaluate_predicate(predicate: Predicate, columns: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """Evaluate a predicate to a boolean mask of length ``n``.

    ``columns`` maps column name -> numpy array (float64+NaN for numerics,
    object+None for strings). Callables receive the dict and must return a
    boolean array.
    """
    if callable(predicate):
        # user callables see plain arrays, never the DictColumn operand
        columns = {k: _materialize(v) for k, v in columns.items()}
        result = predicate(columns)
    else:
        result = _Evaluator(columns).visit(_parse_predicate(predicate))
    mask = _as_bool(result)
    if mask.shape == ():
        mask = np.full(n, bool(mask))
    if mask.shape != (n,):
        raise ExpressionError(f"predicate produced shape {mask.shape}, expected ({n},)")
    return mask
