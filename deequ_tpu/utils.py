"""Small shared utilities."""

from __future__ import annotations

from collections import OrderedDict
from typing import Any


class BoundedLRU:
    """A bounded mapping with least-recently-USED eviction for compiled
    program caches: a hot key touched on every run stays resident while
    cold keys age out. (The previous bounded caches evicted FIFO, so a
    long-lived service could evict its hottest program while one-shot keys
    lingered.) A lock guards the compound lookup-then-reorder/evict steps
    so concurrent readers/writers (the engine's partial pool) keep plain
    dict.get semantics — get never raises."""

    def __init__(self, max_size: int):
        import threading

        self.max_size = int(max_size)
        self._lock = threading.Lock()
        self._data: "OrderedDict[Any, Any]" = OrderedDict()

    def get(self, key, default=None):
        with self._lock:
            try:
                value = self._data[key]
            except KeyError:
                return default
            self._data.move_to_end(key)
            return value

    def __setitem__(self, key, value) -> None:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
            elif len(self._data) >= self.max_size:
                self._data.popitem(last=False)
            self._data[key] = value

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._data

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def pop(self, key, default=None):
        with self._lock:
            return self._data.pop(key, default)

    def keys(self):
        with self._lock:
            return list(self._data.keys())

    def clear(self) -> None:
        with self._lock:
            self._data.clear()


#: warn-once latch for env_number (one line per env var per process)
_ENV_WARNED: set = set()


def env_number(env: str, default, cast, minimum=None):
    """The shared "warn once, keep the default" env-knob parser (the
    DEEQU_TPU_SCAN_DEADLINE_S convention): unparseable values — and, with
    ``minimum``, out-of-range ones — log ONE warning per process per
    variable and fall back to ``default`` instead of crashing the path
    that read them. Knobs whose fallback is not a constant (the watchdog's
    derived deadline) keep their own parsers."""
    import logging
    import os

    raw = os.environ.get(env)
    if raw is None:
        return default
    try:
        value = cast(raw)
        if minimum is not None and value < minimum:
            raise ValueError(raw)
    except ValueError:
        if env not in _ENV_WARNED:
            _ENV_WARNED.add(env)
            logging.getLogger(__name__).warning(
                "ignoring invalid %s=%r; using the default %s",
                env, raw, default,
            )
        return default
    return value


def env_str(env: str, default=None):
    """Shared reader for STRING-valued env knobs (paths, placement names,
    JSON plans): no parsing to fall back from, but one choke point that
    keeps every knob read on the ``utils.env_*`` surface the invariant
    linter (tools/statlint, the env-knob-convention check) can see."""
    import os

    return os.environ.get(env, default)


def env_flag(env: str, default: bool) -> bool:
    """Shared reader for BOOLEAN env knobs following the repo's "0 means
    off" convention: unset (or empty) keeps ``default``, the literal
    ``"0"`` means False, anything else means True. Knobs with richer
    semantics (tri-state probes, strict 0/1 validation with warn-once)
    keep their own parsers and a statlint baseline entry."""
    import os

    raw = os.environ.get(env)
    if raw is None or raw == "":
        return default
    return raw != "0"
