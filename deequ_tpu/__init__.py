"""deequ_tpu: a TPU-native data-quality framework.

"Unit tests for data" with the capabilities of deequ
(https://github.com/awslabs/deequ), re-designed TPU-first: analyzer states
are fixed-shape array pytrees, per-batch updates are fused jit'd XLA
reductions (Pallas kernels for sketch hot loops), rows shard over a
jax.sharding.Mesh, and state merges are collective semigroup sums.

See SURVEY.md for the structural analysis of the reference this build
follows.
"""

from . import config  # noqa: F401  (sets up x64 before anything else)
from . import observability  # noqa: F401  (tracing + flight recorder)
from .checks import Check, CheckLevel, CheckStatus
from .data import ColumnKind, Dataset, Schema
from .repository import (
    AnalysisResult,
    FileSystemMetricsRepository,
    InMemoryMetricsRepository,
    MetricsRepository,
    ResultKey,
)
from .verification import (
    AnomalyCheckConfig,
    VerificationResult,
    VerificationRunBuilder,
    VerificationSuite,
)
from .metrics import (
    BucketDistribution,
    BucketValue,
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    Failure,
    HistogramMetric,
    KeyedDoubleMetric,
    KLLMetric,
    Metric,
    Success,
)

__version__ = "0.1.0"

__all__ = [
    "observability",
    "AnalysisResult",
    "AnomalyCheckConfig",
    "FileSystemMetricsRepository",
    "InMemoryMetricsRepository",
    "MetricsRepository",
    "ResultKey",
    "BucketDistribution",
    "Check",
    "CheckLevel",
    "CheckStatus",
    "VerificationResult",
    "VerificationRunBuilder",
    "VerificationSuite",
    "BucketValue",
    "ColumnKind",
    "Dataset",
    "Distribution",
    "DistributionValue",
    "DoubleMetric",
    "Entity",
    "Failure",
    "HistogramMetric",
    "KLLMetric",
    "KeyedDoubleMetric",
    "Metric",
    "Schema",
    "Success",
    "__version__",
]
