"""Applicability checking: verify that a check / set of analyzers is
compatible with a schema BEFORE running on production data, by generating
random records matching the schema and executing against them
(reference `analyzers/applicability/Applicability.scala:162-273`).
"""

from __future__ import annotations

import random
import string
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from .analyzers import Analyzer
from .checks import Check
from .constraints import Constraint
from .data import ColumnKind, ColumnSchema, Dataset, Schema

NUM_RECORDS = 1000  # reference `Applicability.scala:240`


def generate_random_data(schema: Schema, num_records: int = NUM_RECORDS, seed: int = 42) -> Dataset:
    """Random rows matching a schema; nullable columns get ~1% nulls
    (reference `Applicability.generateRandomData`, `:240-272`)."""
    rng = random.Random(seed)
    nprng = np.random.default_rng(seed)
    columns: Dict[str, list] = {}
    for cs in schema.columns:
        values: List = []
        for _ in range(num_records):
            if cs.nullable and rng.random() < 0.01:
                values.append(None)
            elif cs.kind == ColumnKind.INTEGRAL:
                values.append(int(nprng.integers(-(2**31), 2**31 - 1)))
            elif cs.kind == ColumnKind.FRACTIONAL:
                values.append(float(nprng.normal()))
            elif cs.kind == ColumnKind.BOOLEAN:
                values.append(bool(rng.random() < 0.5))
            elif cs.kind == ColumnKind.TIMESTAMP:
                values.append(np.datetime64("2020-01-01") + np.timedelta64(rng.randrange(10**6), "s"))
            else:
                values.append("".join(rng.choices(string.ascii_letters, k=rng.randrange(1, 20))))
        columns[cs.name] = values
    return Dataset.from_dict(columns)


@dataclass
class CheckApplicability:
    """(reference `Applicability.scala:44-56`)."""

    is_applicable: bool
    failures: Dict[str, Optional[BaseException]]
    constraint_applicabilities: Dict[Constraint, bool] = field(default_factory=dict)


@dataclass
class AnalyzersApplicability:
    is_applicable: bool
    failures: Dict[str, Optional[BaseException]]


class Applicability:
    @staticmethod
    def is_applicable_check(check: Check, schema: Schema) -> CheckApplicability:
        """Run the check against random data; a constraint is applicable if
        its metric computation did not fail (reference `Applicability.
        isApplicable(check, schema)`, `:162-199`)."""
        from .verification import VerificationSuite

        data = generate_random_data(schema)
        result = VerificationSuite.do_verification_run(data, [check])
        constraint_applicabilities: Dict[Constraint, bool] = {}
        failures: Dict[str, Optional[BaseException]] = {}
        for check_result in result.check_results.values():
            for cr in check_result.constraint_results:
                metric_failed = cr.metric is not None and cr.metric.value.is_failure
                missing = cr.metric is None
                applicable = not (metric_failed or missing)
                constraint_applicabilities[cr.constraint] = applicable
                if not applicable:
                    exc = (
                        cr.metric.value.exception
                        if cr.metric is not None and cr.metric.value.is_failure
                        else RuntimeError(cr.message or "missing metric")
                    )
                    # keyed by the CONSTRAINT's string, as the reference does
                    # (`Applicability.scala:176-177` maps
                    # `constraint.toString -> constraint`), so two failing
                    # constraints sharing one analyzer keep distinct entries;
                    # the reference returns a Seq and tolerates duplicate
                    # names — a dict needs a disambiguating suffix instead
                    name = str(cr.constraint)
                    if name in failures:
                        i = 2
                        while f"{name} #{i}" in failures:
                            i += 1
                        name = f"{name} #{i}"
                    failures[name] = exc
        return CheckApplicability(
            not failures, failures, constraint_applicabilities
        )

    @staticmethod
    def is_applicable_analyzers(
        analyzers: Sequence[Analyzer], schema: Schema
    ) -> AnalyzersApplicability:
        """(reference `Applicability.isApplicable(analyzers, schema)`,
        `:201-238`)."""
        from .runners.analysis_runner import AnalysisRunner

        data = generate_random_data(schema)
        context = AnalysisRunner.do_analysis_run(data, analyzers)
        failures: Dict[str, Optional[BaseException]] = {}
        for analyzer, metric in context.metric_map.items():
            if metric.value.is_failure:
                failures[str(analyzer)] = metric.value.exception
        return AnalyzersApplicability(not failures, failures)
