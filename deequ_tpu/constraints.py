"""Constraints: assertions over computed metrics.

A constraint pairs an analyzer with an assertion (and an optional value
picker narrowing the metric value first). Evaluation looks the metric up in
the analysis results, applies the picker, then the assertion, and converts
every error into a structured failure message instead of raising
(reference `constraints/Constraint.scala:36-682`,
`constraints/AnalysisBasedConstraint.scala:42-122`).
"""

from __future__ import annotations

import abc
import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence

from .analyzers import (
    Analyzer,
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Compliance,
    Correlation,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from .metrics import Distribution, Metric


class ConstraintStatus(enum.Enum):
    SUCCESS = "Success"
    FAILURE = "Failure"


class ConstrainableDataTypes(enum.Enum):
    """(reference `constraints/ConstrainableDataTypes.scala`)."""

    NULL = "Null"
    FRACTIONAL = "Fractional"
    INTEGRAL = "Integral"
    BOOLEAN = "Boolean"
    STRING = "String"
    NUMERIC = "Numeric"


class Constraint(abc.ABC):
    """Evaluable on a map of analyzer -> metric."""

    @abc.abstractmethod
    def evaluate(self, analysis_results: Dict[Analyzer, Metric]) -> "ConstraintResult":
        ...


@dataclass(frozen=True)
class ConstraintResult:
    constraint: Constraint
    status: ConstraintStatus
    message: Optional[str] = None
    metric: Optional[Metric] = None


class ConstraintDecorator(Constraint):
    """(reference `constraints/Constraint.scala:41-57`)."""

    def __init__(self, inner: Constraint):
        self._inner = inner

    @property
    def inner(self) -> Constraint:
        if isinstance(self._inner, ConstraintDecorator):
            return self._inner.inner
        return self._inner

    def evaluate(self, analysis_results: Dict[Analyzer, Metric]) -> ConstraintResult:
        result = self._inner.evaluate(analysis_results)
        return ConstraintResult(self, result.status, result.message, result.metric)


class NamedConstraint(ConstraintDecorator):
    """Readable name wrapper (reference `constraints/Constraint.scala:59-69`)."""

    def __init__(self, constraint: Constraint, name: str):
        super().__init__(constraint)
        self._name = name

    def __str__(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return self._name


# messages (reference `constraints/AnalysisBasedConstraint.scala:46-52`)
MISSING_ANALYSIS_MESSAGE = "Missing Analysis, can't run the constraint!"
PROBLEMATIC_METRIC_PICKER = "Can't retrieve the value to assert on"
ASSERTION_EXCEPTION = "Can't execute the assertion"


class AnalysisBasedConstraint(Constraint):
    """Constraint evaluated against a metric computed by an analyzer
    (reference `constraints/AnalysisBasedConstraint.scala:42-122`)."""

    def __init__(
        self,
        analyzer: Analyzer,
        assertion: Callable[[Any], bool],
        value_picker: Optional[Callable[[Any], Any]] = None,
        hint: Optional[str] = None,
    ):
        self.analyzer = analyzer
        self.assertion = assertion
        self.value_picker = value_picker
        self.hint = hint

    def evaluate(self, analysis_results: Dict[Analyzer, Metric]) -> ConstraintResult:
        metric = analysis_results.get(self.analyzer)
        if metric is None:
            return ConstraintResult(self, ConstraintStatus.FAILURE, MISSING_ANALYSIS_MESSAGE)
        return self._pick_value_and_assert(metric)

    def _pick_value_and_assert(self, metric: Metric) -> ConstraintResult:
        if metric.value.is_failure:
            return ConstraintResult(
                self,
                ConstraintStatus.FAILURE,
                f"metric computation failed: {metric.value.exception}",
                metric,
            )
        try:
            raw = metric.value.get()
            if self.value_picker is not None:
                try:
                    assert_on = self.value_picker(raw)
                except Exception as exc:  # noqa: BLE001
                    return ConstraintResult(
                        self,
                        ConstraintStatus.FAILURE,
                        f"{PROBLEMATIC_METRIC_PICKER}: {exc}",
                        metric,
                    )
            else:
                assert_on = raw
            try:
                holds = self.assertion(assert_on)
            except Exception as exc:  # noqa: BLE001
                return ConstraintResult(
                    self, ConstraintStatus.FAILURE, f"{ASSERTION_EXCEPTION}: {exc}", metric
                )
            if holds:
                return ConstraintResult(self, ConstraintStatus.SUCCESS, metric=metric)
            hint = f" {self.hint}" if self.hint else ""
            return ConstraintResult(
                self,
                ConstraintStatus.FAILURE,
                f"Value: {assert_on} does not meet the constraint requirement!{hint}",
                metric,
            )
        except Exception as exc:  # noqa: BLE001
            return ConstraintResult(self, ConstraintStatus.FAILURE, str(exc), metric)

    def __str__(self) -> str:
        return f"AnalysisBasedConstraint({self.analyzer})"

    __repr__ = __str__


# ---------------------------------------------------------------------------
# Constraint factories (reference `constraints/Constraint.scala:83-682`)
# ---------------------------------------------------------------------------


def size_constraint(assertion, where=None, hint=None) -> Constraint:
    inner = AnalysisBasedConstraint(Size(where=where), assertion, hint=hint)
    return NamedConstraint(inner, f"SizeConstraint({Size(where=where)})")


def completeness_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = Completeness(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"CompletenessConstraint({analyzer})")


def uniqueness_constraint(columns: Sequence[str], assertion, hint=None) -> Constraint:
    analyzer = Uniqueness(tuple(columns))
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"UniquenessConstraint({analyzer})")


def distinctness_constraint(columns: Sequence[str], assertion, hint=None) -> Constraint:
    analyzer = Distinctness(tuple(columns))
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"DistinctnessConstraint({analyzer})")


def unique_value_ratio_constraint(columns: Sequence[str], assertion, hint=None) -> Constraint:
    analyzer = UniqueValueRatio(tuple(columns))
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"UniqueValueRatioConstraint({analyzer})")


def compliance_constraint(name, predicate, assertion, where=None, hint=None) -> Constraint:
    analyzer = Compliance(name, predicate, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"ComplianceConstraint({analyzer})")


def pattern_match_constraint(
    column, pattern, assertion, where=None, name=None, hint=None
) -> Constraint:
    analyzer = PatternMatch(column, pattern, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    display = name or f"PatternMatchConstraint({column}, {pattern})"
    return NamedConstraint(inner, display)


def entropy_constraint(column, assertion, hint=None) -> Constraint:
    analyzer = Entropy(column)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"EntropyConstraint({analyzer})")


def mutual_information_constraint(column_a, column_b, assertion, hint=None) -> Constraint:
    analyzer = MutualInformation((column_a, column_b))
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"MutualInformationConstraint({analyzer})")


def histogram_constraint(column, assertion, binning_func=None, max_bins=None, hint=None) -> Constraint:
    kwargs = {} if max_bins is None else {"max_detail_bins": max_bins}
    analyzer = Histogram(column, binning_func, **kwargs)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"HistogramConstraint({analyzer})")


def histogram_bin_constraint(
    column, assertion, binning_func=None, max_bins=None, hint=None
) -> Constraint:
    """Assertion over the number of distinct bins
    (reference `histogramBinConstraint`)."""
    kwargs = {} if max_bins is None else {"max_detail_bins": max_bins}
    analyzer = Histogram(column, binning_func, **kwargs)
    inner = AnalysisBasedConstraint(
        analyzer, assertion, value_picker=lambda d: float(d.number_of_bins), hint=hint
    )
    return NamedConstraint(inner, f"HistogramBinConstraint({analyzer})")


def kll_constraint(column, assertion, kll_parameters=None, hint=None) -> Constraint:
    analyzer = KLLSketch(column, kll_parameters)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"kllSketchConstraint({analyzer})")


def approx_quantile_constraint(
    column, quantile, assertion, relative_error=0.01, where=None, hint=None
) -> Constraint:
    analyzer = ApproxQuantile(column, quantile, relative_error, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"ApproxQuantileConstraint({analyzer})")


def min_length_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = MinLength(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"MinLengthConstraint({analyzer})")


def max_length_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = MaxLength(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"MaxLengthConstraint({analyzer})")


def min_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = Minimum(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"MinimumConstraint({analyzer})")


def max_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = Maximum(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"MaximumConstraint({analyzer})")


def mean_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = Mean(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"MeanConstraint({analyzer})")


def sum_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = Sum(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"SumConstraint({analyzer})")


def standard_deviation_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = StandardDeviation(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"StandardDeviationConstraint({analyzer})")


def approx_count_distinct_constraint(column, assertion, where=None, hint=None) -> Constraint:
    analyzer = ApproxCountDistinct(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"ApproxCountDistinctConstraint({analyzer})")


def correlation_constraint(column_a, column_b, assertion, where=None, hint=None) -> Constraint:
    analyzer = Correlation(column_a, column_b, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"CorrelationConstraint({analyzer})")


def data_type_constraint(column, data_type, assertion, where=None, hint=None) -> Constraint:
    """Assertion over the ratio of values inferred as ``data_type``
    (reference `dataTypeConstraint`, `constraints/Constraint.scala:592-624`)."""

    def ratio_types(ignore_unknown: bool, key: str, distribution: Distribution) -> float:
        absolute = (
            distribution.values[key].absolute if key in distribution.values else 0
        )
        if ignore_unknown:
            if absolute == 0:
                return 0.0
            total = sum(v.absolute for v in distribution.values.values())
            unknown = (
                distribution.values["Unknown"].absolute
                if "Unknown" in distribution.values
                else 0
            )
            denom = total - unknown
            return absolute / denom if denom > 0 else 0.0
        total = sum(v.absolute for v in distribution.values.values())
        return absolute / total if total > 0 else 0.0

    def picker(distribution: Distribution) -> float:
        if data_type == ConstrainableDataTypes.NULL:
            return ratio_types(False, "Unknown", distribution)
        if data_type == ConstrainableDataTypes.NUMERIC:
            return ratio_types(True, "Fractional", distribution) + ratio_types(
                True, "Integral", distribution
            )
        return ratio_types(True, data_type.value, distribution)

    analyzer = DataType(column, where)
    inner = AnalysisBasedConstraint(analyzer, assertion, value_picker=picker, hint=hint)
    return NamedConstraint(inner, f"DataTypeConstraint({analyzer})")


def anomaly_constraint(
    analyzer: Analyzer, assertion: Callable[[float], bool], hint=None
) -> Constraint:
    """Constraint whose assertion encapsulates an anomaly-detection decision
    over the repository history (reference `anomalyConstraint`)."""
    inner = AnalysisBasedConstraint(analyzer, assertion, hint=hint)
    return NamedConstraint(inner, f"AnomalyConstraint({analyzer})")
