"""URI-aware filesystem access — the analog of the reference's Hadoop
`FileSystem` indirection (`io/DfsUtils.scala:24-85`), which lets state blobs
and metric histories live on HDFS/S3 instead of one machine's disk.

Resolution order for a path with a scheme (``s3://``, ``gs://``,
``memory://``, ``hdfs://``, ...):

1. **fsspec** (`fsspec.core.url_to_fs`) — covers every registered fsspec
   protocol, including the in-memory filesystem used by tests and any
   optional backend the operator has installed (s3fs, gcsfs, adlfs...).
2. **pyarrow.fs** (`FileSystem.from_uri`) — pyarrow ships NATIVE S3, GCS
   and HDFS clients, so object stores work with no extra Python packages.

Schemeless paths (and ``file://``) use the local filesystem directly and
keep their exact previous behavior (atomic rename writes, os.makedirs).
Object-store writes are single-put (the store's own atomicity), matching
the reference's overwrite semantics on `FileSystem.create`.
"""

from __future__ import annotations

import os
import re
import tempfile
from contextlib import contextmanager
from typing import IO, Iterator, Optional, Tuple

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")

#: fsspec filesystem instances are cached per (protocol, storage options) by
#: fsspec itself; pyarrow from_uri is cheap. No extra caching needed here.


def _scheme_of(path: str) -> Optional[str]:
    m = _SCHEME_RE.match(path)
    if not m:
        return None
    return m.group(0)[:-3].lower()


def is_local(path: str) -> bool:
    scheme = _scheme_of(path)
    return scheme is None or scheme == "file"


def _strip_file_scheme(path: str) -> str:
    return path[7:] if path.startswith("file://") else path


class _FsspecFs:
    """Adapter over an fsspec AbstractFileSystem."""

    def __init__(self, fs, path: str):
        self.fs = fs
        self.path = path

    def open(self, mode: str) -> IO:
        return self.fs.open(self.path, mode)

    def exists(self) -> bool:
        return self.fs.exists(self.path)

    def makedirs(self) -> None:
        self.fs.makedirs(self.path, exist_ok=True)


class _ArrowFs:
    """Adapter over a pyarrow.fs.FileSystem."""

    def __init__(self, fs, path: str):
        self.fs = fs
        self.path = path

    def open(self, mode: str) -> IO:
        if "r" in mode:
            f = self.fs.open_input_file(self.path)
        else:
            f = self.fs.open_output_stream(self.path)
        if "b" in mode:
            return f
        import io as _io

        return _io.TextIOWrapper(f, encoding="utf-8")

    def exists(self) -> bool:
        import pyarrow.fs as pafs

        return self.fs.get_file_info(self.path).type != pafs.FileType.NotFound

    def makedirs(self) -> None:
        self.fs.create_dir(self.path, recursive=True)


def _resolve_remote(path: str):
    try:
        import fsspec

        fs, stripped = fsspec.core.url_to_fs(path)
        return _FsspecFs(fs, stripped)
    except (ImportError, ValueError):
        pass
    import pyarrow.fs as pafs

    fs, stripped = pafs.FileSystem.from_uri(path)
    return _ArrowFs(fs, stripped)


@contextmanager
def open_file(path: str, mode: str = "r") -> Iterator[IO]:
    """Open ``path`` for reading or writing, any supported scheme."""
    if is_local(path):
        with open(_strip_file_scheme(path), mode) as f:
            yield f
        return
    f = _resolve_remote(path).open(mode)
    try:
        yield f
    finally:
        f.close()


def exists(path: str) -> bool:
    if is_local(path):
        return os.path.exists(_strip_file_scheme(path))
    return _resolve_remote(path).exists()


def makedirs(path: str) -> None:
    if is_local(path):
        os.makedirs(_strip_file_scheme(path), exist_ok=True)
        return
    # object stores have no real directories; create is best-effort (the
    # memory filesystem wants it, S3/GCS ignore it)
    try:
        _resolve_remote(path).makedirs()
    except (NotImplementedError, OSError):
        pass


def join(base: str, *parts: str) -> str:
    """Path join that never turns URI '//' into '/'."""
    if is_local(base):
        return os.path.join(_strip_file_scheme(base), *parts)
    out = base.rstrip("/")
    for p in parts:
        out += "/" + p.strip("/")
    return out


def remove_file(path: str) -> None:
    """Delete one file, any supported scheme. Raises on failure (missing
    file included) — callers decide whether absence is fine."""
    if is_local(path):
        os.remove(_strip_file_scheme(path))
        return
    adapter = _resolve_remote(path)
    fs = adapter.fs
    if hasattr(fs, "rm_file"):  # fsspec
        fs.rm_file(adapter.path)
    elif hasattr(fs, "rm"):  # older fsspec
        fs.rm(adapter.path)
    else:  # pyarrow.fs
        fs.delete_file(adapter.path)


def remove_dir(path: str) -> None:
    """Delete a directory tree, any supported scheme."""
    if is_local(path):
        import shutil

        shutil.rmtree(_strip_file_scheme(path), ignore_errors=True)
        return
    adapter = _resolve_remote(path)
    fs = adapter.fs
    if hasattr(fs, "rm"):  # fsspec
        fs.rm(adapter.path, recursive=True)
    else:  # pyarrow.fs
        fs.delete_dir(adapter.path)


def list_dirs(path: str) -> list:
    """Immediate child directory NAMES of ``path``, sorted; [] when the
    path does not exist. Other failures (auth, network) RAISE — a store
    misconfiguration must not read as an empty listing."""
    if is_local(path):
        local = _strip_file_scheme(path)
        if not os.path.isdir(local):
            return []
        return sorted(
            e for e in os.listdir(local)
            if os.path.isdir(os.path.join(local, e))
        )
    adapter = _resolve_remote(path)
    fs = adapter.fs
    if hasattr(fs, "ls"):  # fsspec
        try:
            entries = fs.ls(adapter.path, detail=True)
        except FileNotFoundError:
            return []
        return sorted(
            os.path.basename(str(e["name"]).rstrip("/"))
            for e in entries
            if e.get("type") == "directory"
        )
    import pyarrow.fs as pafs

    infos = fs.get_file_info(
        pafs.FileSelector(adapter.path, allow_not_found=True)
    )
    return sorted(
        os.path.basename(i.path.rstrip("/"))
        for i in infos
        if i.type == pafs.FileType.Directory
    )


def list_files(path: str) -> list:
    """Immediate child FILE names of ``path``, sorted; [] when the path
    does not exist. Other failures (auth, network) RAISE — the same
    contract as :func:`list_dirs` (a store misconfiguration must not read
    as an empty listing)."""
    if is_local(path):
        local = _strip_file_scheme(path)
        if not os.path.isdir(local):
            return []
        return sorted(
            e for e in os.listdir(local)
            if os.path.isfile(os.path.join(local, e))
        )
    adapter = _resolve_remote(path)
    fs = adapter.fs
    if hasattr(fs, "ls"):  # fsspec
        try:
            entries = fs.ls(adapter.path, detail=True)
        except FileNotFoundError:
            return []
        return sorted(
            os.path.basename(str(e["name"]).rstrip("/"))
            for e in entries
            if e.get("type") == "file"
        )
    import pyarrow.fs as pafs

    infos = fs.get_file_info(
        pafs.FileSelector(adapter.path, allow_not_found=True)
    )
    return sorted(
        os.path.basename(i.path)
        for i in infos
        if i.type == pafs.FileType.File
    )


def write_text_atomic(path: str, payload: str) -> None:
    """Local: write-to-temp + rename so a crash mid-write never corrupts the
    target (the reference relies on HDFS create-overwrite the same way).
    Remote: single-put write — object stores make the put itself atomic."""
    if is_local(path):
        local = _strip_file_scheme(path)
        directory = os.path.dirname(os.path.abspath(local)) or "."
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, local)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return
    with open_file(path, "w") as f:
        f.write(payload)


def read_parquet_table(path, columns=None):
    """Parquet → pyarrow Table for any supported scheme (reference readers
    go through Hadoop input streams the same way)."""
    import pyarrow.parquet as pq

    if isinstance(path, (list, tuple)):
        paths = [str(p) for p in path]
        if all(is_local(p) for p in paths):
            return pq.read_table([_strip_file_scheme(p) for p in paths], columns=columns)
        # remote multi-file read (day-partitioned data on shared storage):
        # all paths must resolve to one filesystem
        resolved = [_resolve_remote(p) for p in paths]
        first = resolved[0]
        if any(type(r.fs) is not type(first.fs) for r in resolved):
            raise ValueError(
                f"all parquet paths must share one filesystem scheme, got {paths}"
            )
        return pq.read_table(
            [r.path for r in resolved], columns=columns, filesystem=first.fs
        )
    if is_local(str(path)):
        return pq.read_table(_strip_file_scheme(str(path)), columns=columns)
    fs = _resolve_remote(str(path))
    return pq.read_table(fs.path, columns=columns, filesystem=fs.fs)


def write_parquet_table(table, path: str) -> None:
    import pyarrow.parquet as pq

    if is_local(path):
        pq.write_table(table, _strip_file_scheme(path))
        return
    fs = _resolve_remote(path)
    pq.write_table(table, fs.path, filesystem=fs.fs)
