"""JVM interop: readers/writers for the reference's persisted state blobs.

The reference persists each state through a fixed per-type binary codec on
a JVM ``DataOutputStream`` (`analyzers/StateProvider.scala:187-311`) —
big-endian, no framing beyond the type's own fields. Reading those blobs
directly lets a jax_graft deployment take over (or run shadow to) an
existing JVM deequ pipeline without re-scanning history: day-partition
states written by Spark merge straight into our engine's semigroup states.

First leg: the ApproxCountDistinct (HLL++) state. The reference stores the
sketch as a packed word array — 512 six-bit registers, 10 per 64-bit word,
52 words (`StatefulHyperloglogPlus.scala`) — serialized as::

    int32  (big-endian)  number of words
    int64 * n (big-endian) the words

(`StateProvider.scala` ``persistLongArrayState``/``loadLongArrayState``).
Our engine keeps the registers UNPACKED (int32[512], device-friendly
``maximum`` merges); `ops/hll.py`'s ``words_to_registers`` /
``registers_to_words`` convert between the two layouts bit-exactly, so a
round trip through the JVM blob format is lossless and the cardinality
estimate is identical on both sides (same hash, same bias tables).
"""

from __future__ import annotations

import struct

import numpy as np

from .exceptions import CorruptStateError
from .ops.hll import M, NUM_WORDS, registers_to_words, words_to_registers

#: bytes of a well-formed reference HLL blob: the int32 count + 52 longs
JVM_HLL_BLOB_BYTES = 4 + 8 * NUM_WORDS


def read_jvm_hll_state_blob(blob: bytes, source: str = "<bytes>"):
    """Parse a reference ``ApproxCountDistinctState`` blob into a live
    :class:`~deequ_tpu.analyzers.states.ApproxCountDistinctState`.

    Raises :class:`CorruptStateError` on any structural violation (short
    read, wrong word count) — a JVM blob has no checksum of its own, so
    the fixed layout IS the integrity check."""
    from .analyzers.states import ApproxCountDistinctState

    if len(blob) < 4:
        raise CorruptStateError(
            "JVM HLL state blob", source,
            f"{len(blob)} bytes is too short for the word-count header",
        )
    (n_words,) = struct.unpack_from(">i", blob, 0)
    if n_words != NUM_WORDS:
        raise CorruptStateError(
            "JVM HLL state blob", source,
            f"word count {n_words} != {NUM_WORDS} (p=9 layout)",
        )
    if len(blob) != 4 + 8 * n_words:
        raise CorruptStateError(
            "JVM HLL state blob", source,
            f"{len(blob)} bytes != expected {4 + 8 * n_words}",
        )
    words = np.frombuffer(blob, dtype=">i8", count=n_words, offset=4)
    registers = words_to_registers(words.astype(np.int64).view(np.uint64))
    import jax.numpy as jnp

    return ApproxCountDistinctState(jnp.asarray(registers, dtype=jnp.int32))


def write_jvm_hll_state_blob(state) -> bytes:
    """Serialize an ``ApproxCountDistinctState`` into the reference's blob
    layout (the inverse of :func:`read_jvm_hll_state_blob`; exists so a
    jax_graft deployment can hand states BACK to a JVM pipeline, and so
    the round-trip tests need no checked-in binary fixture)."""
    registers = np.asarray(state.registers, dtype=np.int32)
    if registers.shape != (M,):
        raise ValueError(
            f"expected int32[{M}] registers, got shape {registers.shape}"
        )
    words = registers_to_words(registers)
    return struct.pack(">i", NUM_WORDS) + words.view(np.int64).astype(
        ">i8"
    ).tobytes()
