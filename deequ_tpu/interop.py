"""JVM interop: readers/writers for the reference's persisted state blobs.

The reference persists each state through a fixed per-type binary codec on
a JVM ``DataOutputStream`` (`analyzers/StateProvider.scala:187-311`) —
big-endian, no framing beyond the type's own fields. Reading those blobs
directly lets a jax_graft deployment take over (or run shadow to) an
existing JVM deequ pipeline without re-scanning history: day-partition
states written by Spark merge straight into our engine's semigroup states.

First leg: the ApproxCountDistinct (HLL++) state. The reference stores the
sketch as a packed word array — 512 six-bit registers, 10 per 64-bit word,
52 words (`StatefulHyperloglogPlus.scala`) — serialized as::

    int32  (big-endian)  number of words
    int64 * n (big-endian) the words

(`StateProvider.scala` ``persistLongArrayState``/``loadLongArrayState``).
Our engine keeps the registers UNPACKED (int32[512], device-friendly
``maximum`` merges); `ops/hll.py`'s ``words_to_registers`` /
``registers_to_words`` convert between the two layouts bit-exactly, so a
round trip through the JVM blob format is lossless and the cardinality
estimate is identical on both sides (same hash, same bias tables).

Second leg: the KLL sketch. The reference serializes a
``QuantileNonSample[Double]`` through a fixed binary codec — header
(sketchSize, shrinkingFactor, item count, number of compactors) followed by
each compactor's (numOfCompress, offset, buffer) — on the same big-endian
``DataOutputStream`` conventions
(`analyzers/catalyst/KLLSketchSerializer.scala:26-121`), and the enclosing
``KLLState`` adds the global max/min the sketch itself does not track
(`analyzers/KLLSketch.scala:42-55`). :func:`read_jvm_kll_state_blob` /
:func:`write_jvm_kll_state_blob` implement that layout against our
fixed-shape :class:`~deequ_tpu.ops.kll.KLLSketchState`: level ``l``'s
occupied item prefix is the reference's compactor-``l`` buffer, ``parity``
is the compactor's alternating ``offset``. Two lossy-by-design edges are
documented rather than hidden: item values ship as f64 but our buffers are
f32 (the engine's quantisation, `ops/kll.py` ITEM_DTYPE — re-reading
quantises once, inside the sketch's rank-error envelope), and
``numOfCompress``/``ticks`` do not survive (each side reconstructs its own
update bookkeeping; both only shape FUTURE compaction offsets, never the
already-folded items).

Third leg: the Gson metrics-history JSON
(`repository/AnalysisResultSerde.scala`). Our FS repository's entry layout
is deliberately Gson-shaped already, but adds ``formatVersion`` +
``checksum`` fields and keeps failed metrics; the JVM dialect has neither.
:func:`write_jvm_metrics_history_json` / :func:`read_jvm_metrics_history_json`
speak the exact reference dialect — successful metrics only, no envelope
fields, and the reference's literal ``"Mutlicolumn"`` entity spelling
(`metrics/Metric.scala`'s famous typo) accepted and emitted — so
reference-written histories load as first-class
:class:`~deequ_tpu.repository.AnalysisResult` inputs and ours read back on
the JVM.

Every reader raises a typed :class:`CorruptStateError` on structural
violations (short reads, negative lengths, trailing bytes, non-list JSON):
JVM payloads carry no checksum, so the fixed layout IS the integrity check.
"""

from __future__ import annotations

import struct

import numpy as np

from .exceptions import CorruptStateError
from .ops.hll import M, NUM_WORDS, registers_to_words, words_to_registers

#: bytes of a well-formed reference HLL blob: the int32 count + 52 longs
JVM_HLL_BLOB_BYTES = 4 + 8 * NUM_WORDS


def read_jvm_hll_state_blob(blob: bytes, source: str = "<bytes>"):
    """Parse a reference ``ApproxCountDistinctState`` blob into a live
    :class:`~deequ_tpu.analyzers.states.ApproxCountDistinctState`.

    Raises :class:`CorruptStateError` on any structural violation (short
    read, wrong word count) — a JVM blob has no checksum of its own, so
    the fixed layout IS the integrity check."""
    from .analyzers.states import ApproxCountDistinctState

    if len(blob) < 4:
        raise CorruptStateError(
            "JVM HLL state blob", source,
            f"{len(blob)} bytes is too short for the word-count header",
        )
    (n_words,) = struct.unpack_from(">i", blob, 0)
    if n_words != NUM_WORDS:
        raise CorruptStateError(
            "JVM HLL state blob", source,
            f"word count {n_words} != {NUM_WORDS} (p=9 layout)",
        )
    if len(blob) != 4 + 8 * n_words:
        raise CorruptStateError(
            "JVM HLL state blob", source,
            f"{len(blob)} bytes != expected {4 + 8 * n_words}",
        )
    words = np.frombuffer(blob, dtype=">i8", count=n_words, offset=4)
    registers = words_to_registers(words.astype(np.int64).view(np.uint64))
    import jax.numpy as jnp

    return ApproxCountDistinctState(jnp.asarray(registers, dtype=jnp.int32))


def write_jvm_hll_state_blob(state) -> bytes:
    """Serialize an ``ApproxCountDistinctState`` into the reference's blob
    layout (the inverse of :func:`read_jvm_hll_state_blob`; exists so a
    jax_graft deployment can hand states BACK to a JVM pipeline, and so
    the round-trip tests need no checked-in binary fixture)."""
    registers = np.asarray(state.registers, dtype=np.int32)
    if registers.shape != (M,):
        raise ValueError(
            f"expected int32[{M}] registers, got shape {registers.shape}"
        )
    words = registers_to_words(registers)
    return struct.pack(">i", NUM_WORDS) + words.view(np.int64).astype(
        ">i8"
    ).tobytes()


# ---------------------------------------------------------------------------
# KLL sketch state (KLLSketchSerializer.scala layout + KLLState min/max)
# ---------------------------------------------------------------------------

def write_jvm_kll_state_blob(state, shrinking_factor: float = 0.64) -> bytes:
    """Serialize a :class:`~deequ_tpu.ops.kll.KLLSketchState` into the
    reference's KLL codec::

        int32   sketchSize
        float64 shrinkingFactor
        int64   item count (exact folded-value count)
        int32   number of compactors (occupied levels; empty tail dropped)
        per compactor:
          int32   numOfCompress   (reference bookkeeping; written as 0 —
                                   our state tracks ``ticks`` instead)
          int32   offset          (the alternating compaction parity)
          int32   buffer length
          float64 * length        (the buffer items, ascending level)
        float64 globalMax
        float64 globalMin

    (all big-endian, ``DataOutputStream`` conventions). The trailing
    max/min pair is the enclosing ``KLLState``'s contribution
    (`analyzers/KLLSketch.scala:42-55`)."""
    items = np.asarray(state.items, dtype=np.float64)
    sizes = np.asarray(state.sizes, dtype=np.int64)
    parity = np.asarray(state.parity, dtype=np.int64)
    occupied = int(np.max(np.nonzero(sizes)[0])) + 1 if np.any(sizes) else 0
    out = [struct.pack(
        ">idqi", int(state.sketch_size), float(shrinking_factor),
        int(state.count), occupied,
    )]
    for level in range(occupied):
        n = int(sizes[level])
        out.append(struct.pack(">iii", 0, int(parity[level]), n))
        out.append(items[level, :n].astype(">f8").tobytes())
    out.append(struct.pack(">dd", float(state.g_max), float(state.g_min)))
    return b"".join(out)


def read_jvm_kll_state_blob(blob: bytes, source: str = "<bytes>"):
    """Parse a reference KLL state blob (see
    :func:`write_jvm_kll_state_blob` for the layout) into a live
    ``KLLSketchState`` plus the sketch's shrinking factor.

    Returns ``(state, shrinking_factor)``. The reconstructed state's
    ``ticks`` update counter is seeded from the exact count (the reference
    tracks ``numOfCompress`` instead; both only perturb FUTURE subsample
    offsets — the folded items, sizes, parities, count and min/max
    round-trip exactly, modulo the engine's documented f32 item
    quantisation). Raises :class:`CorruptStateError` on any structural
    violation."""
    import jax.numpy as jnp

    from .ops.kll import MAX_LEVELS, kll_init

    def corrupt(detail: str) -> CorruptStateError:
        return CorruptStateError("JVM KLL state blob", source, detail)

    header = struct.calcsize(">idqi")
    if len(blob) < header:
        raise corrupt(f"{len(blob)} bytes is too short for the header")
    sketch_size, shrinking_factor, count, n_compactors = struct.unpack_from(
        ">idqi", blob, 0
    )
    # the reference's sketchSize defaults to 2048 and is a user-visible
    # accuracy knob in the hundreds-to-thousands; a 16-bit bound keeps a
    # corrupt header from provoking a multi-GiB buffer allocation (the
    # fixed-shape state allocates 32 levels x 4*sketchSize f32 items)
    if sketch_size < 1 or sketch_size > (1 << 16):
        raise corrupt(f"implausible sketchSize {sketch_size}")
    if not (0.0 < shrinking_factor <= 1.0):
        raise corrupt(f"shrinkingFactor {shrinking_factor} outside (0, 1]")
    if count < 0:
        raise corrupt(f"negative item count {count}")
    if not (0 <= n_compactors <= MAX_LEVELS):
        raise corrupt(
            f"compactor count {n_compactors} outside [0, {MAX_LEVELS}]"
        )
    # parse the FULL structure before allocating the fixed-shape state:
    # nothing bigger than the blob itself materializes until every length,
    # range and trailer check has passed
    buf_len = 4 * int(sketch_size)
    buffers = []
    offset = header
    for level in range(n_compactors):
        if len(blob) < offset + 12:
            raise corrupt(f"truncated compactor header at level {level}")
        _num_compress, level_offset, n = struct.unpack_from(">iii", blob, offset)
        offset += 12
        if n < 0 or n > buf_len:
            raise corrupt(
                f"compactor {level} buffer length {n} outside [0, {buf_len}]"
            )
        if level_offset not in (0, 1):
            raise corrupt(f"compactor {level} offset {level_offset} not 0/1")
        if len(blob) < offset + 8 * n:
            raise corrupt(f"truncated compactor {level} buffer")
        buffers.append(
            (level_offset, np.frombuffer(blob, dtype=">f8", count=n,
                                         offset=offset).astype(np.float64))
        )
        offset += 8 * n
    if len(blob) != offset + 16:
        raise corrupt(
            f"{len(blob)} bytes != expected {offset + 16} "
            "(globalMax/globalMin trailer)"
        )
    g_max, g_min = struct.unpack_from(">dd", blob, offset)
    state = kll_init(int(sketch_size))
    items = np.array(state.items)  # writable host copy
    sizes = np.zeros(MAX_LEVELS, dtype=np.int32)
    parity = np.zeros(MAX_LEVELS, dtype=np.int32)
    for level, (level_offset, buf) in enumerate(buffers):
        items[level, :len(buf)] = buf
        sizes[level] = len(buf)
        parity[level] = level_offset
    state = state.replace(
        items=jnp.asarray(items, dtype=state.items.dtype),
        sizes=jnp.asarray(sizes, dtype=jnp.int32),
        parity=jnp.asarray(parity, dtype=jnp.int32),
        ticks=jnp.asarray(
            min(int(count), np.iinfo(np.int32).max), dtype=jnp.int32
        ),
        count=jnp.asarray(int(count), dtype=state.count.dtype),
        g_min=jnp.asarray(g_min, dtype=state.g_min.dtype),
        g_max=jnp.asarray(g_max, dtype=state.g_max.dtype),
    )
    return state, float(shrinking_factor)


# ---------------------------------------------------------------------------
# Gson metrics-history JSON (AnalysisResultSerde.scala dialect)
# ---------------------------------------------------------------------------

#: the reference's Entity enumeration spells the multicolumn member
#: "Mutlicolumn" (`metrics/Metric.scala`); the JVM dialect must emit and
#: accept that literal spelling or round trips break on exactly the
#: Uniqueness/Correlation-style metrics interop exists for
_JVM_MULTICOLUMN = "Mutlicolumn"


def write_jvm_metrics_history_json(results) -> str:
    """Serialize AnalysisResults into the reference's Gson metrics-history
    dialect: a JSON array of ``{"resultKey": {"dataSetDate", "tags"},
    "analyzerContext": {"metricMap": [{"analyzer", "metric"}, ...]}}``
    records — no ``formatVersion``, no ``checksum``, successful metrics
    only (the reference persists ``Try`` successes), and the JVM's literal
    ``"Mutlicolumn"`` entity spelling. Analyzers our serde cannot express
    as reference JSON are skipped, like the repository writer does."""
    import json

    from .metrics import Entity
    from .repository.serde import (
        SerializationError,
        serialize_analyzer,
        serialize_metric,
    )

    records = []
    for result in results:
        pairs = []
        for analyzer, metric in result.analyzer_context.metric_map.items():
            if metric.value.is_failure:
                continue
            try:
                pair = {
                    "analyzer": serialize_analyzer(analyzer),
                    "metric": serialize_metric(metric),
                }
            except SerializationError:
                continue
            if pair["metric"].get("entity") == Entity.MULTICOLUMN.value:
                pair["metric"]["entity"] = _JVM_MULTICOLUMN
            pairs.append(pair)
        records.append(
            {
                "resultKey": {
                    "dataSetDate": result.result_key.data_set_date,
                    "tags": result.result_key.tags_dict,
                },
                "analyzerContext": {"metricMap": pairs},
            }
        )
    return json.dumps(records)


def read_jvm_metrics_history_json(payload: str, source: str = "<json>"):
    """Parse a reference-written Gson metrics history into a list of
    :class:`~deequ_tpu.repository.AnalysisResult`. Raises
    :class:`CorruptStateError` on structural violations (invalid JSON, a
    non-array root, records missing their key/context shape) — JVM
    histories carry no checksum, so the layout is the integrity check."""
    import json

    from .metrics import Entity
    from .repository import AnalysisResult, ResultKey
    from .repository.serde import deserialize_analyzer, deserialize_metric
    from .runners.context import AnalyzerContext

    def corrupt(detail: str) -> CorruptStateError:
        return CorruptStateError("JVM metrics-history JSON", source, detail)

    try:
        records = json.loads(payload)
    except ValueError as exc:
        raise corrupt(f"invalid JSON: {exc}") from exc
    if not isinstance(records, list):
        raise corrupt(f"root is {type(records).__name__}, expected an array")
    results = []
    for i, record in enumerate(records):
        try:
            key = ResultKey(
                record["resultKey"]["dataSetDate"],
                record["resultKey"].get("tags", {}),
            )
            metric_map = {}
            for pair in record["analyzerContext"]["metricMap"]:
                metric_d = dict(pair["metric"])
                if metric_d.get("entity") == _JVM_MULTICOLUMN:
                    metric_d["entity"] = Entity.MULTICOLUMN.value
                analyzer = deserialize_analyzer(pair["analyzer"])
                metric_map[analyzer] = deserialize_metric(metric_d)
        except (KeyError, TypeError, ValueError) as exc:
            raise corrupt(f"record {i}: {exc}") from exc
        results.append(AnalysisResult(key, AnalyzerContext(metric_map)))
    return results
