"""Resumable ingest: periodic algebraic-state checkpoints through the
existing :class:`StatePersister` machinery.

A multi-batch fold is a left fold of commutative-semigroup states over the
batch sequence, so the state after batch ``k`` plus the remaining batches
``k+1..n`` determines the final state EXACTLY — the same algebraic property
the reference exploits for incremental computation over growing data
(`analyzers/StateProvider.scala:37-66`). The checkpointer persists every
analyzer's state every ``every`` batches (scan-battery states AND host
accumulator states such as grouping frequency tables), together with a
meta record pinning the fold position and shape; an interrupted run then
resumes from the last checkpoint and provably equals the uninterrupted
run: the engine re-enters the batch loop at the checkpoint index with the
restored states, and batch indices are preserved so index-keyed logic
(the KLL sampler offsets) replays identically.

The meta record validates before any resume: batch size, row count, and
the battery fingerprint must match, else the checkpoint is ignored and the
run starts fresh (a checkpoint from a DIFFERENT run shape must never leak
states into this one). Completion clears the meta so a finished run's
checkpoint cannot resurrect into the next.

Mesh-shape independence: the meta record deliberately pins NOTHING about
the device mesh. Mesh runs checkpoint their states in CANONICAL (merged)
form (`ElasticMeshFold.canonical`), and the engine rounds mesh batch
sizes to the re-shard-ladder quantum (`parallel.mesh_batch_quantum`), so
batch boundaries — and therefore this record's ``batch_size`` — are
identical at every ladder rung. A checkpoint taken on 8 devices resumes
on 4, on 1, or on the plain host tier (pinned by
``tests/test_elastic_mesh.py::TestCrossShapeCheckpoint``).
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

_logger = logging.getLogger(__name__)

_META_FILENAME = "ingest-checkpoint-meta.json"


@dataclass(frozen=True)
class _HostStateKey:
    """Analyzer-shaped persistence key for host accumulator states whose
    run-time key is not an Analyzer (the shared per-grouping-set frequency
    tables key on ``("__grouping__", cols)``). Duck-types the two members
    the providers read: ``name`` and a stable ``repr``."""

    ident: str

    @property
    def name(self) -> str:
        return "HostAccumulator"

    def __repr__(self) -> str:
        return f"HostAccumulator({self.ident})"


def _host_key(key: Any) -> Any:
    from ..analyzers.base import Analyzer

    if isinstance(key, Analyzer):
        return key
    return _HostStateKey(str(key))


def _snapshot_state(state: Any) -> Any:
    """An immutable-for-our-purposes copy of a host accumulator state at
    checkpoint time. Frequency tables copy their merged series (a spilled
    table raises its usual budget error — it cannot be persisted anyway);
    everything else deep-copies (host states are small numpy/pandas
    structures)."""
    from ..analyzers.grouping import FrequenciesAndNumRows

    if isinstance(state, FrequenciesAndNumRows):
        return FrequenciesAndNumRows(
            state.frequencies.copy(), state.num_rows,
            list(state.group_columns),
        )
    import copy

    return copy.deepcopy(state)


def battery_fingerprint(
    scan_analyzers: Sequence[Any], host_keys: Sequence[Any]
) -> str:
    """Stable identity of what a run folds: analyzer reprs + host keys.
    Hashed so the meta record stays small for wide batteries."""
    import hashlib

    payload = "\x1f".join(
        [repr(a) for a in scan_analyzers] + [str(k) for k in sorted(
            (str(k) for k in host_keys)
        )]
    )
    return hashlib.sha1(payload.encode("utf-8")).hexdigest()[:16]


@dataclass
class ResumePoint:
    """What a resumed run starts from. ``host_batch_index`` can run AHEAD
    of ``batch_index``: the host tier folds accumulators per batch on the
    submitting thread while scan states advance per chunk fold, so each
    records its own high-water mark and the resumed run replays each from
    its own position."""

    batch_index: int
    scan_states: List[Any]
    host_states: Dict[Any, Any]
    host_batch_index: int = 0


class IngestCheckpointer:
    """Checkpoint/resume driver around one StateLoader+StatePersister.

    ``provider`` must be both a loader and a persister (the same contract
    streaming sessions put on their state providers). Meta rides next to
    the states: as a JSON file for directory-backed providers (anything
    with a ``path``), else through the provider itself under a sentinel
    key (the in-memory provider stores arbitrary objects).
    """

    def __init__(self, provider: Any, every: int = 8):
        from ..analyzers.state_provider import StateLoader, StatePersister

        if not (
            isinstance(provider, StateLoader)
            and isinstance(provider, StatePersister)
        ):
            raise TypeError(
                "checkpoint provider must be both a StateLoader and a "
                f"StatePersister, got {type(provider).__name__}"
            )
        if int(every) < 1:
            raise ValueError(f"checkpoint interval must be >= 1, got {every}")
        import threading

        self.provider = provider
        self.every = int(every)
        #: observability: (batch_index, n_states) per save, newest last
        self.saves: List[Tuple[int, int]] = []
        #: resume points discarded because a meta record or state blob
        #: failed its integrity check (each cost a fresh fold, never a crash)
        self.corrupt_discards: int = 0
        #: saves/completes refused because their pass was FENCED by a newer
        #: one (see begin_run) — the watchdog-abandoned-zombie defense
        self.fenced_saves: int = 0
        #: serializes saves AND the epoch check: a stale pass that is
        #: mid-save when a new pass begins finishes atomically before the
        #: new pass's first save, so save sequences never interleave
        self._save_lock = threading.Lock()
        self._epoch = 0

    def begin_run(self) -> int:
        """Fence every earlier pass and return this pass's epoch token.

        The scan watchdog CANCELS a stalled pass by abandoning its thread —
        Python cannot kill it, so the zombie keeps folding and would keep
        CHECKPOINTING concurrently with the failover re-run over the same
        provider. Interleaved saves could splice a meta record from one
        pass over state blobs from another: every per-blob checksum passes,
        the fingerprint matches, and a resume would silently skip batches.
        Epoch fencing closes this: each engine pass calls ``begin_run()``
        before touching the store, and ``save``/``complete`` carrying a
        stale epoch are refused under the save lock (counted in
        ``fenced_saves``)."""
        with self._save_lock:
            self._epoch += 1
            return self._epoch

    def _current(self, epoch: Optional[int]) -> bool:
        return epoch is None or epoch == self._epoch

    # -- meta ----------------------------------------------------------------

    def _meta_path(self) -> Optional[str]:
        path = getattr(self.provider, "path", None)
        if path is None:
            return None
        from .. import io as dio

        return dio.join(path, _META_FILENAME)

    _META_SENTINEL = _HostStateKey("__ingest_checkpoint_meta__")

    def _write_meta(self, meta: Optional[Dict[str, Any]]) -> None:
        path = self._meta_path()
        if path is not None:
            from .. import io as dio

            if meta is None:
                if dio.exists(path):
                    dio.write_text_atomic(path, json.dumps({"cleared": True}))
            else:
                from ..integrity import checksum_json

                # the meta record pins WHICH states form a resume point; a
                # flipped byte in it (batch index, fingerprint) would splice
                # wrong states into a resumed fold — checksum it like every
                # other durable payload
                meta = dict(meta)
                meta["checksum"] = checksum_json(
                    {k: v for k, v in meta.items() if k != "checksum"}
                )
                dio.write_text_atomic(path, json.dumps(meta))
            return
        self.provider.persist(self._META_SENTINEL, meta)

    def _read_meta(self) -> Optional[Dict[str, Any]]:
        """The persisted meta record, or None. Raises
        :class:`CorruptStateError` when the record exists but is torn or
        fails its checksum — ``load`` turns that into a fresh-start
        fallback, never a crash."""
        path = self._meta_path()
        if path is not None:
            from .. import io as dio
            from ..exceptions import CorruptStateError

            if not dio.exists(path):
                return None
            with dio.open_file(path, "r") as fh:
                raw = fh.read()
            try:
                meta = json.loads(raw)
            except ValueError as exc:
                from ..observability import record_failure

                torn = CorruptStateError(
                    "ingest-checkpoint meta", path, str(exc)
                )
                torn.__cause__ = exc
                record_failure(torn)
                raise torn
            if meta.get("cleared"):
                return None
            if "checksum" in meta:
                from ..integrity import verify_json_checksum

                verify_json_checksum(
                    {k: v for k, v in meta.items() if k != "checksum"},
                    meta["checksum"], "ingest-checkpoint meta", path,
                )
            else:
                from ..integrity import warn_once_unchecksummed

                warn_once_unchecksummed("ingest-checkpoint meta", path)
            return meta
        return self.provider.load(self._META_SENTINEL)

    # -- checkpoint lifecycle ------------------------------------------------

    def save(
        self,
        batch_index: int,
        batch_size: int,
        num_rows: int,
        scan_analyzers: Sequence[Any],
        scan_states: Sequence[Any],
        host_states: Dict[Any, Any],
        host_batch_index: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> None:
        """Persist one checkpoint with an invalidate-first protocol: the
        meta record is CLEARED, then every state overwrites its slot, then
        the new meta lands. States share fixed per-analyzer keys, so a
        crash mid-save would otherwise leave the PREVIOUS meta (batch K)
        paired with a mix of batch-K and batch-K' states — a resume would
        then silently double-fold batches K..K'. With the invalidation
        marker, a torn save costs the resume point (the next run starts
        from batch 0) but can never corrupt results.

        ``epoch`` (from :meth:`begin_run`) fences stale passes: a save
        carrying an epoch that is no longer current is refused whole —
        see begin_run for why."""
        from .faults import fault_point

        with self._save_lock:
            if not self._current(epoch):
                self.fenced_saves += 1
                _logger.warning(
                    "checkpoint save at batch %d refused: its pass was "
                    "fenced by a newer one (watchdog-abandoned zombie?)",
                    batch_index,
                )
                return
            fault_point("checkpoint", tag=str(batch_index))
            self._write_meta(None)  # invalidate: states are about to be torn
            for analyzer, state in zip(scan_analyzers, scan_states):
                self.provider.persist(analyzer, state)
            for key, state in host_states.items():
                # SNAPSHOT mutable accumulator states: the run keeps folding
                # into the live object after this save, and an in-memory
                # provider stores references — without the copy, the
                # "checkpoint" would silently track the live state and a
                # resume would double-fold every batch since the save
                self.provider.persist(_host_key(key), _snapshot_state(state))
            self._write_meta(
                {
                    "batch_index": int(batch_index),
                    "batch_size": int(batch_size),
                    "num_rows": int(num_rows),
                    "host_batch_index": int(
                        batch_index if host_batch_index is None else host_batch_index
                    ),
                    "fingerprint": battery_fingerprint(
                        scan_analyzers, list(host_states)
                    ),
                }
            )
            self.saves.append((int(batch_index), len(list(scan_analyzers))))

    def load(
        self,
        batch_size: int,
        num_rows: int,
        scan_analyzers: Sequence[Any],
        host_keys: Sequence[Any],
        monitor: Optional[Any] = None,
    ) -> Optional[ResumePoint]:
        """The resume point for a run of this exact shape, or None (no
        checkpoint / shape mismatch / any state missing / CORRUPT
        checkpoint). Corruption — a torn meta record, a failed meta or
        state-blob checksum — costs the resume point, never the run: the
        fold restarts from batch 0 and recomputes bit-exactly, which is the
        same outcome the invalidate-first save protocol already accepts for
        a torn save. ``monitor`` (a RunMonitor), when given, counts the
        discard under ``corrupt_quarantined``."""
        from ..exceptions import CorruptStateError

        def discard(what: str, exc: BaseException) -> None:
            self.corrupt_discards += 1
            if monitor is not None:
                monitor.bump("corrupt_quarantined")
            from ..observability import trace as _trace

            _trace.add_event(
                "checkpoint_discarded", what=what,
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            _logger.warning(
                "ingest checkpoint discarded (%s is corrupt; restarting "
                "the fold from batch 0): %s", what, exc,
            )

        try:
            meta = self._read_meta()
        except CorruptStateError as exc:
            discard("meta record", exc)
            return None
        if not meta:
            return None
        fingerprint = battery_fingerprint(scan_analyzers, host_keys)
        if (
            int(meta.get("batch_size", -1)) != int(batch_size)
            or int(meta.get("num_rows", -1)) != int(num_rows)
            or meta.get("fingerprint") != fingerprint
        ):
            _logger.info(
                "ingest checkpoint ignored: run shape changed "
                "(meta=%s, now batch_size=%d num_rows=%d fp=%s)",
                meta, batch_size, num_rows, fingerprint,
            )
            return None
        try:
            scan_states = [self.provider.load(a) for a in scan_analyzers]
        except CorruptStateError as exc:
            discard("a scan state blob", exc)
            return None
        if any(s is None for s in scan_states):
            return None
        host_states = {}
        for key in host_keys:
            try:
                state = self.provider.load(_host_key(key))
            except CorruptStateError as exc:
                discard("a host accumulator state blob", exc)
                return None
            if state is None:
                return None
            # snapshot on the way OUT too: the resumed run folds into this
            # object, and an in-memory provider must keep holding the
            # checkpoint-time value until the next save overwrites it
            host_states[key] = _snapshot_state(state)
        batch_index = int(meta["batch_index"])
        return ResumePoint(
            batch_index, scan_states, host_states,
            host_batch_index=int(meta.get("host_batch_index", batch_index)),
        )

    def complete(self, epoch: Optional[int] = None) -> None:
        """Mark the run finished: clears the meta so the NEXT run over this
        provider starts fresh instead of resuming a done fold. A stale
        (fenced) pass completing late must NOT clear the active pass's
        resume point."""
        with self._save_lock:
            if not self._current(epoch):
                self.fenced_saves += 1
                return
            self._write_meta(None)
