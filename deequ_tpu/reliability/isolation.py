"""Analyzer isolation and tier failover for the fused scan.

The fused ``PackedScanProgram`` buys its 38x scan-sharing speedup by making
every analyzer ride ONE XLA program — which also makes every failure a
battery-wide failure: the reference degrades per-analyzer because Spark
aggregates are independent expressions (`AnalysisRunner.scala:320-323`),
but one bad value, one device fault or one blown compile here used to kill
all N analyzers' metrics at once. This module restores the reference's
contract on top of the fused engine:

- **Tier ladder** (:func:`_attempt_tiered`): a device-infrastructure
  failure (XLA runtime error, lost device) re-runs the SAME battery on the
  host ingest tier — fresh states, no device residue; an OOM first bisects
  the batch size (smaller padded batches shrink the live feature set)
  before falling back. Every hop is recorded on the RunMonitor so the
  service's placement router learns to keep the battery off the sick tier.
- **Battery bisection** (:func:`run_scan_resilient`): a failure that
  survives the tier ladder is attributed by bisecting the analyzer battery
  and re-running partitions — log2(N) extra passes in the worst case —
  until exactly the faulty analyzers are alone in their partitions and
  degrade to typed ``Failure`` metrics while everyone else completes.
- **Host-accumulator knockout**: host-side accumulators (grouping
  frequency tables, histogram fallbacks) fold OUTSIDE the fused program,
  so they need no bisection — each update fn is guarded, and the first
  error knocks only that accumulator out for the rest of the pass.

Interrupts (``KeyboardInterrupt`` and other non-``Exception``
``BaseException``s) deliberately pass through every layer here: an
operator ^C or a preemption must abort the run, not degrade it — the
resumable-ingest checkpoints are the recovery story for those.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..observability import record_failure
from ..observability import trace as _trace

_logger = logging.getLogger(__name__)

#: batch-size floor below which OOM bisection gives up (padding dominates)
_MIN_BISECT_BATCH = 1 << 10

#: OOM bisections per attempt before the tier ladder falls through to host
_MAX_OOM_BISECTIONS = 3


def classify_failure(exc: BaseException) -> str:
    """``"mesh"`` | ``"oom"`` | ``"device"`` | ``"data"`` — what recovery
    applies.

    Typed exceptions from our own taxonomy classify directly; raw
    jax/jaxlib runtime errors (which carry no type hierarchy worth
    matching on) classify by the XLA status phrases they embed. Anything
    else is a data/analyzer-level failure: re-running it elsewhere would
    fail the same way, so only bisection helps.

    Integrity taxonomy: :class:`ScanStallError` is a
    ``DeviceFailureException`` subclass and therefore classifies
    ``"device"`` — a watchdog-cancelled pass takes the tier-failover +
    placement-probation path like a thrown device fault.
    :class:`CorruptStateError` classifies ``"data"`` — a corrupt persisted
    payload reproduces identically on any tier, so the recovery is
    degradation (typed Failure metrics for exactly the analyzers that
    needed it) or the loader-level quarantine/fresh-fold fallbacks, never
    a pointless re-run elsewhere. :class:`ShardLossError` classifies
    ``"mesh"`` — one shard of a multi-device mesh died, which is
    MESH-recoverable (rebuild over the survivors, one ladder rung down)
    BEFORE the blunt host-tier failover applies; losses the engine's
    in-pass elastic layer could not absorb surface here and re-shard at
    the pass level."""
    from ..exceptions import (
        CorruptStateError,
        DeviceFailureException,
        DeviceOOMException,
        ShardLossError,
    )

    if isinstance(exc, CorruptStateError):
        return "data"
    if isinstance(exc, ShardLossError):
        return "mesh"
    if isinstance(exc, DeviceOOMException):
        return "oom"
    if isinstance(exc, DeviceFailureException):
        return "device"
    message = str(exc)
    if (
        "RESOURCE_EXHAUSTED" in message
        or "Out of memory" in message
        or "out of memory" in message.lower()
    ):
        return "oom"
    if type(exc).__name__ in ("XlaRuntimeError", "JaxRuntimeError") or any(
        marker in message
        for marker in ("INTERNAL:", "UNAVAILABLE:", "DATA_LOSS:", "ABORTED:")
    ):
        return "device"
    return "data"


@dataclass
class ResilientScanOutcome:
    """Per-analyzer results of a resilient scan: disjoint success states
    and typed errors, plus the host accumulator states/errors."""

    states: Dict[Any, Any] = field(default_factory=dict)
    errors: Dict[Any, BaseException] = field(default_factory=dict)
    host_states: Dict[Any, Any] = field(default_factory=dict)
    host_errors: Dict[Any, BaseException] = field(default_factory=dict)


def _guard_host_updates(
    host_updates: Dict[Any, Callable],
    host_errors: Dict[Any, BaseException],
    monitor,
) -> Dict[Any, Callable]:
    """Wrap each accumulator's update fn so one raising accumulator is
    knocked out (typed Failure later) without touching the others or the
    device battery."""

    def make(key, fn):
        def guarded(state, batch):
            if key in host_errors:
                return state
            try:
                return fn(state, batch)
            except Exception as exc:  # noqa: BLE001 - degrade only this key
                host_errors[key] = exc
                monitor.note_degraded(f"host:{key}")
                _logger.warning(
                    "host accumulator %s knocked out: %s", key, exc
                )
                return state

        return guarded

    return {key: make(key, fn) for key, fn in host_updates.items()}


def run_scan_resilient(
    run_pass: Callable,
    battery: Sequence[Any],
    make_host_states: Callable[[], Tuple[Dict[Any, Any], Dict[Any, Callable]]],
    monitor,
    *,
    batch_size: int,
    placement: Optional[str],
    sharding: Optional[Any] = None,
) -> ResilientScanOutcome:
    """Run the shared pass with isolation + failover.

    ``run_pass(analyzers, host_states, host_updates, placement, batch_size)
    -> (states, host_states)`` executes one engine pass (the runner owns
    engine construction); ``make_host_states() -> (states, update_fns)``
    builds FRESH host accumulators — retries must never refold into
    partially-updated state. ``sharding`` (the pass's mesh, if any) lets
    the tier ladder rebuild a DEGRADED mesh when a shard loss escapes the
    engine's in-pass recovery: a mesh-sharded ``run_pass`` must then also
    accept a ``sharding=`` keyword override (only ever passed after a
    mesh failure, so mesh-free callers keep their simpler signature).
    """
    outcome = ResilientScanOutcome()
    host_keys = list(make_host_states()[0])
    progress = {"host_done": not host_keys, "bisecting": False}

    def attempt(part: Tuple, with_host: bool):
        if with_host:
            host_states, host_updates = make_host_states()
            # keep already-knocked-out keys dead across retries: their
            # first error is the typed result, and refolding a partially
            # poisoned accumulator would just re-raise
            host_updates = _guard_host_updates(
                host_updates, outcome.host_errors, monitor
            )
        else:
            host_states, host_updates = {}, {}
        states, folded = _attempt_tiered(
            run_pass, part, host_states, host_updates,
            monitor, batch_size=batch_size, placement=placement,
            sharding=sharding,
        )
        return states, folded

    def degrade(part: Tuple, exc: BaseException) -> None:
        for analyzer in part:
            outcome.errors[analyzer] = exc
            monitor.note_degraded(repr(analyzer))
        if part:
            _trace.add_event(
                "analyzers_degraded", count=len(part),
                analyzers=[repr(a) for a in part[:8]],
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )

    def run_partition(part: Tuple):
        """Run one partition, bisecting on failure. Returns (fully_failed,
        signature): fully_failed means EVERY member degraded, and
        ``signature`` identifies the failure when they all failed alike."""
        try:
            states, folded = attempt(part, with_host=not progress["host_done"])
        except Exception as exc:  # noqa: BLE001 - typed degradation below
            signature = (type(exc), str(exc))
            if len(part) <= 1:
                if part:
                    degrade(part, exc)
                    _logger.warning(
                        "analyzer %r isolated as faulty: %s", part[0], exc
                    )
                else:
                    # a host-only pass failed outright: every accumulator
                    # that hasn't already got a more specific error shares
                    # the pass failure
                    for key in host_keys:
                        outcome.host_errors.setdefault(key, exc)
                    progress["host_done"] = True
                return True, signature
            if not progress["bisecting"]:
                progress["bisecting"] = True
                _logger.warning(
                    "fused battery of %d analyzers failed (%s: %s); "
                    "bisecting to isolate", len(part), type(exc).__name__, exc,
                )
            _trace.add_event(
                "isolation_bisect", partition=len(part),
                error=f"{type(exc).__name__}: {str(exc)[:200]}",
            )
            monitor.bump("isolation_reruns")
            mid = len(part) // 2
            left, right = part[:mid], part[mid:]
            failed_left, sig_left = run_partition(left)
            if failed_left and len(left) > 1 and sig_left == signature:
                # the left subtree (more than one member) reproduced the
                # parent failure WHOLESALE: this is a pass-level fault
                # (corrupt input, dead tier) no bisection can isolate —
                # bisecting the right half would burn ~2x its size in
                # identical full-data re-passes. A single faulty analyzer
                # never trips this: its clean siblings succeed, so no >1
                # subtree fully fails.
                _logger.warning(
                    "partition of %d reproduced the same failure wholesale; "
                    "degrading the remaining %d analyzers without further "
                    "re-passes", len(left), len(right),
                )
                degrade(right, exc)
                return True, signature
            failed_right, sig_right = run_partition(right)
            return (
                failed_left and failed_right
                and sig_left == sig_right == signature,
                signature,
            )
        for analyzer, state in zip(part, states):
            outcome.states[analyzer] = state
        if not progress["host_done"]:
            outcome.host_states = folded
            progress["host_done"] = True
        return False, None

    battery = tuple(battery)
    if battery or host_keys:
        run_partition(battery)
    if not progress["host_done"]:
        # every battery partition failed before any pass completed with the
        # accumulators attached — give them one dedicated battery-free pass
        try:
            _, folded = attempt((), with_host=True)
            outcome.host_states = folded
        except Exception as exc:  # noqa: BLE001
            for key in host_keys:
                outcome.host_errors.setdefault(key, exc)
    # a knocked-out accumulator's folded state is partial garbage: drop it
    for key in outcome.host_errors:
        outcome.host_states.pop(key, None)
    return outcome


def _attempt_tiered(
    run_pass: Callable,
    part: Tuple,
    host_states: Dict[Any, Any],
    host_updates: Dict[Any, Callable],
    monitor,
    *,
    batch_size: int,
    placement: Optional[str],
    sharding: Optional[Any] = None,
):
    """One partition through the tier ladder: mesh re-shard for escaped
    shard losses, then device (as placed) with OOM batch bisection, then
    host-tier failover for device-infrastructure failures when every
    member supports host partials."""
    bs = batch_size
    placement_now = placement
    oom_left = _MAX_OOM_BISECTIONS
    mesh_now = sharding
    mesh_overridden = False
    host_capable = bool(part) and all(
        getattr(a, "supports_host_partial", False) for a in part
    )
    while True:
        try:
            kwargs = {"placement": placement_now, "batch_size": bs}
            if mesh_overridden:
                kwargs["sharding"] = mesh_now
            return run_pass(part, dict(host_states), host_updates, **kwargs)
        except Exception as exc:  # noqa: BLE001 - ladder decides
            kind = classify_failure(exc)
            if kind == "mesh":
                smaller = _degraded_mesh(mesh_now, exc)
                if smaller is not None:
                    # re-shard BEFORE host failover: the pass re-runs whole
                    # on a mesh rebuilt over the surviving devices — the
                    # mesh analog of the device->host hop, one rung at a
                    # time (8->4->2->1), host only when the ladder is out
                    monitor.bump("mesh_reshards")
                    monitor.note_degraded("mesh:pass_reshard")
                    record_failure(exc)
                    _trace.add_event(
                        "mesh_reshard",
                        from_devices=int(mesh_now.devices.size),
                        to_devices=int(smaller.devices.size),
                        scope="pass",
                    )
                    _logger.warning(
                        "mesh pass failed with a shard loss (%s); re-running "
                        "the whole pass on a %d-device degraded mesh",
                        exc, int(smaller.devices.size),
                    )
                    mesh_now = smaller
                    mesh_overridden = True
                    host_states = _refresh_host_states(host_states, monitor)
                    continue
                # no smaller mesh possible: drop the (broken) mesh and
                # treat like a thrown device fault (tier failover below)
                mesh_now = None
                mesh_overridden = True
                kind = "device"
            if kind == "oom" and oom_left > 0 and _oom_bisection_futile(part, bs):
                # halving the batch shrinks the live FEATURE buffers but
                # never a device frequency table's fixed-shape
                # (slots + buffer) footprint — when the tables dominate
                # the partition's device memory, bisection re-passes are
                # pure waste; fall through to failover/battery-bisection
                # (which isolates the table scans so the runner's host
                # accumulator fallback takes the set)
                oom_left = 0
                _logger.warning(
                    "device OOM with frequency-table states dominating the "
                    "footprint; skipping futile batch bisection"
                )
            if (
                kind == "oom"
                and oom_left > 0
                and bs // 2 >= _MIN_BISECT_BATCH
                and placement_now != "host"
            ):
                oom_left -= 1
                bs //= 2
                monitor.bump("batch_bisections")
                record_failure(exc)
                _trace.add_event("oom_bisect", batch_size=bs)
                _logger.warning(
                    "device OOM (%s); bisecting batch size to %d", exc, bs
                )
                # host accumulators refold from scratch on the retry: the
                # failed pass left them partially updated
                host_states = _refresh_host_states(host_states, monitor)
                continue
            if kind in ("oom", "device") and placement_now != "host" and host_capable:
                monitor.bump("device_failovers")
                monitor.note_degraded(f"tier:device->{kind}")
                # the typed failure event + flight-recorder dump, then the
                # failover hop itself — a degraded run's trace shows the
                # failed device pass, the exception, and the host re-pass
                # as one connected tree
                record_failure(exc)
                _trace.add_event(
                    "device_failover", to="host", kind=kind,
                    analyzers=len(part),
                )
                _logger.warning(
                    "device tier failed (%s: %s); failing battery of %d "
                    "over to the host ingest tier",
                    type(exc).__name__, exc, len(part),
                )
                placement_now = "host"
                host_states = _refresh_host_states(host_states, monitor)
                continue
            raise


def _oom_bisection_futile(part: Tuple, batch_size: int) -> bool:
    """Whether an OOM cannot be relieved by halving the batch: the
    partition's device frequency TABLES (fixed-shape sorted table + key
    buffer, sized by ``slots``/``buffer_entries``, not by the batch)
    already outweigh the reclaimable per-batch feature bytes (~8B per row
    per analyzer, all of which a halving could at best free)."""
    from ..analyzers.grouping import DeviceFrequencyTableScan

    tables = [a for a in part if isinstance(a, DeviceFrequencyTableScan)]
    if not tables:
        return False
    table_bytes = sum(
        24 * a.slots + 8 * a.buffer_entries for a in tables
    )
    reclaimable = 8 * batch_size * max(1, len(part))
    return table_bytes > reclaimable


def _degraded_mesh(mesh, exc):
    """A mesh rebuilt over ``exc``'s surviving devices at the next ladder
    rung STRICTLY below the current size, or None when no smaller mesh is
    possible (single device, no rung fits, no mesh to begin with)."""
    if mesh is None:
        return None
    from ..parallel import make_mesh
    from ..parallel.elastic import mesh_ladder, next_rung

    devices = list(mesh.devices.flat)
    survivors = getattr(exc, "survivors", None)
    if survivors is None:
        lost = set(getattr(exc, "lost", ()) or (0,))
        survivors = [d for i, d in enumerate(devices) if i not in lost]
    if not survivors:
        return None
    rung = next_rung(
        [r for r in mesh_ladder() if r < len(devices)], len(survivors)
    )
    if rung is None:
        return None
    return make_mesh(devices=survivors[:rung])


def _refresh_host_states(host_states: Dict[Any, Any], monitor) -> Dict[Any, Any]:
    """Fresh identity states for the accumulators a failed pass partially
    updated (same keys; grouping tables re-empty, host_init re-runs)."""
    from ..analyzers.base import Analyzer
    from ..analyzers.grouping import FrequenciesAndNumRows

    fresh: Dict[Any, Any] = {}
    for key, state in host_states.items():
        if isinstance(state, FrequenciesAndNumRows):
            fresh[key] = FrequenciesAndNumRows.empty(list(state.group_columns))
        elif isinstance(key, Analyzer) and hasattr(key, "host_init"):
            fresh[key] = key.host_init()
        else:
            fresh[key] = state
    return fresh
