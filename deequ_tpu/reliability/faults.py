"""Deterministic fault injection at named engine/service sites.

The reliability layer's claims — analyzer isolation, tier failover,
resumable ingest, typed degradation — are only worth anything if they are
EXERCISED, and real device faults cannot be provoked on demand. This
module plants cheap ``fault_point(site, tag)`` probes at the places real
faults occur (device dispatch, compile, host partials, ingest folds, state
fetch, scheduler workers) and lets tests/tools arm them with a seeded,
fully deterministic plan: the same plan + seed produces the same faults at
the same sites in the same order, every run (the chaos-engineering analog
of the reference forcing 2 shuffle partitions to push merge code paths,
`SparkContextSpec.scala:75-84`).

Arming is explicit (``inject(...)`` context manager / ``install``), or
environment-driven for whole-process runs: ``DEEQU_TPU_FAULTS`` holds a
JSON list of spec dicts and ``DEEQU_TPU_FAULT_SEED`` the rng seed — the
``tools.chaos_soak`` entry point drives a full service this way. When
nothing is armed, a fault point is one global read and a ``None`` check.

Instrumented sites (grep ``fault_point(`` for ground truth):

===================  ========================================================
site                 fires
===================  ========================================================
``analyzer``         once per scan analyzer per pass, tag = ``repr(analyzer)``
``device_update``    before each fused device-batch dispatch, tag = batch idx
``compile``          when a fused program is first BUILT for a battery
``device_feed``      before features are placed on device
``host_partial``     before each host-tier partial, tag = batch idx
``ingest_fold``      before each host-tier chunk fold on device
``state_fetch``      before the packed device->host state fetch
``sharded_fold``     before a mesh ingest fold dispatch
``collective_merge`` before a collective state merge dispatch
``worker``           at job pickup in the service scheduler, tag = worker id
``checkpoint``       before an ingest checkpoint is persisted
``state_load``       in FileSystemStateProvider.load, tag = repr(analyzer)
``repository_load``  in the FS metrics repository's read-all, tag = path
``partition_store_load``  in PartitionStateStore.get, tag = dataset/partition
``stream_fold``      before a streaming session's fold mutates state
``coalesced_fold``   before a coalesced fast/device/fleet fold executes a
                     claimed group, tag = session key
``shard_probe``      per mesh shard in the heartbeat health probe, tag = shard
``frame_decode``     per ingest-plane frame before it folds, tag = frame idx
``prefetch``         per staged batch in the device feed pipeline, tag = idx
``host_heartbeat``   per host in the cluster membership scan, tag = host id
``ring_rebalance``   before a hash-ring host add/remove re-hashes key ranges
``lease_acquire``    at a compaction-lease election attempt, tag = lease path
``catalog_load``     before a tenant catalog document parses, tag = tenant
``row_gate``         per frame before the row-level conformance mask runs,
                     tag = ``tenant/dataset``
===================  ========================================================

The ``corrupt`` kind (a typed ``CorruptStateError``) injected at the three
load sites stands in for bit rot/torn writes the checksum layer would
detect; ``drift`` (a typed ``SchemaDriftError``) at ``stream_fold`` stands
in for a micro-batch whose schema drifted from the session contract. At
``catalog_load`` the ``corrupt`` kind stands in for a torn/garbled tenant
catalog document (the catalog quarantines it and keeps serving last-good);
at ``row_gate`` it stands in for a frame the conformance mask cannot even
be computed over (the gate surfaces it typed before anything folds).

The ingest kinds: ``frame_corrupt`` (a typed ``MalformedFrameError``)
injected at ``frame_decode`` stands in for torn/garbled Arrow IPC bytes a
producer shipped; ``feed_stall`` (a typed ``FeedStallError``) at
``prefetch`` stands in for the device feed pipeline wedging mid-pass —
with a ``delay_s`` it sleeps that long first, modeling a slow feed before
the stall is declared.

The mesh kinds: ``mesh_loss`` (a typed ``ShardLossError`` whose ``lost``
list carries the spec's ``shard``, default 0) stands in for a device or
process dying mid-pass — injected at ``sharded_fold``/``collective_merge``
it exercises the elastic salvage + re-shard path, at ``shard_probe`` it
makes the heartbeat declare that shard dead; ``shard_stall`` (a typed
``ShardStallError``, same payload) stands in for a shard that wedged
without raising and was declared lost by the heartbeat deadline.

The cluster kind: ``host_loss`` (a typed ``HostLossError`` whose ``host``
carries the probe tag) stands in for a whole worker PROCESS dying —
injected at ``host_heartbeat`` it makes the membership scan declare that
host dead and the front tier re-hash its ring range to survivors.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..exceptions import (
    AnalyzerFaultException,
    CorruptStateError,
    DeviceFailureException,
    DeviceOOMException,
    PoisonedBatchException,
    SchemaDriftError,
)

#: env vars arming a process-wide plan (JSON spec list / int seed)
FAULTS_ENV = "DEEQU_TPU_FAULTS"
FAULT_SEED_ENV = "DEEQU_TPU_FAULT_SEED"


class InjectedInterrupt(KeyboardInterrupt):
    """Simulated hard interruption (operator ^C / preemption). Deliberately
    a ``KeyboardInterrupt`` subclass: every recovery layer catches only
    ``Exception``, so this rides OUT of the engine exactly like a real
    SIGINT — the resumable-ingest tests use it to kill a run mid-fold."""


class WorkerCrash(RuntimeError):
    """Simulated death of a service worker mid-job (the Spark executor-loss
    analog). Raised inside the job attempt, it exercises the scheduler's
    defense-in-depth path: the job must terminate with a typed error or be
    retried, never hang its handle."""


#: fault kind -> exception factory (tag-aware where the type carries one)
def _make_error(
    kind: str, site: str, tag: str, shard: Optional[int] = None
) -> BaseException:
    note = f"injected fault at site={site!r} tag={tag!r}"
    if kind == "device":
        return DeviceFailureException(note)
    if kind == "oom":
        return DeviceOOMException(f"RESOURCE_EXHAUSTED: {note}")
    if kind == "poison":
        try:
            index = int(tag)
        except (TypeError, ValueError):
            index = -1
        return PoisonedBatchException(index, note)
    if kind == "analyzer":
        return AnalyzerFaultException(note)
    if kind == "interrupt":
        return InjectedInterrupt(note)
    if kind == "worker_death":
        return WorkerCrash(note)
    if kind == "corrupt":
        return CorruptStateError("injected payload", site, note)
    if kind == "drift":
        return SchemaDriftError(site, [note])
    if kind == "frame_corrupt":
        from ..exceptions import MalformedFrameError

        try:
            index = int(tag)
        except (TypeError, ValueError):
            index = -1
        return MalformedFrameError(site, note, frame_index=index)
    if kind == "feed_stall":
        from ..exceptions import FeedStallError

        return FeedStallError(site, note)
    if kind == "mesh_loss":
        from ..exceptions import ShardLossError

        return ShardLossError([0 if shard is None else shard], site, detail=note)
    if kind == "shard_stall":
        from ..exceptions import ShardStallError

        return ShardStallError([0 if shard is None else shard], site, detail=note)
    if kind == "host_loss":
        from ..cluster.membership import HostLossError

        return HostLossError(tag or site, site=site, detail=note)
    raise ValueError(f"unknown fault kind {kind!r}")


FAULT_KINDS = (
    "device", "oom", "poison", "analyzer", "interrupt", "worker_death",
    "stall", "corrupt", "drift", "mesh_loss", "shard_stall",
    "frame_corrupt", "feed_stall", "host_loss",
)

#: The fault-site REGISTRY: every ``fault_point(site, ...)`` planted in the
#: package must name a site listed here, and every site listed here must
#: have at least one live probe — both directions are machine-checked by
#: the invariant linter (tools/statlint, failure-registry check), so the
#: docstring table above and the chaos tooling can rely on this tuple as
#: ground truth instead of a grep.
KNOWN_FAULT_SITES = frozenset({
    "analyzer",
    "device_update",
    "compile",
    "device_feed",
    "host_partial",
    "ingest_fold",
    "state_fetch",
    "sharded_fold",
    "collective_merge",
    "worker",
    "checkpoint",
    "state_load",
    "repository_load",
    "partition_store_load",
    "stream_fold",
    "coalesced_fold",
    "shard_probe",
    "frame_decode",
    "prefetch",
    "host_heartbeat",
    "ring_rebalance",
    "lease_acquire",
    "catalog_load",
    "row_gate",
})


@dataclass
class FaultSpec:
    """One deterministic rule: at ``site``, on hits selected by ``at`` /
    ``every`` / ``p`` (and optionally narrowed by ``match`` against the
    tag), raise the ``kind`` error — at most ``count`` times (None =
    unlimited). ``kind="stall"`` sleeps ``delay_s`` instead of raising
    (compile-stall injection). Hit numbering is PER SITE and 1-based, so
    ``at=2`` means "the second time this site fires". ``shard`` is the
    mesh position the ``mesh_loss``/``shard_stall`` kinds declare lost
    (default 0; meaningless for other kinds)."""

    site: str
    kind: str
    at: Optional[int] = None
    every: Optional[int] = None
    p: float = 0.0
    count: Optional[int] = 1
    match: Optional[str] = None
    delay_s: float = 0.0
    shard: Optional[int] = None

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"fault kind {self.kind!r} not in {FAULT_KINDS}"
            )

    def to_dict(self) -> Dict:
        return {
            k: v
            for k, v in self.__dict__.items()
            if v not in (None, 0.0) or k in ("site", "kind")
        }

    @staticmethod
    def from_dict(d: Dict) -> "FaultSpec":
        return FaultSpec(**d)


class FaultInjector:
    """Armed fault plan. Deterministic: per-site hit counters plus ONE
    seeded ``random.Random`` consumed in probe order — identical call
    sequences see identical faults. Thread-safe: the service scheduler's
    workers and the engine's prefetch threads all probe concurrently."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        import random

        self.specs = list(specs)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._hits: Dict[str, int] = {}
        self._fired: List[str] = []
        self._spec_fired = [0] * len(self.specs)
        self._lock = threading.Lock()

    @property
    def fired(self) -> List[str]:
        """``"site:tag:kind"`` records of every fault fired, in order."""
        with self._lock:
            return list(self._fired)

    def fire(self, site: str, tag: str = "") -> None:
        delay = 0.0
        error: Optional[BaseException] = None
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            for i, spec in enumerate(self.specs):
                if spec.site != site:
                    continue
                if spec.match is not None and spec.match not in tag:
                    continue
                if spec.count is not None and self._spec_fired[i] >= spec.count:
                    continue
                selected = False
                if spec.at is not None:
                    selected = hit == spec.at
                elif spec.every is not None:
                    selected = hit % spec.every == 0
                elif spec.p > 0.0:
                    # one shared seeded stream, consumed ONLY for p-specs on
                    # their own site so unrelated probes don't shift it
                    selected = self._rng.random() < spec.p
                else:
                    selected = True
                if not selected:
                    continue
                self._spec_fired[i] += 1
                self._fired.append(f"{site}:{tag}:{spec.kind}")
                # every kind honors delay_s ("stall" sleeps and nothing
                # more; other kinds model a SLOW failure — a feed that
                # drags before wedging — by sleeping, then raising)
                delay = spec.delay_s
                if spec.kind != "stall":
                    error = _make_error(spec.kind, site, tag, shard=spec.shard)
                break
        if delay:
            time.sleep(delay)
        if error is not None:
            raise error


#: the armed injector (process-global; None = disarmed). Reads are
#: lock-free — arming mid-probe at worst misses one probe, which the
#: deterministic tests never do.
_ACTIVE: Optional[FaultInjector] = None
_ENV_CHECKED = False


def active_injector() -> Optional[FaultInjector]:
    global _ACTIVE, _ENV_CHECKED
    if _ACTIVE is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        from ..utils import env_str

        env = env_str(FAULTS_ENV)
        if env:
            specs = [FaultSpec.from_dict(d) for d in json.loads(env)]
            # deliberately NOT warn-and-fallback (like the plan itself): a
            # chaos drill with an unparseable seed must abort loudly, not
            # silently run a different fault sequence under seed 0
            _ACTIVE = FaultInjector(
                specs, seed=int(env_str(FAULT_SEED_ENV, "0"))
            )
    return _ACTIVE


def install(specs: Sequence[FaultSpec], seed: int = 0) -> FaultInjector:
    global _ACTIVE
    _ACTIVE = FaultInjector(specs, seed=seed)
    return _ACTIVE


def clear() -> None:
    global _ACTIVE, _ENV_CHECKED
    _ACTIVE = None
    _ENV_CHECKED = False


@contextmanager
def inject(*specs: FaultSpec, seed: int = 0):
    """Arm a plan for the enclosed block (the test-facing entry point)::

        with inject(FaultSpec("device_update", "device", at=2)) as inj:
            result = VerificationSuite.on_data(data).add_check(c).run()
        assert inj.fired
    """
    global _ACTIVE
    prior = _ACTIVE
    injector = install(specs, seed=seed)
    try:
        yield injector
    finally:
        _ACTIVE = prior


def fault_point(site: str, tag: str = "") -> None:
    """Probe planted at an instrumented site; near-free when disarmed."""
    injector = _ACTIVE
    if injector is None:
        if _ENV_CHECKED:
            return
        injector = active_injector()
        if injector is None:
            return
    injector.fire(site, tag)
