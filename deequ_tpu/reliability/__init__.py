"""Fault tolerance for the verification engine.

The reference inherits retry, speculative execution and partial-failure
semantics from Spark; the jax/XLA engine gets none of that for free, and a
production verification service cannot 500 a whole battery because one
column's sketch overflowed. This package is the substrate:

- :mod:`.faults` — deterministic, seeded fault injection at named engine
  and service sites (``fault_point``), so every recovery path below is
  exercised on demand instead of waiting for real hardware to misbehave;
- :mod:`.isolation` — analyzer isolation by battery bisection (exactly the
  faulty analyzers degrade to typed ``Failure`` metrics), device→host tier
  failover for XLA/runtime errors, and OOM-triggered batch bisection;
- :mod:`.checkpoint` — resumable multi-batch ingest: algebraic states
  checkpoint through the existing ``StatePersister`` every K batches, and
  an interrupted run resumes from the last checkpoint with results equal
  to the uninterrupted run;
- :mod:`.watchdog` — deadline monitoring for device/host passes: a pass
  that HANGS (rather than throws) is cancelled with a typed
  ``ScanStallError`` and takes the same tier-failover + placement-
  probation path as a thrown device fault.

See README "Failure semantics" for the operator-facing contract.
"""

from .checkpoint import IngestCheckpointer, ResumePoint, battery_fingerprint
from .faults import (
    FAULT_SEED_ENV,
    FAULTS_ENV,
    FaultInjector,
    FaultSpec,
    InjectedInterrupt,
    WorkerCrash,
    active_injector,
    clear,
    fault_point,
    inject,
    install,
)
from .isolation import (
    ResilientScanOutcome,
    classify_failure,
    run_scan_resilient,
)
from .watchdog import (
    SCAN_DEADLINE_ENV,
    RateTracker,
    rate_tracker,
    run_with_deadline,
    scan_deadline_s,
)

__all__ = [
    "IngestCheckpointer", "ResumePoint", "battery_fingerprint",
    "FaultSpec", "FaultInjector", "InjectedInterrupt", "WorkerCrash",
    "inject", "install", "clear", "fault_point", "active_injector",
    "FAULTS_ENV", "FAULT_SEED_ENV",
    "ResilientScanOutcome", "classify_failure", "run_scan_resilient",
    "SCAN_DEADLINE_ENV", "RateTracker", "rate_tracker",
    "run_with_deadline", "scan_deadline_s",
]
