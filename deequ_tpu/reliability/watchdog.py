"""Scan watchdog: deadline monitoring for device and host-tier passes.

The PR-2 reliability layer reacts to RAISED exceptions — isolation,
failover, retry all begin when something throws. A pass that HANGS (a
wedged device tunnel, a collective waiting on a peer that died, a kernel
spinning on a poisoned shape) defeats all of it: the worker blocks
forever, the battery never degrades, the scheduler queue backs up behind
a job that will never finish. This module closes that gap with the
hang-detection analog of a thrown fault:

- every engine pass runs under a DEADLINE derived from the measured
  per-ROW rate of previous passes on the same tier (a generous
  multiple, so normal variance never trips; per-row so micro-batch and
  full-batch passes share one honest rate), overridable with
  ``DEEQU_TPU_SCAN_DEADLINE_S`` (<= 0 disables);
- a pass exceeding its deadline is cancelled — the caller gets a typed
  :class:`~deequ_tpu.exceptions.ScanStallError`, which classifies as a
  ``"device"`` fault and takes the EXISTING tier-failover +
  placement-probation path (`isolation.classify_failure`); the
  ``RunMonitor.stalls`` counter records it;
- the service scheduler treats an escaped stall as retryable, so a
  watchdog-flagged job is requeued instead of failing outright
  (`scheduler._maybe_retry`).

Division of labor with the mesh heartbeat: this watchdog guards the WHOLE
pass (one deadline around the fold); `parallel/health.py`'s per-shard
heartbeat guards individual mesh shards DURING the fold, declaring a
wedged shard lost (typed ``ShardStallError``, a ``ShardLossError``) so
the elastic layer salvages and re-shards instead of abandoning the whole
pass — the pass-level deadline stays as the backstop when the entire mesh
(or the host tier) hangs.

Cancellation semantics: Python cannot kill a thread, so the stalled pass
is ABANDONED on a daemon thread while the caller proceeds with recovery.
The zombie's side effects are bounded by design — engine passes fold into
pass-local state and only publish by RETURNING (which the abandoned
caller discards); the one durable side channel, a checkpoint save, writes
a self-consistent resume point that a later run may legitimately use.
Before the first measured rate exists, derived deadlines are disabled
(there is nothing honest to derive from); the env override always
applies.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..exceptions import ScanStallError

#: env var: per-pass deadline in seconds. Overrides the derived deadline;
#: "0" (or any value <= 0) disables the watchdog entirely.
SCAN_DEADLINE_ENV = "DEEQU_TPU_SCAN_DEADLINE_S"

#: multiple of the measured per-row time a pass may take before it is
#: declared stalled — generous, because the cost of a false trip (a
#: spurious failover) is far higher than a few extra seconds of waiting
DEADLINE_RATE_MULTIPLE = 10.0

#: floor on any derived deadline: compile time, feed-link warmup and probe
#: costs all amortize into the first batches, so short passes get slack
DEADLINE_FLOOR_S = 30.0


class RateTracker:
    """EWMA of measured per-ROW wall seconds, per tier. Fed by successful
    engine passes; consulted to derive the next pass's deadline.
    Per-row, not per-batch: one tier serves both 512-row streaming
    micro-batches and 1M-row verification batches, and a per-batch rate
    learned from the small ones would derive deadlines no healthy
    large-batch pass can meet. Thread-safe (service workers run passes
    concurrently)."""

    ALPHA = 0.3

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._per_row_s: Dict[str, float] = {}

    def observe(self, tier: str, rows: int, seconds: float) -> None:
        if rows <= 0 or seconds <= 0:
            return
        per_row = seconds / rows
        with self._lock:
            prev = self._per_row_s.get(tier)
            self._per_row_s[tier] = (
                per_row if prev is None
                else self.ALPHA * per_row + (1 - self.ALPHA) * prev
            )

    def per_row_s(self, tier: str) -> Optional[float]:
        with self._lock:
            return self._per_row_s.get(tier)

    def clear(self) -> None:
        with self._lock:
            self._per_row_s.clear()


#: the process-wide rate ledger (deadlines derive from what THIS process
#: measured; rates do not survive restarts — the first pass of a process
#: runs unguarded unless the env override is set)
_TRACKER = RateTracker()


def rate_tracker() -> RateTracker:
    return _TRACKER


#: warn-once latch for an unparseable env override
_ENV_WARNED = False


def scan_deadline_s(n_rows: int, tier: str) -> Optional[float]:
    """The deadline for a pass over ``n_rows`` on ``tier``, or None
    (watchdog disabled: no override and no measured rate yet)."""
    env = os.environ.get(SCAN_DEADLINE_ENV)
    if env is not None:
        try:
            value = float(env)
        except ValueError:
            # an operator who set "60s"/"1m" believes hang detection is
            # armed — falling back to the derived deadline (instead of
            # silently disabling BOTH paths) keeps some guard up, and the
            # warning says why the pinned value was ignored
            global _ENV_WARNED
            if not _ENV_WARNED:
                _ENV_WARNED = True
                import logging

                logging.getLogger(__name__).warning(
                    "ignoring unparseable %s=%r (expected seconds as a "
                    "number); falling back to the measured-rate deadline",
                    SCAN_DEADLINE_ENV, env,
                )
        else:
            return value if value > 0 else None
    per_row = _TRACKER.per_row_s(tier)
    if per_row is None:
        return None
    return max(
        DEADLINE_FLOOR_S,
        DEADLINE_RATE_MULTIPLE * per_row * max(int(n_rows), 1),
    )


def run_with_deadline(
    fn: Callable[[], "object"],
    deadline_s: float,
    monitor,
    site: str,
):
    """Run ``fn`` to completion or to the deadline, whichever first.

    On deadline: bump ``monitor.stalls``, abandon the worker thread (it
    stays a daemon; its eventual return value is discarded) and raise
    :class:`ScanStallError`. On completion: return/raise exactly what
    ``fn`` did."""
    from ..observability import record_failure
    from ..observability import trace as _trace

    box: Dict[str, object] = {}
    done = threading.Event()
    # the pass body runs on a daemon thread: carry the caller's trace
    # context over so the pass's spans stay in the caller's tree (an
    # abandoned zombie keeps appending to the SAME trace, which is exactly
    # what a post-mortem wants to see)
    ctx = _trace.capture()

    def body() -> None:
        try:
            with _trace.attach(ctx):
                box["value"] = fn()
        except BaseException as exc:  # noqa: BLE001 - re-raised in caller
            box["error"] = exc
        finally:
            done.set()

    t0 = time.perf_counter()
    worker = threading.Thread(
        target=body, name=f"scan-watchdog-{site}", daemon=True
    )
    worker.start()
    if not done.wait(deadline_s):
        waited = time.perf_counter() - t0
        if monitor is not None:
            monitor.bump("stalls")
            if site == "device":
                # tier-attributed: only DEVICE stalls should teach the
                # placement router to avoid the device tier — pinning a
                # battery to the host tier because the HOST hung would
                # probation it onto the sick tier
                monitor.bump("device_stalls")
        stall = ScanStallError(site, deadline_s, waited)
        _trace.add_event(
            "scan_stall", site=site, deadline_s=deadline_s, waited_s=waited
        )
        record_failure(stall)
        raise stall
    if "error" in box:
        raise box["error"]
    return box["value"]
