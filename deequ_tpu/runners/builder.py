"""Fluent run configuration (reference `analyzers/runners/AnalysisRunBuilder.scala:25-186`)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from ..analyzers.base import Analyzer
from ..data import Dataset
from .context import AnalyzerContext
from .engine import RunMonitor


class AnalysisRunBuilder:
    def __init__(self, data: Dataset):
        self._data = data
        self._analyzers: List[Analyzer] = []
        self._aggregate_with = None
        self._save_states_with = None
        self._metrics_repository = None
        self._reuse_key = None
        self._fail_if_results_missing = False
        self._save_key = None
        self._batch_size: Optional[int] = None
        self._monitor: Optional[RunMonitor] = None
        self._json_path: Optional[str] = None
        self._overwrite = False

    def add_analyzer(self, analyzer: Analyzer) -> "AnalysisRunBuilder":
        self._analyzers.append(analyzer)
        return self

    def add_analyzers(self, analyzers: Sequence[Analyzer]) -> "AnalysisRunBuilder":
        self._analyzers.extend(analyzers)
        return self

    def aggregate_with(self, state_loader) -> "AnalysisRunBuilder":
        self._aggregate_with = state_loader
        return self

    def save_states_with(self, state_persister) -> "AnalysisRunBuilder":
        self._save_states_with = state_persister
        return self

    def with_batch_size(self, batch_size: int) -> "AnalysisRunBuilder":
        self._batch_size = batch_size
        return self

    def with_monitor(self, monitor: RunMonitor) -> "AnalysisRunBuilder":
        self._monitor = monitor
        return self

    def use_repository(self, repository) -> "AnalysisRunBuilder":
        self._metrics_repository = repository
        return self

    def reuse_existing_results_for_key(
        self, key, fail_if_results_missing: bool = False
    ) -> "AnalysisRunBuilder":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key) -> "AnalysisRunBuilder":
        self._save_key = key
        return self

    def save_success_metrics_json_to_path(
        self, path: str, overwrite: bool = False
    ) -> "AnalysisRunBuilder":
        self._json_path = path
        self._overwrite = overwrite
        return self

    def run(self) -> AnalyzerContext:
        from .analysis_runner import AnalysisRunner

        context = AnalysisRunner.do_analysis_run(
            self._data,
            self._analyzers,
            aggregate_with=self._aggregate_with,
            save_states_with=self._save_states_with,
            metrics_repository=self._metrics_repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_or_append_results_with_key=self._save_key,
            batch_size=self._batch_size,
            monitor=self._monitor,
        )
        if self._json_path:
            import os

            if self._overwrite or not os.path.exists(self._json_path):
                with open(self._json_path, "w", encoding="utf-8") as fh:
                    fh.write(context.success_metrics_as_json())
        return context


class Analysis:
    """Immutable list of analyzers + run convenience
    (reference `analyzers/Analysis.scala:29-63`)."""

    def __init__(self, analyzers: Optional[Sequence[Analyzer]] = None):
        self.analyzers: List[Analyzer] = list(analyzers or [])

    def add_analyzer(self, analyzer: Analyzer) -> "Analysis":
        return Analysis(self.analyzers + [analyzer])

    def add_analyzers(self, analyzers: Sequence[Analyzer]) -> "Analysis":
        return Analysis(self.analyzers + list(analyzers))

    def run(self, data: Dataset, **kwargs) -> AnalyzerContext:
        from .analysis_runner import AnalysisRunner

        return AnalysisRunner.do_analysis_run(data, self.analyzers, **kwargs)
