"""ScanEngine: the fused single-pass executor.

Replaces the reference's `runScanningAnalyzers` fused `data.agg(...)` scan
(reference `analyzers/runners/AnalysisRunner.scala:289-336`): all requested
scan-shareable analyzers fold each padded batch into their states inside ONE
jit'd XLA program (fusion by the compiler, not row offsets), while grouping /
host-accumulated analyzers consume the same batch on the host — so the whole
run makes exactly one pass over the data.

``RunMonitor`` is the SparkMonitor analog (reference test fixture
`SparkMonitor.scala:39-76`): pass/batch/program counts are first-class
observables so tests can assert scan-sharing invariants, not just values.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analyzers.base import ScanShareableAnalyzer
from ..analyzers.grouping import FrequenciesAndNumRows, GroupingAnalyzer
from ..config import DEFAULT_BATCH_SIZE
from ..data import Dataset
from ..observability import trace as _trace
from ..reliability.faults import fault_point
from .features import FeatureBuilder

_logger = logging.getLogger(__name__)

#: env var overriding the default ingest-tier placement ("auto" when unset;
#: "host"/"device" pin the tier). Read through `utils.env_str` so the
#: env-knob convention check (tools/statlint) can see every read site.
PLACEMENT_ENV = "DEEQU_TPU_PLACEMENT"

#: env var: directory receiving a `jax.profiler` trace of every pass
PROFILE_DIR_ENV = "DEEQU_TPU_PROFILE_DIR"


@dataclass
class RunMonitor:
    """Counts execution events for scan-sharing assertions. Also records
    which ingest tier a run executed on (``placement``), the probed feed
    bandwidth that drove the decision, and per-phase wall time
    (``phase_seconds``) so a run's cost is attributable without external
    tooling (SURVEY §5: lightweight phase timers).

    The reliability fields are the engine-side ledger the service's
    placement router learns from: ``device_failovers`` counts device→host
    tier hops, ``batch_bisections`` OOM-driven batch halvings,
    ``isolation_reruns`` battery-bisection re-passes, and ``degraded``
    names what was knocked out (analyzer reprs, host accumulator keys,
    tier hops). ``checkpoint_saves``/``resumed_at_batch`` trace the
    resumable-ingest path."""

    passes: int = 0
    batches: int = 0
    device_updates: int = 0
    jit_compiles: int = 0
    #: XLA program traces NEWLY paid during this monitor's runs (a DELTA,
    #: unlike ``jit_compiles`` which mirrors the absolute program-cache
    #: occupancy): a warm re-run of the same battery records 0 here. The
    #: compile-budget regression test and the bench's per-stage artifact
    #: key on this.
    program_compiles: int = 0
    placement: Optional[str] = None
    feed_bandwidth_mbps: Optional[float] = None
    phase_seconds: Dict[str, float] = field(default_factory=dict)
    device_failovers: int = 0
    batch_bisections: int = 0
    isolation_reruns: int = 0
    degraded: List[str] = field(default_factory=list)
    checkpoint_saves: int = 0
    resumed_at_batch: Optional[int] = None
    #: corrupt persisted payloads (repository entries, checkpoint states)
    #: this run's loaders quarantined or discarded instead of crashing on
    corrupt_quarantined: int = 0
    #: passes the scan watchdog cancelled for exceeding their deadline
    stalls: int = 0
    #: the subset of ``stalls`` that happened on the DEVICE tier — the
    #: placement router's probation signal (a host-tier hang must not pin
    #: the battery onto the tier that hung)
    device_stalls: int = 0
    #: per-analyzer cost attribution (seconds, keyed by repr(analyzer)):
    #: each signature bundle's measured compile+dispatch wall time split
    #: evenly across its REAL slots (pad slots re-fold a duplicate and
    #: charge nothing). Shares sum to ``bundle_dispatch_seconds`` exactly,
    #: so "what did analyzer X cost this run" is answerable even though
    #: bundling makes individual programs invisible. Dispatch is async:
    #: what a share measures is enqueue time plus, on a bundle's FIRST
    #: dispatch, the synchronous trace+XLA-compile it pays — the periodic
    #: solo-timing probe (``cost_probes``) adds synchronized samples where
    #: the bundle's true per-batch execution time is captured too.
    cost_by_analyzer: Dict[str, float] = field(default_factory=dict)
    #: total measured per-bundle dispatch wall seconds (the attribution
    #: denominator: sum(cost_by_analyzer.values()) == this, within float
    #: rounding)
    bundle_dispatch_seconds: float = 0.0
    #: synchronized solo-timing probes taken (every _COST_PROBE_EVERY
    #: batches a bundle dispatch is bracketed by block_until_ready, so its
    #: measured time is true execution, not enqueue)
    cost_probes: int = 0
    #: grouping sets that rode the device frequency TABLE engine this run
    #: (hashed fixed-shape count tables in the fused pass; ROADMAP item 3)
    device_freq_sets: int = 0
    #: device frequency tables whose compactions dropped groups — those
    #: sets re-ran through the host accumulator last-resort tier
    freq_overflow_fallbacks: int = 0
    #: mesh shards (devices/processes) declared lost mid-pass — dead
    #: collectives, injected mesh_loss faults, heartbeat-declared stalls
    shard_losses: int = 0
    #: times a degraded mesh was rebuilt over the surviving devices (the
    #: 8→4→2→1→host ladder; the terminal host drop counts too)
    mesh_reshards: int = 0
    #: surviving per-shard states salvaged into a canonical merge after a
    #: shard loss (what the elastic layer kept instead of recomputing)
    salvaged_states: int = 0
    #: streaming folds served by the tiny-delta HOST fast path (delta state
    #: computed with the host kernels, merged algebraically — no engine
    #: pass, no device dispatch; service.coalesce routes these)
    fast_path_folds: int = 0
    #: streaming folds executed inside a cross-session COALESCED device
    #: launch (stacked along a leading session axis, one vmapped program)
    coalesced_folds: int = 0
    #: streaming folds sharded over a FLEET sub-mesh: per-slice host
    #: partials fold shard-local states, butterfly-merged at the coalesce
    #: drain boundary (service.coalesce._execute_mesh_fold)
    fleet_mesh_folds: int = 0
    #: incremental verification (runners.incremental): partitions the
    #: delta planner scheduled a scan for this run (new + invalidated)
    partitions_scanned: int = 0
    #: partitions whose stored states were loaded with ZERO data touched
    partitions_reused: int = 0
    #: partitions whose stored states went stale (content change,
    #: fingerprint mismatch, battery growth, corruption) and re-scanned
    partitions_invalidated: int = 0
    #: stored partitions absent from the incoming set — excluded from the
    #: merge (retention deletions show up here)
    partitions_dropped: int = 0
    #: partitions whose states were served by the ROLLUP cache (the
    #: persisted left-fold prefix) — neither their data nor their state
    #: blobs were touched
    partitions_rolled_up: int = 0

    def reset(self) -> None:
        self.passes = 0
        self.batches = 0
        self.device_updates = 0
        self.jit_compiles = 0
        self.program_compiles = 0
        self.placement = None
        self.feed_bandwidth_mbps = None
        self.phase_seconds = {}
        self.device_failovers = 0
        self.batch_bisections = 0
        self.isolation_reruns = 0
        self.degraded = []
        self.checkpoint_saves = 0
        self.resumed_at_batch = None
        self.corrupt_quarantined = 0
        self.stalls = 0
        self.device_stalls = 0
        self.cost_by_analyzer = {}
        self.bundle_dispatch_seconds = 0.0
        self.cost_probes = 0
        self.device_freq_sets = 0
        self.freq_overflow_fallbacks = 0
        self.shard_losses = 0
        self.mesh_reshards = 0
        self.salvaged_states = 0
        self.fast_path_folds = 0
        self.coalesced_folds = 0
        self.fleet_mesh_folds = 0
        self.partitions_scanned = 0
        self.partitions_reused = 0
        self.partitions_invalidated = 0
        self.partitions_dropped = 0
        self.partitions_rolled_up = 0

    def merge_from(self, other: "RunMonitor") -> None:
        """Absorb another monitor's counters and phase times (locked).
        The coalescer records each fold's costs into a fold-local monitor
        while the fold executes inside ANOTHER job's launch; the fold's
        own job absorbs them here exactly once, so the export-plane
        harvest attributes the work to the tenant that submitted it."""
        with _MONITOR_LOCK:
            for name in (
                "passes", "batches", "device_updates", "program_compiles",
                "device_failovers", "batch_bisections", "isolation_reruns",
                "checkpoint_saves", "corrupt_quarantined", "stalls",
                "device_stalls", "device_freq_sets",
                "freq_overflow_fallbacks", "shard_losses", "mesh_reshards",
                "salvaged_states", "fast_path_folds", "coalesced_folds",
                "fleet_mesh_folds", "cost_probes", "partitions_scanned",
                "partitions_reused", "partitions_invalidated",
                "partitions_dropped", "partitions_rolled_up",
            ):
                setattr(self, name, getattr(self, name) + getattr(other, name))
            self.bundle_dispatch_seconds += other.bundle_dispatch_seconds
            for phase, seconds in other.phase_seconds.items():
                self.phase_seconds[phase] = (
                    self.phase_seconds.get(phase, 0.0) + seconds
                )
            for key, seconds in other.cost_by_analyzer.items():
                self.cost_by_analyzer[key] = (
                    self.cost_by_analyzer.get(key, 0.0) + seconds
                )
            self.degraded.extend(other.degraded)
            if other.placement is not None:
                self.placement = other.placement

    def note_degraded(self, tag: str) -> None:
        with _MONITOR_LOCK:
            self.degraded.append(tag)

    def bump(self, field_name: str, by: int = 1) -> None:
        """Locked counter increment: overlapped profile passes share one
        monitor across threads, and `+=` on a dataclass int is not
        atomic."""
        with _MONITOR_LOCK:
            setattr(self, field_name, getattr(self, field_name) + by)

    def add_phase_time(self, phase: str, seconds: float) -> None:
        with _MONITOR_LOCK:
            self.phase_seconds[phase] = self.phase_seconds.get(phase, 0.0) + seconds

    def timed(self, phase: str):
        """Context manager accumulating wall time under ``phase``; safe to
        use from the prefetch/ingest worker threads."""
        return _PhaseTimer(self, phase)


import threading as _threading  # noqa: E402

_MONITOR_LOCK = _threading.Lock()

#: guards _PROGRAM_CACHE's check-then-insert: service workers and the
#: placement warmer race on the same battery, and a losing duplicate
#: (executed=False) overwriting the winner would make the battery read as
#: cold forever after a completed warm
_PROGRAM_CACHE_LOCK = _threading.Lock()

#: per-thread device-feature-cache bypass: warm runs execute a throwaway
#: 1-row sample whose padded features must not occupy (or evict from) the
#: production cache budget
_CACHE_BYPASS = _threading.local()


class _PhaseTimer:
    """Span-backed phase timer: the measured interval both accumulates into
    ``phase_seconds`` (unchanged numbers, now derived from the same ns
    clock) and, when the calling thread carries a trace context, publishes
    as a finished child span — so a trace's phase durations can never
    disagree with the monitor's."""

    __slots__ = ("monitor", "phase", "t0_ns")

    def __init__(self, monitor: RunMonitor, phase: str):
        self.monitor = monitor
        self.phase = phase

    def __enter__(self):
        import time

        self.t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        import time

        end_ns = time.perf_counter_ns()
        self.monitor.add_phase_time(self.phase, (end_ns - self.t0_ns) / 1e9)
        _trace.record_phase(self.phase, self.t0_ns, end_ns)
        return False


#: battery-level scan orchestrators keyed by (analyzer battery, mesh) —
#: analyzers are frozen dataclasses, so identical batteries across runs
#: reuse the SAME BundledScanProgram (whose `executed` flag carries the
#: service's warmth semantics). The COMPILED units live one level down in
#: _BUNDLE_PROGRAM_CACHE, keyed by signature so different batteries share
#: them. LRU-bounded so a long-lived multi-tenant service cycling through
#: many distinct batteries cannot grow program/device memory monotonically;
#: an evicted battery simply reads as cold again and re-warms through the
#: placement router.
from ..utils import BoundedLRU as _BoundedLRU  # noqa: E402

_PROGRAM_CACHE = _BoundedLRU(256)


class PackedScanProgram:
    """The fused per-batch update over a PACKED carry: every scalar state
    leaf rides in one stacked float vector + one stacked int vector; array
    leaves (HLL registers, KLL buffers, ...) stay separate.

    Why: XLA's fusion groups form around OUTPUT roots. With the naive carry
    — a tuple of per-analyzer states holding ~dozens of independent scalar
    leaves — every reduction becomes its own fusion root and the TPU runs
    one full pass over the batch PER REDUCTION: measured 138ms per 1M-row
    batch for 24 reductions over 4 f64 columns (~6ms per analyzer,
    perfectly additive, zero sharing). Stacking the scalar results into one
    vector gives the sibling reduces a single root, and XLA fuses them into
    one pass over each column: the same 24 reductions measure 3.6ms — a
    ~38x speedup with bit-identical results. Floats and ints pack into
    SEPARATE vectors so int32/int64 counters round-trip exactly even in
    32-bit mode (f32 slots would corrupt counts beyond 2^24).

    The packed carry lives on device across the whole pass; ``unpack``
    (jit'd slices + casts, negligible) restores the ordinary state pytrees
    for the fetch/merge paths, so everything outside the hot loop keeps the
    plain-state protocol.

    COLUMN-AGNOSTIC TRACE: the jit'd update consumes per-slot POSITIONAL
    feature tuples, and the traced body rebuilds each slot's features dict
    from this program's own analyzers' spec keys. Feature arrays are thereby
    remapped positionally, so one compiled program serves EVERY battery
    whose per-slot (class, feature kinds, state shapes) signatures match —
    ``Mean("a")`` and ``Mean("z")`` run the same XLA executable. This is
    what lets the signature-keyed bundle cache share programs across
    columns, batteries and the suggestion stage (the device-tier analog of
    the host ingest tier's signature bundling)."""

    def __init__(self, analyzers: Tuple[ScanShareableAnalyzer, ...], mesh):
        self.analyzers = analyzers
        self.mesh = mesh
        #: True once the fused update has DISPATCHED at least once: jax.jit
        #: compiles lazily, so mere construction leaves the program cold —
        #: warmth claims (the service's cache-aware placement) key on this
        self.executed = False
        #: per-slot feature keys of the TEMPLATE analyzers this program was
        #: traced with; callers with same-signature batteries feed arrays
        #: positionally and the trace rebinds them under these keys
        self._spec_keys = [
            tuple(spec.key for spec in a.feature_specs()) for a in analyzers
        ]

        init_shapes = jax.eval_shape(
            lambda: tuple(a.init_state() for a in analyzers)
        )
        leaves, treedef = jax.tree_util.tree_flatten(init_shapes)
        self._treedef = treedef
        self._float_idx = [
            i for i, l in enumerate(leaves)
            if l.ndim == 0 and jnp.issubdtype(l.dtype, jnp.floating)
        ]
        self._int_idx = [
            i for i, l in enumerate(leaves)
            if l.ndim == 0 and not jnp.issubdtype(l.dtype, jnp.floating)
        ]
        self._aux_idx = [i for i, l in enumerate(leaves) if l.ndim != 0]
        self._leaf_dtypes = [l.dtype for l in leaves]
        from ..config import ACC_DTYPE, COUNT_DTYPE

        self._fvec_dtype = ACC_DTYPE
        self._ivec_dtype = COUNT_DTYPE

        pack, unpack = self._pack, self._unpack
        spec_keys = self._spec_keys

        def fused_update(carry, slot_features):
            states = unpack(carry)
            return pack(
                tuple(
                    a.update(s, dict(zip(keys, feats)))
                    for a, keys, s, feats in zip(
                        analyzers, spec_keys, states, slot_features
                    )
                )
            )

        if mesh is None:
            self._update = jax.jit(fused_update, donate_argnums=0)
        else:
            from ..parallel import replicated

            self._update = jax.jit(
                fused_update,
                in_shardings=(replicated(mesh), None),
                out_shardings=replicated(mesh),
                donate_argnums=0,
            )
        #: the raw traced bodies, kept so the cross-session COALESCED path
        #: can lift the SAME update/unpack over a leading session axis
        #: (jax.vmap) — one fused launch folds W sessions' batches; built
        #: lazily on first coalesced use so ordinary runs pay nothing
        self._fused_update_fn = fused_update
        self._update_stacked = None
        self._unpack_stacked_jit = None
        self._init_stacked_jit = None
        self._unpack_jit = jax.jit(unpack)
        # pass-END unpack: the carry is dead afterwards, so donating it
        # lets the pass-through (aux) leaves alias instead of copy — a
        # resident frequency buffer is hundreds of MB, and the identity
        # copy was measurable (~0.26s at 256MB on CPU). NEVER use for the
        # mid-pass checkpoint unpack, whose carry keeps folding.
        self._unpack_final_jit = jax.jit(unpack, donate_argnums=0)
        self._init_jit = jax.jit(
            lambda: pack(tuple(a.init_state() for a in analyzers))
        )

    def _pack(self, states: Tuple):
        leaves = jax.tree_util.tree_flatten(states)[0]
        fvec = (
            jnp.stack([leaves[i].astype(self._fvec_dtype) for i in self._float_idx])
            if self._float_idx
            else jnp.zeros((0,), self._fvec_dtype)
        )
        ivec = (
            jnp.stack([leaves[i].astype(self._ivec_dtype) for i in self._int_idx])
            if self._int_idx
            else jnp.zeros((0,), self._ivec_dtype)
        )
        return fvec, ivec, tuple(leaves[i] for i in self._aux_idx)

    def _unpack(self, carry) -> Tuple:
        fvec, ivec, aux = carry
        leaves: List[Any] = [None] * len(self._leaf_dtypes)
        for j, i in enumerate(self._float_idx):
            leaves[i] = fvec[j].astype(self._leaf_dtypes[i])
        for j, i in enumerate(self._int_idx):
            leaves[i] = ivec[j].astype(self._leaf_dtypes[i])
        for j, i in enumerate(self._aux_idx):
            leaves[i] = aux[j]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def init_carry(self):
        """Packed identity states, built ON DEVICE (one dispatch): pulling
        init scalars to host first would cost a feed-link round trip per
        leaf."""
        return self._init_jit()

    def __call__(self, carry, features: Dict[str, jax.Array]):
        """Dispatch one batch with a GLOBAL features dict (keys = this
        program's own analyzers' spec keys — the monolithic/bench entry)."""
        slots = tuple(
            tuple(features[k] for k in keys) for keys in self._spec_keys
        )
        return self.call_with_slots(carry, slots)

    def call_with_slots(self, carry, slot_features):
        """Dispatch one batch with PRE-GATHERED per-slot feature tuples (the
        bundled entry: the caller gathered them via its OWN analyzers' spec
        keys, positionally parallel to this program's template specs)."""
        out = self._update(carry, slot_features)
        self.executed = True  # the jit call above traced + compiled
        return out

    def unpack(self, carry) -> Tuple:
        """Packed carry -> ordinary per-analyzer state pytrees (on device)."""
        return self._unpack_jit(carry)

    def unpack_final(self, carry) -> Tuple:
        """Like :meth:`unpack` but DONATES the carry (pass-end only: the
        carry must not be dispatched again)."""
        import warnings

        with warnings.catch_warnings():
            # the stacked fvec/ivec leaves change dtype on unpack, so jax
            # reports their donated buffers as unusable — expected; the
            # donation exists for the pass-through aux leaves (a resident
            # frequency buffer is hundreds of MB)
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return self._unpack_final_jit(carry)

    def pack_states(self, states: Tuple):
        """Ordinary per-analyzer state pytrees -> packed carry; the inverse
        of :meth:`unpack`, used to re-enter the fused loop from
        checkpointed (host numpy) states. Lossless: every scalar leaf's
        dtype is ACC_DTYPE/COUNT_DTYPE, the packed vectors' own dtypes."""
        return self._pack(tuple(states))

    # -- coalesced (stacked-over-sessions) entry points ----------------------
    #
    # The cross-session fold coalescer (service.coalesce) stacks W
    # same-signature sessions' single padded batches along a leading axis
    # and folds them as ONE device program: jax.vmap of the identical
    # fused_update, so per-slot semantics — and the compiled reduction
    # bits — match the serial dispatch exactly (pinned by the coalesce
    # parity tests). jit re-specializes per W, and the coalescer buckets W
    # to powers of two, so the compiled-shape space stays log-bounded.

    def init_carry_stacked(self, width: int):
        """W stacked identity carries, built on device in one dispatch."""
        if self._init_stacked_jit is None:
            pack, analyzers = self._pack, self.analyzers
            self._init_stacked_jit = jax.jit(
                lambda d: jax.vmap(
                    lambda _: pack(tuple(a.init_state() for a in analyzers))
                )(d)
            )
        return self._init_stacked_jit(jnp.zeros((width,), jnp.int32))

    def call_with_slots_stacked(self, carry, slot_features):
        """One coalesced dispatch: ``slot_features`` mirror
        :meth:`call_with_slots` but every array carries a leading session
        axis of the carry's width. The carry is DONATED (fold programs
        never re-read it), so per-launch state copies disappear."""
        if self._update_stacked is None:
            self._update_stacked = jax.jit(
                jax.vmap(self._fused_update_fn), donate_argnums=0
            )
        out = self._update_stacked(carry, slot_features)
        self.executed = True
        return out

    def unpack_stacked_final(self, carry) -> Tuple:
        """Stacked packed carry -> per-analyzer state pytrees whose leaves
        keep the leading session axis (the caller splits per session after
        ONE packed fetch). Donates the carry — launch-end only."""
        if self._unpack_stacked_jit is None:
            self._unpack_stacked_jit = jax.jit(
                jax.vmap(self._unpack), donate_argnums=0
            )
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return self._unpack_stacked_jit(carry)

    def _cache_size(self) -> int:
        return self._update._cache_size()


#: signature-keyed bundle programs: the compiled-XLA sharing layer. Keys are
#: tuples of per-slot scan signatures + mesh, NOT analyzer identities, so
#: ``(Mean("a"), Mean("b"))`` and ``(Mean("x"), Mean("y"))`` — and the same
#: classes inside a different battery, or the suggestion stage's evaluation
#: batteries — all resolve to ONE PackedScanProgram. Sized above the
#: battery-level cache: bundles are the scarcer, more reusable resource.
_BUNDLE_PROGRAM_CACHE = _BoundedLRU(512)

_SCAN_SIG_CACHE = _BoundedLRU(4096)

#: batches between synchronized cost-attribution probes: the probed batch's
#: bundle dispatches are bracketed with block_until_ready so their measured
#: time is true execution (async dispatch otherwise measures enqueue). The
#: first probe lands on batch index 1 — batch 0 pays any cold compile and
#: would conflate compile with execution.
_COST_PROBE_EVERY = 64


class _CostLedger:
    """PASS-LOCAL per-analyzer cost accumulation. Two reasons it exists
    instead of writing straight to the RunMonitor:

    - **Hot-path cost.** Attribution runs per bundle per batch; a local
      dict accumulate is lock-free and uses the bundle programs'
      PRECOMPUTED repr strings, with ONE locked flush per pass.
    - **Zombie-pass hygiene.** A watchdog-abandoned pass keeps dispatching
      on its daemon thread while the failover re-pass runs against the
      SAME monitor; flushing only at pass completion — and only when the
      engine has not marked the pass cancelled — keeps an abandoned pass's
      costs out of ``cost_by_analyzer`` (the attribution analog of the
      rate tracker's contamination guard)."""

    __slots__ = ("by_key", "total", "probes")

    def __init__(self):
        self.by_key: Dict[str, float] = {}
        self.total = 0.0
        self.probes = 0

    def add_bundle(self, slot_reprs, seconds: float) -> None:
        self.total += seconds
        share = seconds / len(slot_reprs)
        by_key = self.by_key
        for key in slot_reprs:
            by_key[key] = by_key.get(key, 0.0) + share

    def flush(self, monitor: RunMonitor) -> None:
        if not self.by_key and not self.probes:
            return
        with _MONITOR_LOCK:
            costs = monitor.cost_by_analyzer
            for key, seconds in self.by_key.items():
                costs[key] = costs.get(key, 0.0) + seconds
            monitor.bundle_dispatch_seconds += self.total
            monitor.cost_probes += self.probes


def _scan_signature(a: ScanShareableAnalyzer) -> Tuple:
    """Program-identity key of an analyzer's fused-scan update: the ingest
    signature (class + state tree structure + leaf shapes/dtypes) extended
    with the feature-spec KIND tuple (a where-filter adds a predicate
    feature, changing the traced update) and the analyzer's own
    ``scan_program_key`` escape hatch. Valid because every ``update`` is a
    pure function of the state and feature VALUES given that key: columns,
    predicates, regexes and quantile points act host-side (feature
    computation) or at metric time, never inside the trace."""
    sig = _SCAN_SIG_CACHE.get(a)
    if sig is None:
        keys = [spec.key for spec in a.feature_specs()]
        sig = _ingest_signature(a) + (
            tuple(spec.kind for spec in a.feature_specs()),
            # the key-DUPLICATION pattern: the traced update rebinds slot
            # arrays under the template's keys via dict(zip(keys, feats)),
            # so an analyzer whose specs repeat a key (e.g. where ==
            # predicate) collapses positions a distinct-key analyzer keeps
            # separate — they must not share a program
            tuple(keys.index(k) for k in keys),
            a.scan_program_key(),
        )
        _SCAN_SIG_CACHE[a] = sig
    return sig


def _signature_bundles(analyzers, sig_fn, bundle_size: int):
    """Partition analyzer indices into signature-homogeneous bundles of at
    most ``bundle_size``, preserving relative order within a signature;
    returns (indices, n_real) pairs. Pad positions (j >= n_real) re-fold a
    REPEAT of the bundle's first index and their outputs MUST be discarded
    by the caller. Two padding rules bound the compiled-shape space per
    signature to log2(bundle_size)+1 variants while keeping pad waste < 2x:

    - a signature spanning MORE than one bundle pads its tail to the full
      ``bundle_size`` so the tail reuses the full-size compiled program
      instead of compiling a second length variant;
    - a LONE small group pads to the next power of two, so batteries with
      nearby same-class counts (pass-2 numeric batteries, suggestion
      evaluation subsets) converge on the same program shapes instead of
      compiling one program per exact count.

    Shared by the host ingest tier and the device scan bundling so the two
    partitioning policies cannot drift."""
    by_sig: Dict[Tuple, List[int]] = {}
    for i, a in enumerate(analyzers):
        by_sig.setdefault(sig_fn(a), []).append(i)
    bundles: List[Tuple[List[int], int]] = []
    for idxs in by_sig.values():
        for j in range(0, len(idxs), bundle_size):
            part = idxs[j : j + bundle_size]
            n_real = len(part)
            if j > 0 and n_real < bundle_size:
                part = part + [idxs[0]] * (bundle_size - n_real)
            elif j == 0 and n_real < bundle_size:
                slots = 1
                while slots < n_real:
                    slots *= 2
                part = part + [idxs[0]] * (slots - n_real)
            bundles.append((part, n_real))
    return bundles


def _bundle_program(
    bundle_analyzers: Tuple[ScanShareableAnalyzer, ...], mesh
) -> PackedScanProgram:
    """The signature-cached PackedScanProgram for one bundle. The stored
    program was traced with the FIRST battery's analyzers that materialized
    this key (the templates); every later same-signature bundle feeds its
    feature arrays positionally through ``call_with_slots``. Callers hold
    _PROGRAM_CACHE_LOCK."""
    key = (
        tuple(_scan_signature(a) for a in bundle_analyzers),
        None if mesh is None else tuple(mesh.devices.flat),
    )
    cached = _BUNDLE_PROGRAM_CACHE.get(key)
    if cached is None:
        fault_point("compile", tag=str(len(bundle_analyzers)))
        cached = PackedScanProgram(bundle_analyzers, mesh)
        _BUNDLE_PROGRAM_CACHE[key] = cached
    return cached


class BundledScanProgram:
    """Battery-level orchestrator over signature-keyed bundle programs.

    The monolithic PackedScanProgram keys its compile on the full analyzer
    tuple, so a cold 50-column profile battery pays one giant XLA compile
    (measured 1140.6s staging vs 1.98s warm — 575x, BENCH_r05) that nothing
    else can reuse. This splits the battery into (class, state-shape)
    signature bundles of at most ``config.scan_bundle_size()`` analyzers:
    each bundle compiles a SMALL program cached by signature, so a 50-column
    profile compiles ~10 programs that are shared across its own columns,
    across batteries, across the profiler's passes and the suggestion stage
    — and, via jax's persistent compilation cache, across processes. The
    packed-carry fusion win survives WITHIN each bundle (same-class sibling
    reductions share one output root); what is traded away is cross-class
    fusion over one column, bought back many times over in compile time.

    ``DEEQU_TPU_SCAN_BUNDLE=0`` restores the monolithic single-bundle
    behavior (the parity baseline the bundled path is tested bit-identical
    against).

    Presents the same interface the engine drives (`init_carry` /
    ``__call__`` / `unpack` / `pack_states` / `_cache_size`); the carry is a
    tuple of per-bundle packed carries."""

    def __init__(self, analyzers: Tuple[ScanShareableAnalyzer, ...], mesh):
        from ..config import scan_bundle_size

        self.analyzers = analyzers
        self.mesh = mesh
        #: battery-level warmth: True once THIS battery dispatched. Shared
        #: bundle programs may already be compiled (that is the point), but
        #: warmth introspection stays conservative at battery granularity so
        #: the service's placement probes keep their lazy-compile semantics.
        self.executed = False
        bundle_size = scan_bundle_size()
        if bundle_size <= 0:
            self._bundles = [(list(range(len(analyzers))), len(analyzers))]
        else:
            self._bundles = _signature_bundles(
                analyzers, _scan_signature, bundle_size
            )
        self._programs = [
            _bundle_program(tuple(analyzers[i] for i in idxs), mesh)
            for idxs, _ in self._bundles
        ]
        #: per-bundle, per-slot feature keys of the ACTUAL analyzers —
        #: gathered from the global features dict at dispatch and fed
        #: positionally to the (possibly template-traced) bundle program
        self._slot_keys = [
            [
                tuple(spec.key for spec in analyzers[i].feature_specs())
                for i in idxs
            ]
            for idxs, _ in self._bundles
        ]
        #: per-bundle repr strings of the REAL slots — precomputed so cost
        #: attribution never builds repr() on the dispatch hot path
        self._slot_reprs = [
            [repr(analyzers[i]) for i in idxs[:n_real]]
            for idxs, n_real in self._bundles
        ]

    def init_carry(self):
        return tuple(prog.init_carry() for prog in self._programs)

    def __call__(
        self,
        carry,
        features: Dict[str, jax.Array],
        ledger: Optional[_CostLedger] = None,
        probe: bool = False,
    ):
        """Dispatch one batch. With ``ledger`` (a pass-local
        :class:`_CostLedger`), each bundle's dispatch wall time is measured
        and attributed evenly across its REAL slots; async dispatch means
        the share normally measures enqueue + (on the first dispatch) the
        synchronous trace/XLA compile. ``probe=True`` brackets each bundle
        with ``block_until_ready`` so this batch's measurement is TRUE
        execution time — the engine schedules one probe every
        ``_COST_PROBE_EVERY`` batches, bounding the sync overhead."""
        import time as _time

        out = []
        for c, prog, keys, reprs in zip(
            carry, self._programs, self._slot_keys, self._slot_reprs
        ):
            slots = tuple(tuple(features[k] for k in slot) for slot in keys)
            if ledger is None:
                out.append(prog.call_with_slots(c, slots))
                continue
            if probe:
                jax.block_until_ready(jax.tree_util.tree_leaves(c))
            t0 = _time.perf_counter()
            result = prog.call_with_slots(c, slots)
            if probe:
                jax.block_until_ready(jax.tree_util.tree_leaves(result))
            out.append(result)
            ledger.add_bundle(reprs, _time.perf_counter() - t0)
        if probe and ledger is not None and self._programs:
            # one probe per probed BATCH (the documented unit), however
            # many bundles the battery spans
            ledger.probes += 1
        self.executed = True
        return tuple(out)

    def unpack(self, carry) -> Tuple:
        """Per-analyzer state pytrees in battery order (pad slots, which
        re-folded a duplicate of their bundle's first analyzer, are
        discarded)."""
        return self._unpack(carry, final=False)

    def unpack_final(self, carry) -> Tuple:
        """Pass-end variant: donates each bundle's carry (it must not be
        dispatched again) so pass-through leaves alias instead of copy."""
        return self._unpack(carry, final=True)

    def _unpack(self, carry, final: bool) -> Tuple:
        out: List[Any] = [None] * len(self.analyzers)
        for (idxs, n_real), prog, c in zip(self._bundles, self._programs, carry):
            states = prog.unpack_final(c) if final else prog.unpack(c)
            for j in range(n_real):
                out[idxs[j]] = states[j]
        return tuple(out)

    def pack_states(self, states: Tuple):
        """Inverse of :meth:`unpack` (checkpoint resume): pad slots are
        refilled with their bundle's first state, mirroring what the fold
        would have computed for them."""
        states = tuple(states)
        return tuple(
            prog.pack_states(tuple(states[i] for i in idxs))
            for (idxs, _), prog in zip(self._bundles, self._programs)
        )

    def _distinct_programs(self) -> List[PackedScanProgram]:
        seen: Dict[int, PackedScanProgram] = {}
        for prog in self._programs:
            seen.setdefault(id(prog), prog)
        return list(seen.values())

    def _cache_size(self) -> int:
        return sum(p._cache_size() for p in self._distinct_programs())


def fold_sessions_coalesced(
    orchestrators: Sequence[BundledScanProgram],
    features_list: Sequence[Dict[str, np.ndarray]],
) -> List[Tuple]:
    """Fold W same-signature sessions' single padded batches as ONE device
    launch per signature bundle (the cross-session coalescer's device arm).

    ``orchestrators[i]`` is session i's own battery orchestrator — its
    ``_slot_keys`` gather ``features_list[i]`` under that battery's spec
    keys; every battery in the group shares the template's per-position
    scan signatures (the coalesce key guarantees it), so the gathered
    arrays feed the TEMPLATE's bundle programs positionally, exactly like
    a single-session bundled dispatch. The group pads to the next power of
    two with duplicates of session 0 (bounding compiled widths to
    log2(max_width) variants); pad outputs are discarded.

    One vmapped dispatch per bundle + ONE packed state fetch for the whole
    group — the per-session fixed cost this path exists to amortize.
    Returns per REAL session a tuple of host state pytrees in battery
    order. Mesh-free only (service streaming sessions coalesce; GSPMD
    passes keep the serial path)."""
    template = orchestrators[0]
    if template.mesh is not None:
        raise ValueError("coalesced folds are mesh-free by design")
    n_real = len(features_list)
    width = 1
    while width < n_real:
        width *= 2
    gathered = [
        [
            tuple(tuple(feats[k] for k in slot) for slot in keys)
            for keys in prog._slot_keys
        ]
        for prog, feats in zip(orchestrators, features_list)
    ]
    gathered.extend([gathered[0]] * (width - n_real))
    stacked_states: List[Any] = [None] * len(template.analyzers)
    for j, ((idxs, n_real_slots), bprog) in enumerate(
        zip(template._bundles, template._programs)
    ):
        stacked_slots = tuple(
            tuple(
                np.stack([gathered[w][j][s][f] for w in range(width)])
                for f in range(len(gathered[0][j][s]))
            )
            for s in range(len(template._slot_keys[j]))
        )
        carry = bprog.init_carry_stacked(width)
        out = bprog.call_with_slots_stacked(carry, stacked_slots)
        states = bprog.unpack_stacked_final(out)
        for k in range(n_real_slots):
            stacked_states[idxs[k]] = states[k]
    fetched = _fetch_states_packed(tuple(stacked_states))
    return [
        tuple(
            jax.tree_util.tree_map(lambda x, _w=w: x[_w], st)
            for st in fetched
        )
        for w in range(n_real)
    ]


def _program_cache_key(analyzers: Tuple[ScanShareableAnalyzer, ...], mesh) -> Tuple:
    from ..config import scan_bundle_size

    # bundle size joins the key: an orchestrator bakes its partitioning in
    # __init__, so a DEEQU_TPU_SCAN_BUNDLE flip mid-process must MISS the
    # battery cache and re-partition instead of silently serving the old
    # layout (config.py promises the knob is honored without re-import,
    # and the bundled-vs-monolithic parity tests depend on it)
    return (
        analyzers,
        None if mesh is None else tuple(mesh.devices.flat),
        scan_bundle_size(),
    )


def _fused_program(analyzers: Tuple[ScanShareableAnalyzer, ...], mesh):
    key = _program_cache_key(analyzers, mesh)
    # construction is cheap (eval_shape + lazy jit wrappers, no compile),
    # so holding the lock across it guarantees ONE instance per key — the
    # instance whose `executed` flag warmth decisions read
    with _PROGRAM_CACHE_LOCK:
        cached = _PROGRAM_CACHE.get(key)
        if cached is None:
            cached = BundledScanProgram(analyzers, mesh)
            _PROGRAM_CACHE[key] = cached
        return cached


def _deduped_battery(analyzers) -> Tuple[ScanShareableAnalyzer, ...]:
    """Scan-shareable subset, deduped in first-encounter order — the same
    normalization do_analysis_run applies before building its battery, so
    warm registrations and cache probes key consistently with real runs."""
    return tuple(
        dict.fromkeys(
            a for a in analyzers if isinstance(a, ScanShareableAnalyzer)
        )
    )


def fused_program_is_cached(
    analyzers: Sequence[ScanShareableAnalyzer], mesh=None
) -> bool:
    """Whether the fused scan program for this exact battery has already
    EXECUTED in this process (jit compiles lazily, so a merely-constructed
    program would still pay the full XLA compile on its first dispatch —
    warmth means "a dispatch already happened", not "an object exists").
    The service's cache-aware placement keys its routing on this."""
    program = _PROGRAM_CACHE.get(
        _program_cache_key(_deduped_battery(analyzers), mesh)
    )
    return program is not None and program.executed


def effective_batch_size(data: Dataset, batch_size: Optional[int] = None) -> int:
    """The batch size a run over ``data`` will actually use when the
    caller leaves it unset. (The service plane always passes an EXPLICIT
    batch size — the bucketed `_session_batch_size` — so its warmth keys
    key on the shape it dispatches, not on this default.)"""
    return batch_size or min(DEFAULT_BATCH_SIZE, max(int(data.num_rows), 1))


def detached_warm_sample(data: Dataset) -> Dataset:
    """A 1-row DEEP copy of the dataset for background warming. A zero-copy
    ``slice(0, 1)`` would keep the parent table's buffers alive for as long
    as the warm sits queued — with a backlog of multi-second compiles, that
    pins whole datasets in memory after their jobs finished. The IPC round
    trip copies only the one row plus each dictionary column's dictionary
    (which warm battery planning needs)."""
    import pyarrow as pa

    head = data.arrow.slice(0, 1)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, head.schema) as writer:
        writer.write_table(head)
    table = pa.ipc.open_stream(sink.getvalue()).read_all()
    return Dataset(table, probe_encoding=False)


def warm_fused_program(
    analyzers: Sequence[ScanShareableAnalyzer],
    mesh=None,
    data: Optional[Dataset] = None,
    batch_size: Optional[int] = None,
) -> None:
    """Compile the fused scan program for a battery ahead of its first
    production run. Cold compiles stall a request for tens of seconds (the
    575x cold-compile gap); the service calls this from a background warmer
    so queued jobs fall back to the host tier instead of blocking.

    With ``data``, runs the REAL pipeline over a 1-row slice padded to the
    production batch size, with the FULL analyzer list — grouping analyzers
    included, so run-time battery augmentations (DeviceFrequencyScan over
    the dict columns; a slice shares its parent's table-wide dictionary)
    compile exactly as production will dispatch them. Without ``data`` only
    the program object is built (registration; the compile stays lazy)."""
    if data is None:
        battery = _deduped_battery(analyzers)
        if battery:
            _fused_program(battery, mesh)
        return
    from .analysis_runner import AnalysisRunner

    sample = Dataset(data.arrow.slice(0, 1), probe_encoding=False)
    # default to the PRODUCTION batch size: deriving it from ``data`` would
    # compile a shape-1 program when handed a detached 1-row warm sample,
    # falsely marking the battery warm at a shape no real run dispatches
    bs = batch_size or DEFAULT_BATCH_SIZE
    _CACHE_BYPASS.active = True
    try:
        AnalysisRunner.do_analysis_run(
            sample, list(analyzers), batch_size=bs, sharding=mesh,
            placement="device",
        )
    finally:
        _CACHE_BYPASS.active = False


def _group_leaves(leaves, idx=None) -> Dict[Tuple, List[int]]:
    """Leaf indices (all, or the subset ``idx``) grouped by (shape, dtype)
    in first-encounter order. A battery fetch packs hundreds of leaves;
    grouping same-shaped leaves into one ``stack`` before the final concat
    compiles ~6x faster than a 600-operand concat (cold fetch was paying
    seconds of XLA compile) and produces the same bytes in the GROUPED
    leaf order, which the unpackers walk via _grouped_leaf_order — both
    derive from this one grouping so the byte-order contract cannot
    drift."""
    groups: Dict[Tuple, List[int]] = {}
    for i in range(len(leaves)) if idx is None else idx:
        leaf = leaves[i]
        groups.setdefault((tuple(leaf.shape), str(leaf.dtype)), []).append(i)
    return groups


def _grouped_leaf_order(leaves, idx=None) -> List[int]:
    return [i for grp in _group_leaves(leaves, idx).values() for i in grp]


@jax.jit
def _pack_leaves_f64(leaves):
    """Concatenate every state leaf into ONE f64 device buffer (in GROUPED
    leaf order, see _group_leaves). Fetching a state pytree leaf-by-leaf
    costs a full device round-trip per buffer, which on remote-tunnel
    devices (~100ms each) dominates the entire scan; one packed fetch costs
    a single round trip regardless of battery size. f64 represents every
    state dtype in use exactly (f32/f16 subsets; bool / (u)int8/16/32
    exactly; int64 counters exactly up to 2^53 — counters are row counts,
    far below that). 64-bit *bitcasts* would be bit-perfect but the TPU
    x64-emulation rewriter does not implement them."""
    parts = []
    for idxs in _group_leaves(leaves).values():
        if len(idxs) == 1:
            parts.append(jnp.ravel(leaves[idxs[0]]).astype(jnp.float64))
        else:
            parts.append(
                jnp.ravel(jnp.stack([leaves[i] for i in idxs]).astype(jnp.float64))
            )
    return jnp.concatenate(parts)


@jax.jit
def _pack_leaves_u64_u8(leaves):
    """x64-mode packing of 8-byte UNSIGNED leaves — the frequency engine's
    full-range u64 hash keys, which the f64 upcast path would corrupt above
    2^53. Each leaf splits into (lo, hi) uint32 halves and ships through
    the bit-exact u8 bitcast (the TPU x64-emulation rewriter implements no
    64-bit bitcasts; 32-bit ones it does). Per group the layout is one
    lo-block then one hi-block, grouped leaf order."""
    parts = []
    for idxs in _group_leaves(leaves).values():
        grp = [leaves[i] for i in idxs]
        stacked = grp[0] if len(grp) == 1 else jnp.stack(grp)
        lo = (stacked & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (stacked >> jnp.uint64(32)).astype(jnp.uint32)
        parts.append(
            jnp.ravel(
                jax.lax.bitcast_convert_type(jnp.stack([lo, hi]), jnp.uint8)
            )
        )
    return jnp.concatenate(parts)


@jax.jit
def _pack_leaves_u8(leaves):
    """32-bit-mode packing (grouped leaf order): bitcast each (<=32-bit)
    leaf to raw bytes — bit-exact, and int32 values above f32's 2^24
    integer range survive."""
    parts = []
    for idxs in _group_leaves(leaves).values():
        grp = [leaves[i] for i in idxs]
        if grp[0].dtype == jnp.bool_:
            grp = [g.astype(jnp.uint8) for g in grp]
        stacked = grp[0] if len(grp) == 1 else jnp.stack(grp)
        parts.append(jnp.ravel(jax.lax.bitcast_convert_type(stacked, jnp.uint8)))
    return jnp.concatenate(parts)


def _empty_batch_like(data: Dataset, columns):
    """A 0-valid-row batch with the dataset's schema (identity partials)."""
    names = list(columns) if columns is not None else data.schema.names
    empty = data.arrow.slice(0, 0)
    for b in Dataset(empty, probe_encoding=False).batches(1, columns=names):
        return b
    raise AssertionError("batches() always yields at least one batch")


#: below this many narrow bytes the second transfer's round trip costs more
#: than the f64 upcast wastes
_NARROW_SPLIT_BYTES = 1 << 15

#: leaves at least this big skip the packed-transfer paths and transfer
#: directly (one leaf = one transfer; the repack's extra full-buffer
#: copies dominate at resident-key-buffer sizes)
_DIRECT_LEAF_BYTES = 4 << 20


def _slim_kll_for_fetch(states: Tuple) -> Tuple[Tuple, List[Optional[int]]]:
    """Shrink each KLL state's item buffer before fetching: after every
    fold/merge the compaction cascade leaves <= k items in every level it
    processes, so columns beyond k are structural +inf padding — 3/4 of the
    buffer's bytes. The TOP level is the one level the cascade never
    compacts and can legitimately exceed k, so it ships FULL width; the
    transform is lossless. Returns (slimmed states, original widths)."""
    from ..ops.kll import KLLSketchState

    widths: List[Optional[int]] = []
    slim: List[Any] = []
    for s in states:
        if (
            isinstance(s, KLLSketchState)
            and s.items.ndim == 2
            and s.items.shape[1] > s.sketch_size
        ):
            widths.append(int(s.items.shape[1]))
            low = s.replace(items=s.items[:-1, : s.sketch_size])
            top = s.items[-1:, :]
            slim.append((low, top))
        else:
            widths.append(None)
            slim.append(s)
    return tuple(slim), widths


def _assert_kll_slim_invariant(sizes: np.ndarray, sketch_size: int) -> None:
    """Losslessness of every slim-for-fetch variant rests on each non-top
    level holding <= sketch_size items at fetch time (guaranteed because
    every update/ingest/merge ends in a compaction cascade). A future code
    path fetching mid-append would otherwise silently truncate items; the
    shipped ``sizes`` let us fail loudly instead."""
    if (sizes[:-1] > sketch_size).any():
        raise AssertionError(
            "KLL slim-for-fetch invariant violated: non-top level holds "
            f"{int(sizes[:-1].max())} items > sketch_size "
            f"{sketch_size}; state was fetched mid-append"
        )


def _restore_kll_width(fetched: List[Any], widths: List[Optional[int]]) -> List[Any]:
    for i, width in enumerate(widths):
        if width is None:
            continue
        low_state, top = fetched[i]
        low = np.asarray(low_state.items)
        _assert_kll_slim_invariant(np.asarray(low_state.sizes), low_state.sketch_size)
        pad = np.full((low.shape[0], width - low.shape[1]), np.inf, dtype=low.dtype)
        items = np.concatenate(
            [np.concatenate([low, pad], axis=1), np.asarray(top)], axis=0
        )
        fetched[i] = low_state.replace(items=items)
    return fetched


#: host-side identity leaf values per scan signature: the slim fetch
#: reconstructs non-metric-bearing leaves from these instead of hauling
#: them over the feed link. One device round trip per SIGNATURE per
#: process (not per analyzer per pass).
_HOST_INIT_LEAVES = _BoundedLRU(1024)


def _host_init_leaf_values(a) -> List[np.ndarray]:
    key = _scan_signature(a)
    cached = _HOST_INIT_LEAVES.get(key)
    if cached is None:
        cached = [
            np.asarray(leaf)
            for leaf in jax.tree_util.tree_leaves(a.init_state())
        ]
        _HOST_INIT_LEAVES[key] = cached
    return cached


def _slim_metric_leaves(analyzers, states: Tuple):
    """Replace each analyzer's NON-metric-bearing state leaves (per
    ``Analyzer.metric_leaves``) with zero-size placeholders so they cost
    nothing on the feed link; returns (slimmed states, restore plan). Only
    called on runs that neither persist nor aggregate states — the metric
    never reads the dropped leaves, so reconstructing them from identity
    values (:func:`_restore_slim_leaves`) is observationally lossless."""
    plan: List[Tuple[int, List[int]]] = []
    out = list(states)
    for i, a in enumerate(analyzers):
        idx = a.metric_leaves()
        if idx is None:
            continue
        leaves, treedef = jax.tree_util.tree_flatten(out[i])
        keep = {int(j) for j in idx}
        dropped = [j for j in range(len(leaves)) if j not in keep]
        if not dropped:
            continue
        for j in dropped:
            leaves[j] = jnp.zeros((0,), jnp.asarray(leaves[j]).dtype)
        out[i] = jax.tree_util.tree_unflatten(treedef, leaves)
        plan.append((i, dropped))
    return tuple(out), plan


def _restore_slim_leaves(analyzers, fetched: List[Any], plan) -> List[Any]:
    for i, dropped in plan:
        init_leaves = _host_init_leaf_values(analyzers[i])
        leaves, treedef = jax.tree_util.tree_flatten(fetched[i])
        for j in dropped:
            leaves[j] = init_leaves[j]
        fetched[i] = jax.tree_util.tree_unflatten(treedef, leaves)
    return fetched


#: floor on statically-slimmed KLL item bytes below which the two-phase
#: fetch is never considered (the economic gate below also weighs the
#: probed link bandwidth/latency)
_TWO_PHASE_KLL_BYTES = 1 << 20

#: fraction of the slimmed bytes the occupied-levels slice typically drops
#: (~log2(rows/k) of 32 levels occupied)
_TWO_PHASE_EXPECTED_SAVING = 0.6


def _fetch_states_packed(states: Tuple, analyzers=None) -> List[Any]:
    """Device states -> host numpy pytrees via packed D2H transfers.

    In x64 mode, leaves that are natively <= 32-bit (KLL item buffers are
    f32[levels, 4k] — by far the largest states) ship bit-exact through the
    u8-bitcast buffer instead of being upcast to f64, halving the bytes on
    the feed link; 64-bit leaves ride the f64 buffer as before. Both packs
    dispatch before either blocks, so the link sees back-to-back transfers.
    KLL item buffers additionally ship only their occupied column range
    (see _slim_kll_for_fetch) and are re-padded host-side; when the
    battery carries enough sketch bytes, the two-phase variant also drops
    every level row above the deepest occupied one.

    With ``analyzers`` (the SLIM fetch — runs that neither persist nor
    aggregate states), each analyzer's non-metric-bearing leaves are
    dropped from the transfer entirely and reconstructed host-side from
    identity values (see ``Analyzer.metric_leaves``); everything above
    composes on top."""
    from ..ops.kll import KLLSketchState

    fault_point("state_fetch")
    slim_plan = None
    if analyzers is not None:
        from ..config import slim_fetch_enabled

        if slim_fetch_enabled() and len(analyzers) == len(states):
            states, slim_plan = _slim_metric_leaves(analyzers, states)

    def finish(fetched: List[Any]) -> List[Any]:
        if slim_plan:
            fetched = _restore_slim_leaves(analyzers, fetched, slim_plan)
        return fetched

    kll_idx = [
        i for i, s in enumerate(states)
        if isinstance(s, KLLSketchState)
        and s.items.ndim == 2
        and s.items.shape[1] > s.sketch_size
    ]
    slim_bytes = sum(
        ((states[i].items.shape[0] - 1) * states[i].sketch_size
         + states[i].items.shape[1]) * states[i].items.dtype.itemsize
        for i in kll_idx
    )
    if slim_bytes > _TWO_PHASE_KLL_BYTES:
        # economic gate: splitting the fetch serializes one extra link
        # round trip, so it must buy more transfer time than it costs —
        # on a fast-but-latent link a few MB is cheaper in one shot
        bw_bytes_per_s = probe_feed_bandwidth() * 1e6
        expected_saving_s = _TWO_PHASE_EXPECTED_SAVING * slim_bytes / bw_bytes_per_s
        if expected_saving_s > probe_feed_latency():
            return finish(_fetch_states_two_phase(states, kll_idx))
    states, kll_widths = _slim_kll_for_fetch(states)
    if any(w is not None for w in kll_widths):
        return finish(
            _restore_kll_width(_fetch_states_packed_raw(states), kll_widths)
        )
    return finish(_fetch_states_packed_raw(states))


def _fetch_states_two_phase(states: Tuple, kll_idx: List[int]) -> List[Any]:
    """Two feed-link transfers instead of one, but only the OCCUPIED slice
    of each KLL item buffer crosses the link: phase A ships every state
    leaf except the item buffers (including the per-level ``sizes``), the
    host derives each sketch's deepest occupied level, and phase B ships
    rows ``[0..T]`` at sketch_size width (typical occupancy is ~log2(rows/k)
    of the 32 levels, so this cuts the dominant fetch bytes another ~2-4x
    on top of the width slim). The reconstruction re-pads with the +inf
    structural padding; the non-top <= k occupancy invariant is asserted
    exactly like the one-phase slim. Shipped row counts round up to the
    next power of two so the packed-fetch program shapes stay stable
    across runs with different occupancy depths (no recompile per
    signature)."""
    placeholders = {i: states[i].items for i in kll_idx}
    stripped = list(states)
    for i in kll_idx:
        stripped[i] = states[i].replace(
            items=jnp.zeros((0, 0), states[i].items.dtype)
        )
    fetched = _fetch_states_packed_raw(tuple(stripped))

    slices: List[Any] = []
    metas: List[Tuple[int, int, bool]] = []
    for i in kll_idx:
        st = fetched[i]
        sizes = np.asarray(st.sizes)
        _assert_kll_slim_invariant(sizes, st.sketch_size)
        items = placeholders[i]
        levels = items.shape[0]
        k = st.sketch_size
        occupied = np.nonzero(sizes > 0)[0]
        top_level = int(occupied.max()) if occupied.size else -1
        if top_level == levels - 1:
            # the uncompacted top level can exceed k: ship it full width
            slices.append((items[: levels - 1, :k], items[levels - 1 :, :]))
            metas.append((i, 0, True))
        else:
            # power-of-two row count: stable packed-program shapes (at most
            # log2(levels) variants) at <= 2x the minimal bytes; rows above
            # the deepest occupied level are structural +inf padding
            rows = 1
            while rows < top_level + 1:
                rows *= 2
            rows = min(rows, levels - 1)
            slices.append(items[:rows, :k])
            metas.append((i, rows, False))
    fetched_items = _fetch_states_packed_raw(tuple(slices))

    for (i, rows, has_top), item in zip(metas, fetched_items):
        st = fetched[i]
        levels, width = placeholders[i].shape
        k = st.sketch_size
        full = np.full(
            (levels, width), np.inf, dtype=np.dtype(placeholders[i].dtype.name)
        )
        if has_top:
            low, top = item
            full[: levels - 1, :k] = np.asarray(low)
            full[levels - 1, :] = np.asarray(top)
        elif rows:
            full[:rows, :k] = np.asarray(item)
        fetched[i] = st.replace(items=full)
    return fetched


def _fetch_states_packed_raw(states: Tuple) -> List[Any]:
    leaves, treedef = jax.tree_util.tree_flatten(states)
    if not leaves:
        return list(states)
    leaves = [jnp.asarray(l) for l in leaves]
    x64 = jax.config.jax_enable_x64
    out_leaves: List[Any] = [None] * len(leaves)

    def unpack_f64(idx: List[int], flat: np.ndarray) -> None:
        offset = 0
        for i in idx:
            leaf = leaves[i]
            part = flat[offset:offset + leaf.size]
            out_leaves[i] = part.reshape(leaf.shape).astype(np.dtype(leaf.dtype.name))
            offset += leaf.size

    def unpack_u8(idx: List[int], raw: bytes) -> None:
        offset = 0
        for i in idx:
            leaf = leaves[i]
            dtype = np.dtype(leaf.dtype.name)
            host = np.frombuffer(raw, dtype=dtype, count=leaf.size, offset=offset)
            out_leaves[i] = host.reshape(leaf.shape).copy()
            offset += leaf.size * dtype.itemsize

    def unpack_u64(idx: List[int], raw: bytes) -> None:
        # inverse of _pack_leaves_u64_u8: per (shape, dtype) group, one
        # lo-u32 block then one hi-u32 block covering the whole group
        offset = 0
        for grp in _group_leaves(leaves, idx).values():
            n = sum(leaves[i].size for i in grp)
            lo = np.frombuffer(raw, dtype=np.uint32, count=n, offset=offset)
            offset += 4 * n
            hi = np.frombuffer(raw, dtype=np.uint32, count=n, offset=offset)
            offset += 4 * n
            vals = lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32))
            at = 0
            for i in grp:
                leaf = leaves[i]
                out_leaves[i] = (
                    vals[at : at + leaf.size]
                    .astype(np.dtype(leaf.dtype.name))
                    .reshape(leaf.shape)
                )
                at += leaf.size

    def start_d2h(arr):
        # kick off the device->host copy without blocking, so a second
        # packed buffer's transfer (and any remaining host work) overlaps
        # it; np.asarray then completes an already-in-flight copy
        if hasattr(arr, "copy_to_host_async"):
            try:
                arr.copy_to_host_async()
            except Exception:  # noqa: BLE001 - overlap is best-effort
                pass
        return arr

    if not x64:
        unpack_u8(_grouped_leaf_order(leaves), np.asarray(start_d2h(_pack_leaves_u8(leaves))).tobytes())
        return list(jax.tree_util.tree_unflatten(treedef, out_leaves))

    # HUGE leaves (a resident frequency key buffer is hundreds of MB, its
    # count table tens) transfer DIRECTLY: one leaf is one transfer anyway,
    # and skipping the stack/convert/repack round-trips saves several
    # full-buffer copies per side (on the CPU backend np.asarray of the
    # leaf is zero-copy: measured 2.3s packed -> ~0s direct for a 256MB
    # buffer). The packed paths exist to batch MANY SMALL leaves into few
    # transfers — past _DIRECT_LEAF_BYTES a leaf is its own bulk transfer.
    direct = [
        i for i, l in enumerate(leaves)
        if l.size * l.dtype.itemsize >= _DIRECT_LEAF_BYTES
    ]
    for i in direct:
        start_d2h(leaves[i])  # kick the D2H copy early; harvested below
    # remaining 8-byte UNSIGNED leaves (u64 hash keys) must never ride the
    # f64 upcast — values above 2^53 would round; they get the split-to-u32
    # bit-exact transfer. (int64 counters stay on the f64 path: they hold
    # row counts, far below 2^53 — the documented contract.)
    wide_u64 = [
        i for i, l in enumerate(leaves)
        if i not in set(direct)
        and l.dtype.itemsize == 8
        and np.dtype(l.dtype.name).kind == "u"
    ]
    packed_u64 = (
        start_d2h(_pack_leaves_u64_u8([leaves[i] for i in wide_u64]))
        if wide_u64
        else None
    )
    rest = [
        i for i in range(len(leaves))
        if i not in set(direct) and i not in set(wide_u64)
    ]

    def unpack_direct() -> None:
        for i in direct:
            out_leaves[i] = np.asarray(leaves[i])

    narrow = [i for i in rest if leaves[i].dtype.itemsize <= 4]
    narrow_bytes = sum(leaves[i].size * leaves[i].dtype.itemsize for i in narrow)
    if narrow_bytes < _NARROW_SPLIT_BYTES:
        if rest:
            unpack_f64(
                _grouped_leaf_order(leaves, rest),
                np.asarray(start_d2h(_pack_leaves_f64([leaves[i] for i in rest]))),
            )
        if packed_u64 is not None:
            unpack_u64(wide_u64, np.asarray(packed_u64).tobytes())
        unpack_direct()
        return list(jax.tree_util.tree_unflatten(treedef, out_leaves))

    wide = [i for i in rest if i not in set(narrow)]
    packed_narrow = start_d2h(_pack_leaves_u8([leaves[i] for i in narrow]))
    packed_wide = (
        start_d2h(_pack_leaves_f64([leaves[i] for i in wide])) if wide else None
    )
    # subset packs reindex their leaf lists, so group over the SUBSET in
    # its original positions — same keys, same encounter order
    unpack_u8(_grouped_leaf_order(leaves, narrow), np.asarray(packed_narrow).tobytes())
    if packed_wide is not None:
        unpack_f64(_grouped_leaf_order(leaves, wide), np.asarray(packed_wide))
    if packed_u64 is not None:
        unpack_u64(wide_u64, np.asarray(packed_u64).tobytes())
    unpack_direct()
    return list(jax.tree_util.tree_unflatten(treedef, out_leaves))


#: cached result of the device-feed bandwidth probe (MB/s), per process
_FEED_BANDWIDTH_MBPS: Optional[float] = None
_FEED_LATENCY_S: Optional[float] = None

#: feed bandwidth below which raw column streaming to the device loses to
#: host-side partial aggregation (a TPU-VM PCIe/DMA link runs at GB/s; a
#: remote tunnel runs at tens of MB/s)
_FEED_BANDWIDTH_THRESHOLD_MBPS = 500.0


def probe_feed_bandwidth() -> float:
    """Measured round-trip bandwidth (MB/s) of the default-device feed link,
    cached per process. A put+get round trip forces a REAL transfer — put
    alone can report completion before bytes move on relayed transports.

    The first transfer of a process can pay one-time backend/tunnel
    initialization; an untimed warm-up plus best-of-3 keeps a transient
    stall from silently flipping every later auto-placement decision."""
    global _FEED_BANDWIDTH_MBPS, _FEED_LATENCY_S
    if _FEED_BANDWIDTH_MBPS is None:
        # 1MB payload keeps probing a 6MB/s tunnel at ~1s, not ~5s; fixed
        # round-trip LATENCY is measured separately with a tiny transfer and
        # subtracted, so a fast-but-latent link (e.g. 1GB/s at 4ms RTT, which
        # a raw 1MB timing would score at ~300MB/s) is not misclassified to
        # the host tier
        arr = np.zeros(1 << 17, dtype=np.float64)
        tiny = np.zeros(512, dtype=np.float64)  # 4KB: pure-latency proxy
        import time

        np.asarray(jax.device_put(arr))  # untimed warm-up
        latency = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(jax.device_put(tiny))
            latency = min(latency, time.perf_counter() - t0)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            d = jax.device_put(arr)
            np.asarray(d)
            elapsed = time.perf_counter() - t0
            transfer = max(elapsed - latency, 1e-9)
            best = max(best, 2 * arr.nbytes / transfer / 1e6)
        _FEED_BANDWIDTH_MBPS = best
        _FEED_LATENCY_S = latency
    return _FEED_BANDWIDTH_MBPS


def probe_feed_latency() -> float:
    """Round-trip latency (seconds) of the feed link; probes on first use."""
    probe_feed_bandwidth()
    return _FEED_LATENCY_S if _FEED_LATENCY_S is not None else 0.0


def resolve_scan_placement(scan_analyzers, placement, monitor=None) -> str:
    """THE ingest-tier decision for a fused scan pass: "device" streams
    batches to the accelerator, "host" folds per-analyzer partials in a
    thread pool. Module-level (not a method) because the runner's
    device-frequency eligibility gate must ask the same question BEFORE
    an engine exists — one copy means the two can never drift.

    - a battery with any device-only analyzer (no host partial) streams
      to the device regardless of the requested placement
    - explicit "host"/"device" placements are honored otherwise
    - "auto" probes the feed link: below the bandwidth threshold, host
      partials win (composes with a mesh: _run_host_tier shards the fold
      over the devices — streaming raw columns over a slow feed would
      starve ALL chips at once)
    """
    from ..utils import env_str

    effective = placement or env_str(PLACEMENT_ENV, "auto")
    if not scan_analyzers:
        return "device"
    if not all(a.supports_host_partial for a in scan_analyzers):
        return "device"
    if effective == "host":
        return "host"
    if effective == "auto":
        bw = probe_feed_bandwidth()
        if monitor is not None:
            monitor.feed_bandwidth_mbps = bw
        if bw < _FEED_BANDWIDTH_THRESHOLD_MBPS:
            return "host"
    return "device"


class _DeviceFeatureCache:
    """Device-RESIDENT feature cache (opt-in): per-(table, batching,
    battery) feature arrays stay in HBM across passes and runs, so a warm
    run over the same dataset streams nothing over the feed link — the
    device-placement analog of a cached columnar scan. Strong table refs
    pin the id()-based keys.

    Entries group by their source TABLE; when the byte budget is exhausted,
    whole least-recently-used table groups are evicted — dropping the Arrow
    table pin with them — so a long-lived service rotating across datasets
    cannot grow host + HBM footprint monotonically. The group currently
    being admitted is never evicted to make room for itself (evicting batch
    0 to admit batch N of the same table would thrash every pass); when no
    other group can be freed, admission stops and that is logged once."""

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self.bytes = 0
        self.store: Dict[Tuple, Dict[str, Any]] = {}
        self.tables: Dict[int, Any] = {}
        self.evictions = 0
        #: table-id groups in least-recently-USED-first order
        self._group_order: "OrderedDict[int, None]" = OrderedDict()
        self._group_keys: Dict[int, List[Tuple]] = {}
        self._group_bytes: Dict[int, int] = {}
        self._admission_stop_logged = False
        self._lock = _threading.Lock()

    def get(self, key: Tuple) -> Optional[Dict[str, Any]]:
        with self._lock:
            features = self.store.get(key)
            if features is not None:
                self._group_order.move_to_end(key[0])
            return features

    def admit(
        self, key: Tuple, table: Any, features: Dict[str, Any], nbytes: int
    ) -> bool:
        """Insert ``features`` under ``key`` (whose first element is the
        source table's id), evicting LRU table groups as needed. Returns
        False when the entry cannot fit without evicting its own group."""
        table_id = key[0]
        with self._lock:
            if key in self.store:
                # two workers prepared the same batch concurrently: keep the
                # first insert (double-inserting would double-count bytes
                # and leave a duplicate group key that breaks eviction)
                self._group_order.move_to_end(table_id)
                return True
            if nbytes + self._group_bytes.get(table_id, 0) > self.budget:
                # no amount of eviction can ever fit this entry (its OWN
                # group is never evicted for it) — refuse UP FRONT instead
                # of flushing every other warm group for nothing
                self._log_admission_stop(nbytes)
                return False
            while (
                self.bytes + nbytes > self.budget
                and self._evict_lru_group(exclude=table_id)
            ):
                pass
            if self.bytes + nbytes > self.budget:
                self._log_admission_stop(nbytes)
                return False
            self.store[key] = features
            self.bytes += nbytes
            self.tables[table_id] = table
            self._group_keys.setdefault(table_id, []).append(key)
            self._group_bytes[table_id] = (
                self._group_bytes.get(table_id, 0) + nbytes
            )
            if table_id in self._group_order:
                self._group_order.move_to_end(table_id)
            else:
                self._group_order[table_id] = None
            return True

    def _log_admission_stop(self, nbytes: int) -> None:
        if not self._admission_stop_logged:
            self._admission_stop_logged = True
            _logger.warning(
                "device feature cache stopped admitting: entry of %d bytes "
                "does not fit the %d-byte budget (%d bytes in use by "
                "unevictable entries); raise %s or expect cold feeds for "
                "the overflow batches",
                nbytes, self.budget, self.bytes, DEVICE_FEATURE_CACHE_ENV,
            )

    def _evict_lru_group(self, exclude: int) -> bool:
        for table_id in self._group_order:
            if table_id == exclude:
                continue
            del self._group_order[table_id]
            for key in self._group_keys.pop(table_id):
                del self.store[key]
            freed = self._group_bytes.pop(table_id)
            self.bytes -= freed
            self.tables.pop(table_id, None)
            self.evictions += 1
            _logger.info(
                "device feature cache evicted table group %d (%d bytes)",
                table_id, freed,
            )
            return True
        return False

    def clear(self) -> None:
        with self._lock:
            self.store.clear()
            self.tables.clear()
            self.bytes = 0
            self._group_order.clear()
            self._group_keys.clear()
            self._group_bytes.clear()
            self._admission_stop_logged = False


#: env var overriding the host ingest tier's partial-worker pool size
#: (default: all cores). The `tools/host_tier_sweep.py` scaling sweep
#: drives this; PERF.md records the measured workers -> rows/s curve.
HOST_TIER_WORKERS_ENV = "DEEQU_TPU_HOST_TIER_WORKERS"

#: env var enabling the device feature cache; value = HBM budget in GB
DEVICE_FEATURE_CACHE_ENV = "DEEQU_TPU_DEVICE_FEATURE_CACHE"
_DEVICE_FEATURE_CACHE: Optional[_DeviceFeatureCache] = None


def device_feature_cache() -> Optional[_DeviceFeatureCache]:
    from ..utils import env_number

    global _DEVICE_FEATURE_CACHE
    if getattr(_CACHE_BYPASS, "active", False):
        return None  # warm-run sample features must not enter the budget
    budget_gb = env_number(DEVICE_FEATURE_CACHE_ENV, 0.0, float, minimum=0.0)
    if not budget_gb:
        return None
    if _DEVICE_FEATURE_CACHE is None:
        _DEVICE_FEATURE_CACHE = _DeviceFeatureCache(int(budget_gb * 1e9))
    return _DEVICE_FEATURE_CACHE


def clear_device_feature_cache() -> None:
    global _DEVICE_FEATURE_CACHE
    if _DEVICE_FEATURE_CACHE is not None:
        _DEVICE_FEATURE_CACHE.clear()
    _DEVICE_FEATURE_CACHE = None


_INGEST_CACHE: Dict[Tuple, Any] = {}

#: batches folded per ingest-program call; fixed so the program shape (and
#: therefore the compile) is independent of the run's batch count
_INGEST_CHUNK = 32

#: analyzers per ingest sub-program: bundles of same-SIGNATURE analyzers
#: share one compiled program (a 50-column battery folds through ~3 small
#: compiles instead of one mega-program; signatures repeat across runs and
#: datasets, so cold runs converge on warm)
_INGEST_BUNDLE = 8

from ..utils import BoundedLRU

_INGEST_SIG_CACHE = BoundedLRU(4096)


def _ingest_signature(a: ScanShareableAnalyzer) -> Tuple:
    """Program-identity key of an analyzer's ingest fold: class + state
    tree structure + leaf shapes/dtypes. Valid because every
    ``ingest_partial`` implementation is a pure function of the state and
    partial VALUES given the class and state shapes — column names,
    predicates, regexes and where-filters act host-side (feature
    computation), never inside the fold — so two same-class analyzers over
    different columns share one compiled program."""
    sig = _INGEST_SIG_CACHE.get(a)
    if sig is None:
        shapes = jax.eval_shape(a.init_state)
        leaves, treedef = jax.tree_util.tree_flatten(shapes)
        sig = (
            type(a),
            str(treedef),
            tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
        )
        _INGEST_SIG_CACHE[a] = sig
    return sig


def _ingest_bundles(analyzers: Tuple[ScanShareableAnalyzer, ...]):
    """Signature-homogeneous ingest bundles (see :func:`_signature_bundles`
    for the partitioning/padding policy, shared with the device scan)."""
    return _signature_bundles(analyzers, _ingest_signature, _INGEST_BUNDLE)


_INGEST_INIT_CACHE: Dict[Tuple, Any] = {}


def _ingest_init_program(bundle: Tuple[ScanShareableAnalyzer, ...]):
    """jit'd identity-state constructor for one bundle (signature-cached,
    same validity argument as _ingest_program: init values depend only on
    class + shapes)."""
    key = tuple(_ingest_signature(a) for a in bundle)
    prog = _INGEST_INIT_CACHE.get(key)
    if prog is None:
        prog = jax.jit(lambda: tuple(a.init_state() for a in bundle))
        _INGEST_INIT_CACHE[key] = prog
    return prog


def _ingest_program(bundle: Tuple[ScanShareableAnalyzer, ...]):
    """jit'd fold of stacked host partials into device states via lax.scan —
    the device-side half of the host ingest tier (the merge tree the TPU
    owns; batch count appears only as the scan length). Padding steps in
    the tail chunk compute-then-select (see make_flagged_ingest_body): the
    wasted work is a few identity folds once per run, bought against ~35%
    of the fold's compile time. Cached by SIGNATURE: all bundles of
    same-class/same-shape analyzers reuse one program."""
    key = tuple(_ingest_signature(a) for a in bundle)
    cached = _INGEST_CACHE.get(key)
    if cached is not None:
        return cached

    body = make_flagged_ingest_body(bundle)

    def fold(states, flags, stacked):
        out, _ = jax.lax.scan(body, states, (flags, stacked))
        return out

    # no donation: a tail-padded bundle passes one state buffer twice (the
    # pad slots), and per-analyzer states are small enough that the copy is
    # noise at chunk granularity
    program = jax.jit(fold)
    _INGEST_CACHE[key] = program
    return program


def make_flagged_ingest_body(analyzers: Tuple[ScanShareableAnalyzer, ...]):
    """The scan body folding one (flag, partial) step into the states;
    identity when the flag marks a padding entry. Shared by the
    single-device ingest program and the sharded mesh fold
    (parallel.sharded_ingest_fold) so the two paths cannot drift.

    Padding steps compute-then-SELECT rather than `lax.cond`-branch: only
    the tail chunk ever carries padding, so the skipped work is negligible,
    while a cond would compile BOTH branches (measured ~35% of the ingest
    fold's compile time, which dominates cold runs)."""

    def body(states, xs):
        flag, partial_slice = xs
        applied = tuple(
            a.ingest_partial(s, p)
            for a, s, p in zip(analyzers, states, partial_slice)
        )
        kept = jax.tree_util.tree_map(
            lambda new, old: jnp.where(flag, new, old), applied, states
        )
        return kept, None

    return body


class ScanEngine:
    """One shared pass: device-fused scan analyzers + host accumulators.

    ``placement`` decides where the per-row work happens:

    - ``"device"``: stream raw column batches to the accelerator; the fused
      XLA program does everything (the default on TPU-VM-class feed links).
    - ``"host"``: the native C ingest tier computes per-batch partial states
      next to the data and the device folds the tiny partials — the same
      partial-aggregate/merge split Spark runs executor-side (reference
      `AnalysisRunner.scala:303-318`). Chosen when raw streaming would be
      feed-bandwidth-bound.
    - ``"auto"`` (default, or env DEEQU_TPU_PLACEMENT): probe the feed link
      once per process and pick.
    """

    def __init__(
        self,
        scan_analyzers: Sequence[ScanShareableAnalyzer],
        monitor: Optional[RunMonitor] = None,
        sharding: Optional[Any] = None,
        placement: Optional[str] = None,
    ):
        from ..utils import env_str

        self.scan_analyzers = list(scan_analyzers)
        self.monitor = monitor or RunMonitor()
        #: set when the watchdog abandons this engine's pass: the zombie
        #: thread checks it before flushing its cost ledger, so an
        #: abandoned pass's attribution never contaminates the monitor the
        #: failover re-pass (a NEW engine) is reporting into
        self._cancelled = _threading.Event()
        self.mesh = sharding  # a jax.sharding.Mesh -> row-sharded GSPMD scan
        self.placement = placement or env_str(PLACEMENT_ENV, "auto")
        self.builder = FeatureBuilder(
            [s for a in self.scan_analyzers for s in a.feature_specs()]
        )
        analyzers = self.scan_analyzers

        if not analyzers:
            self._update = None
        elif self._resolve_placement_inner() == "host":
            # the host tier never dispatches the fused device program;
            # building it here would register the battery in the program
            # cache while leaving it uncompiled (jit is lazy), which the
            # service's cache-aware placement would misread as warm
            self._update = None
        else:
            self._update = _fused_program(tuple(analyzers), self.mesh)

    def _resolve_placement(self) -> str:
        placement = self._resolve_placement_inner()
        self.monitor.placement = placement
        return placement

    def _resolve_placement_inner(self) -> str:
        return resolve_scan_placement(
            self.scan_analyzers, self.placement, self.monitor
        )

    def required_columns(self) -> List[str]:
        return self.builder.required_columns

    def _prepare(self, batch):
        """Host side of one batch: feature build + device placement. Runs on
        the prefetch thread so it overlaps the previous batch's device work
        (numpy / pyarrow / the native C++ kernels all release the GIL)."""
        with self.monitor.timed("feature_build"):
            features = self.builder.build(batch)
        fault_point("device_feed")
        with self.monitor.timed("device_feed"):
            if self.mesh is not None:
                from ..parallel import shard_features

                features = shard_features(
                    features, self.mesh, batch_rows=len(batch.row_mask)
                )
            else:
                features = jax.device_put(features)
        return features

    def run(
        self,
        data: Dataset,
        batch_size: Optional[int] = None,
        host_accumulators: Optional[Dict[Any, Any]] = None,
        host_update_fns: Optional[Dict[Any, Any]] = None,
        columns: Optional[Sequence[str]] = None,
        checkpointer: Optional[Any] = None,
        slim_fetch: bool = False,
    ) -> Tuple[List[Any], Dict[Any, Any]]:
        """Run the shared pass. Returns (device states per scan analyzer,
        host accumulator states keyed as given).

        ``checkpointer`` (a `reliability.IngestCheckpointer`) makes the
        multi-batch fold resumable: algebraic states persist every
        ``checkpointer.every`` batches, and a run over the same data shape
        restarts from the last checkpoint instead of batch 0 — the states
        fold identically (same batch boundaries, same batch indices), so
        the resumed result equals the uninterrupted one.

        ``slim_fetch``: the caller asserts the fetched states feed ONLY
        ``compute_metric_from`` (no persistence, no aggregation, no
        checkpoint) — each analyzer's non-metric-bearing leaves then skip
        the feed link and are reconstructed from identity values.

        Set ``DEEQU_TPU_PROFILE_DIR`` to capture a ``jax.profiler`` trace of
        every pass into that directory (SURVEY §5's optional profiler hook;
        view with tensorboard or Perfetto). The lightweight phase timers in
        RunMonitor are always on."""
        import contextlib

        from ..utils import env_str

        profile_dir = env_str(PROFILE_DIR_ENV)
        if profile_dir:
            import jax.profiler

            tracer = jax.profiler.trace(profile_dir)
        else:
            tracer = contextlib.nullcontext()
        with tracer:
            from ..reliability.watchdog import (
                rate_tracker,
                run_with_deadline,
                scan_deadline_s,
            )

            bs = effective_batch_size(data, batch_size)
            n_batches = max(1, -(-int(data.num_rows) // bs))
            n_rows = max(1, int(data.num_rows))
            tier = self._resolve_placement_inner()
            deadline = scan_deadline_s(n_rows, tier)
            bypass = getattr(_CACHE_BYPASS, "active", False)
            import time

            batches_before = self.monitor.batches
            t0 = time.perf_counter()
            with _trace.span(
                "engine_pass", kind="engine", tier=tier, rows=n_rows,
                batches=n_batches, analyzers=len(self.scan_analyzers),
            ):
                if deadline is None:
                    result = self._run_inner(
                        data, batch_size, host_accumulators, host_update_fns,
                        columns, checkpointer, slim_fetch,
                    )
                else:
                    # the pass body moves to the watchdog's worker thread;
                    # the per-thread cache-bypass flag (background warm
                    # runs) and the trace context must move with it, or a
                    # warm sample would enter the budget and the pass's
                    # phases would orphan into a fresh trace
                    ctx = _trace.capture()

                    def pass_body():
                        _CACHE_BYPASS.active = bypass
                        with _trace.attach(ctx):
                            return self._run_inner(
                                data, batch_size, host_accumulators,
                                host_update_fns, columns, checkpointer,
                                slim_fetch,
                            )

                    from ..exceptions import ScanStallError

                    try:
                        result = run_with_deadline(
                            pass_body, deadline, self.monitor, tier
                        )
                    except ScanStallError:
                        # the abandoned zombie must stop reporting costs
                        # into this monitor (best-effort: a flush already
                        # in flight at this instant is the same bounded
                        # race the rate tracker tolerates)
                        self._cancelled.set()
                        raise
            # only COMPLETED passes teach the rate tracker, and only
            # REPRESENTATIVE ones: background warm runs (1-row samples
            # under the cache bypass) and the batches a resume skipped
            # would both poison the EWMA toward a deadline no production
            # pass can meet — observe the batches this pass actually
            # processed (the monitor delta), never the nominal count. A
            # delta EXCEEDING the pass's own batch count proves another
            # pass (a watchdog-abandoned zombie, an overlapped profile
            # scan) bumped the shared monitor concurrently — skip the
            # observation rather than learn a contaminated rate
            if not bypass:
                folded = self.monitor.batches - batches_before
                if 0 < folded <= n_batches:
                    rate_tracker().observe(
                        tier, min(folded * bs, n_rows),
                        time.perf_counter() - t0,
                    )
            return result

    def _run_inner(
        self,
        data: Dataset,
        batch_size: Optional[int] = None,
        host_accumulators: Optional[Dict[Any, Any]] = None,
        host_update_fns: Optional[Dict[Any, Any]] = None,
        columns: Optional[Sequence[str]] = None,
        checkpointer: Optional[Any] = None,
        slim_fetch: bool = False,
    ) -> Tuple[List[Any], Dict[Any, Any]]:
        monitor = self.monitor
        monitor.bump("passes")
        bs = effective_batch_size(data, batch_size)
        if self.mesh is not None or checkpointer is not None:
            from ..parallel import mesh_batch_quantum

            # round to the LADDER quantum, not the mesh size: a checkpoint
            # pins batch_size, so batch boundaries must stay put when the
            # elastic layer rebuilds the mesh one rung smaller (8->4->2->1
            # all see the same effective batch size). Checkpointed
            # MESH-FREE runs round too — the documented mesh<->plain-host
            # resume legs need both sides to derive the same boundaries
            # from the same nominal batch size
            n_dev = 1 if self.mesh is None else int(self.mesh.devices.size)
            q = mesh_batch_quantum(n_dev)
            bs = ((bs + q - 1) // q) * q  # shardable batches
        host_states = dict(host_accumulators or {})
        update_fns = host_update_fns or {}
        has_battery = bool(self.scan_analyzers)
        if not has_battery and not host_states:
            return [], {}
        for a in self.scan_analyzers:
            # one probe per analyzer per pass: the injection point through
            # which tests pin "exactly the faulty analyzer degrades"
            fault_point("analyzer", tag=repr(a))
        # mesh runs checkpoint in CANONICAL (merged) form, so the meta is
        # mesh-shape independent: a checkpoint taken on 8 devices resumes
        # on 4 (the batch-size quantum above keeps batch boundaries put)
        ckpt = checkpointer
        resume = None
        ckpt_epoch = None
        if ckpt is not None:
            # fence any earlier pass over this checkpointer FIRST: a
            # watchdog-abandoned zombie still folding must not interleave
            # its saves with this pass's (see IngestCheckpointer.begin_run)
            ckpt_epoch = ckpt.begin_run()
            resume = ckpt.load(
                bs, int(data.num_rows), list(self.scan_analyzers),
                list(host_states), monitor=monitor,
            )
            if resume is not None:
                monitor.resumed_at_batch = resume.batch_index
                host_states.update(resume.host_states)
                _logger.info(
                    "resuming ingest from checkpoint at batch %d",
                    resume.batch_index,
                )
        if ckpt is not None:
            # checkpoints persist full states; a slim fetch would save
            # identity-valued leaves into the resume point
            slim_fetch = False
        if has_battery and self._resolve_placement() == "host":
            return self._run_host_tier(
                data, bs, host_states, update_fns, columns,
                checkpointer=ckpt, resume=resume, slim_fetch=slim_fetch,
                ckpt_epoch=ckpt_epoch,
            )
        if has_battery and self._update is None:
            # constructed under a host resolution but asked to run device
            # (defensive: resolution is deterministic per process)
            self._update = _fused_program(tuple(self.scan_analyzers), self.mesh)
        # device path: the packed carry IS the state; the pytree states only
        # materialize once, from unpack() after the last batch
        states: Tuple = ()
        cache_size_fn = getattr(self._update, "_cache_size", None)

        def compiled_count() -> int:
            try:
                return cache_size_fn() if cache_size_fn is not None else 0
            except Exception:  # noqa: BLE001
                return 0

        compiled_before = compiled_count()

        # pipelined pass: a single prefetch thread pulls batch i+1 and builds
        # its features while the (async-dispatched) device program chews on
        # batch i — the analog of Spark overlapping scan IO with aggregation
        batches = data.batches(bs, columns=columns)

        cache = device_feature_cache() if self._update is not None else None
        if cache is not None:
            cache_base = (
                id(data.arrow),
                bs,
                None if columns is None else tuple(columns),
                tuple(sorted(self.builder.specs)),
            )
        import itertools

        idx_counter = itertools.count()
        # the prefetch worker builds features on its own thread: carry the
        # trace context over so feature_build/device_feed phase spans stay
        # children of this pass instead of orphaning
        trace_ctx = _trace.capture()

        def produce():
            with _trace.attach(trace_ctx):
                return produce_inner()

        def produce_inner():
            index = next(idx_counter)
            try:
                batch = next(batches)
            except StopIteration:
                return None
            if self._update is None:
                return batch, None
            if cache is not None:
                key = cache_base + (index,)
                features = cache.get(key)
                if features is None:
                    features = self._prepare(batch)
                    nbytes = sum(v.nbytes for v in features.values())
                    # admit() pins the table only once something of it is
                    # cached (the id()-keyed entries must not outlive the
                    # table) and evicts LRU table groups to make room
                    cache.admit(key, data.arrow, features, nbytes)
                return batch, features
            return batch, self._prepare(batch)

        carry = self._update.init_carry() if self._update is not None else None
        cost_ledger = _CostLedger()
        folded = 0
        if resume is not None:
            # re-enter the fold at the checkpoint: restore the carry from
            # the persisted states and skip the already-folded batches
            # (index alignment preserved, so feature-cache keys and any
            # index-keyed analyzer logic see the same numbering)
            folded = resume.batch_index
            if self._update is not None:
                carry = self._update.pack_states(tuple(resume.scan_states))
            for _ in range(folded):
                next(idx_counter)
                next(batches)

        def save_checkpoint():
            with monitor.timed("checkpoint"):
                if carry is not None:
                    ck_states = _fetch_states_packed(self._update.unpack(carry))
                else:
                    ck_states = []
                ckpt.save(
                    folded, bs, int(data.num_rows),
                    list(self.scan_analyzers), ck_states, host_states,
                    epoch=ckpt_epoch,
                )
                monitor.bump("checkpoint_saves")

        # double-buffered feed pipeline (deequ_tpu.ingest.prefetch): the
        # feed thread stages batch k+1's feature build + host->device copy
        # (and with the default depth 2, k+2's) while batch k's fold
        # executes — transfer time hides under device compute instead of
        # serializing with it. DEEQU_TPU_PREFETCH_DEPTH=0 restores the
        # serial path (the measured baseline for the overlap numbers).
        # Single-batch passes (every streaming micro-batch fold) stage
        # inline: there is nothing to overlap, and the feed-thread spawn
        # was pure fixed cost on the micro-fold path.
        from ..ingest.prefetch import PrefetchingBatchIterator, staging_depth

        n_total_batches = max(1, -(-int(data.num_rows) // bs))
        with PrefetchingBatchIterator(
            produce, depth=staging_depth(n_total_batches)
        ) as staged:
            for item in staged:
                batch, features = item
                monitor.bump("batches")
                if features is not None:
                    fault_point("device_update", tag=str(folded + 1))
                    with monitor.timed("device_dispatch"):
                        carry = self._update(
                            carry, features, ledger=cost_ledger,
                            probe=(folded % _COST_PROBE_EVERY == 1),
                        )
                    monitor.bump("device_updates")
                with monitor.timed("host_accumulators"):
                    for key, fn in update_fns.items():
                        host_states[key] = fn(host_states[key], batch)
                folded += 1
                if ckpt is not None and folded % ckpt.every == 0:
                    save_checkpoint()
        if ckpt is not None:
            ckpt.complete(ckpt_epoch)
        if carry is not None:
            # drain the async dispatch queue UNDER the dispatch timer:
            # device execution time belongs to device_dispatch, so the
            # state_fetch phase measures the transfer alone (previously the
            # blocking fetch absorbed all queued compute and the warm
            # profile read as fetch-bound when it was not)
            with monitor.timed("device_dispatch"):
                jax.block_until_ready(jax.tree_util.tree_leaves(carry))
            states = self._update.unpack_final(carry)
            carry = None  # donated — it must never be touched again
        compiled = compiled_count()
        with _MONITOR_LOCK:
            monitor.jit_compiles = max(monitor.jit_compiles, compiled)
            monitor.program_compiles += max(0, compiled - compiled_before)
        with monitor.timed("state_fetch"):
            host_side = _fetch_states_packed(
                states,
                analyzers=tuple(self.scan_analyzers) if slim_fetch else None,
            )
        if not self._cancelled.is_set():
            cost_ledger.flush(monitor)
        return host_side, host_states

    def _run_host_tier(
        self, data, bs, host_states, update_fns, columns,
        checkpointer: Optional[Any] = None, resume: Optional[Any] = None,
        slim_fetch: bool = False, ckpt_epoch: Optional[int] = None,
    ) -> Tuple[List[Any], Dict[Any, Any]]:
        """Host ingest tier: per-batch partial states next to the data, then
        chunked device folds of the stacked partials (+ one packed state
        fetch) — total device traffic is O(state size), independent of row
        count.

        Per-batch partials are computed on a thread pool spanning all cores:
        the native C kernels and numpy release the GIL, so this is the
        executor-side parallelism of the reference's partial aggregation
        (`AnalysisRunner.scala:303-318`) realized with threads instead of
        Spark tasks. Partials are folded IN BATCH ORDER (the KLL sampler
        offsets key on the batch index), so results are identical to the
        sequential fold regardless of scheduling. Grouping-analyzer
        accumulators (`update_fns`) fold on the submitting thread, overlapped
        with the pool's work."""
        import os

        from ..analyzers.base import HostBatchContext

        monitor = self.monitor
        analyzers = tuple(self.scan_analyzers)
        mesh = self.mesh
        elastic = None
        if mesh is not None:
            # mesh x host tier: per-device states, each fold shards the
            # chunk's partials over the devices; a final collective merge
            # combines the per-device states. The global chunk size stays
            # ~_INGEST_CHUNK so the padding waste is mesh-independent.
            # The ElasticMeshFold owns the states: a shard lost mid-pass is
            # salvaged (surviving states merge), the mesh rebuilds one
            # ladder rung down and the lost shard's batches replay below.
            from ..parallel import ElasticMeshFold

            n_dev = int(mesh.devices.size)
            local_chunk = max(1, _INGEST_CHUNK // n_dev)
            chunk = local_chunk * n_dev
            elastic = ElasticMeshFold(analyzers, mesh, monitor=monitor)
            states = elastic.states
            program = None
        else:
            chunk = _INGEST_CHUNK
            bundles = _ingest_bundles(analyzers)
            program = [
                ((b, n_real_b), _ingest_program(tuple(analyzers[i] for i in b)))
                for b, n_real_b in bundles
            ]
            try:
                ingest_compiled_before = sum(
                    p._cache_size()
                    for p in {id(p): p for _, p in program}.values()
                )
            except Exception:  # noqa: BLE001
                ingest_compiled_before = 0
            # identity states built ON DEVICE, one jit'd dispatch per bundle
            # (eager per-analyzer init_state cost one feed-link dispatch per
            # state LEAF — ~12s of a 300-analyzer cold profile)
            states_list: List[Any] = [None] * len(analyzers)
            for b, n_real_b in bundles:
                sub = _ingest_init_program(tuple(analyzers[i] for i in b))()
                for j in range(n_real_b):
                    states_list[b[j]] = sub[j]
            states = tuple(states_list)
        start_batch = 0
        host_start = 0
        if resume is not None:
            start_batch = resume.batch_index
            # accumulators fold per SUBMITTED batch (ahead of the chunked
            # scan states), so they resume from their own high-water mark
            host_start = resume.host_batch_index
            if elastic is not None:
                # checkpoints store CANONICAL merged states: seeding them
                # into shard 0 of whatever mesh THIS run has is what makes
                # a checkpoint taken under one mesh shape resume under a
                # smaller one
                elastic.seed(tuple(resume.scan_states), start_batch)
                states = elastic.states
            else:
                states = tuple(resume.scan_states)

        # one token per pass: host partials may skip work a previous batch
        # of the SAME pass already contributed (e.g. HLL registers of
        # dictionary entries already seen) but never across passes
        run_token = object()

        # host partials run on a pool spanning all cores: carry the trace
        # context so host_partials phase spans stay in this pass's tree
        trace_ctx = _trace.capture()
        cost_ledger = _CostLedger()
        # repr strings precomputed once per pass (never on the fold path)
        bundle_reprs = (
            [[repr(analyzers[i]) for i in b[:n_real_b]]
             for (b, n_real_b), _ in program]
            if program is not None else []
        )

        def compute_partial(index: int, batch, token=None) -> Tuple:
            with _trace.attach(trace_ctx):
                fault_point("host_partial", tag=str(index))
                with monitor.timed("host_partials"):
                    ctx = HostBatchContext(
                        batch, batch_index=index,
                        run_token=token if token is not None else run_token,
                    )
                    return tuple(a.host_partial(ctx) for a in analyzers)

        def stack_group(group: List[Tuple]) -> Tuple:
            return tuple(
                jax.tree_util.tree_map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]),
                    *[p[i] for p in group],
                )
                for i in range(len(analyzers))
            )

        def fold_chunk(states, group: List[Tuple], n_real: int):
            import time as _time

            fault_point("ingest_fold")
            with monitor.timed("ingest_fold"):
                stacked = stack_group(group)
                flags = np.zeros(len(group), dtype=bool)
                flags[:n_real] = True
                monitor.bump("device_updates")
                if elastic is not None:
                    first = progress["folded"]
                    return elastic.fold(
                        stacked, flags,
                        batch_indices=range(first, first + n_real),
                    )
                # per-bundle async dispatches; states reassemble in the
                # original analyzer order. Pad slots (positions >= n_real
                # in a tail bundle) re-fold an analyzer another bundle owns
                # and their outputs are discarded. Each bundle's dispatch
                # wall time is attributed evenly across its real slots —
                # the host-tier arm of per-analyzer cost attribution.
                out = list(states)
                for ((b, n_real_b), prog), reprs in zip(program, bundle_reprs):
                    t0 = _time.perf_counter()
                    sub = prog(
                        tuple(states[i] for i in b),
                        flags,
                        tuple(stacked[i] for i in b),
                    )
                    cost_ledger.add_bundle(reprs, _time.perf_counter() - t0)
                    for j in range(n_real_b):
                        out[b[j]] = sub[j]
                return tuple(out)

        from collections import deque

        from ..utils import env_number

        # a typo'd sweep var must not crash every host-tier pass (which
        # the resilience layer would then bisect N times): env_number
        # warns once — including on negatives — and keeps the core-count
        # default (0/unset = default)
        workers = env_number(HOST_TIER_WORKERS_ENV, 0, int, minimum=0)
        workers = workers or max(2, os.cpu_count() or 1)
        window = workers + chunk  # in-flight bound: O(window) live batches
        pending: deque = deque()
        buffer: List[Tuple] = []
        n = start_batch
        #: folded = batches merged into `states`; saved = last checkpoint.
        #: Host-tier checkpoints land on chunk boundaries (states only
        #: advance per chunk fold), so a resume point is always chunk-
        #: aligned and the resumed fold replays identically.
        progress = {"folded": start_batch, "saved": start_batch}

        def maybe_checkpoint(states):
            if checkpointer is None:
                return
            if progress["folded"] - progress["saved"] < checkpointer.every:
                return
            if elastic is not None and elastic.pending_replay:
                # a shard loss left batches awaiting replay: the canonical
                # merge does not cover them yet, so a checkpoint here would
                # under-count exactly the lost shard's batches on resume
                return
            with monitor.timed("checkpoint"):
                if elastic is not None:
                    # CANONICAL merged form: mesh-shape independent, so the
                    # resume point works on any (smaller) mesh or the host
                    ck_states = _fetch_states_packed(tuple(elastic.canonical()))
                    if elastic.pending_replay:
                        # a shard died DURING the canonical merge: the
                        # snapshot under-counts its batches — skip this
                        # save (the end-of-pass replay restores coverage)
                        return
                else:
                    ck_states = _fetch_states_packed(tuple(states))
                checkpointer.save(
                    progress["folded"], bs, int(data.num_rows),
                    list(analyzers), ck_states,
                    host_states, host_batch_index=n, epoch=ckpt_epoch,
                )
                monitor.bump("checkpoint_saves")
            progress["saved"] = progress["folded"]

        def drain_one(states):
            buffer.append(pending.popleft().result())
            if len(buffer) == chunk:
                states = fold_chunk(states, list(buffer), n_real=chunk)
                buffer.clear()
                progress["folded"] += chunk
                maybe_checkpoint(states)
            return states

        with ThreadPoolExecutor(max_workers=workers) as pool:
            for index, batch in enumerate(
                data.batches(bs, columns=columns, pad_to_batch_size=False)
            ):
                if index < start_batch:
                    continue  # already folded into the resumed states
                monitor.bump("batches")
                n += 1
                pending.append(pool.submit(compute_partial, index, batch))
                if index >= host_start:
                    with monitor.timed("host_accumulators"):
                        for key, fn in update_fns.items():
                            host_states[key] = fn(host_states[key], batch)
                # backpressure: never let un-drained batches outgrow the
                # window, so peak memory stays O(window), not O(dataset)
                while len(pending) > window:
                    states = drain_one(states)
            # consume the rest in submission order (partials fold in batch
            # order, so results equal the sequential fold exactly)
            while pending:
                states = drain_one(states)
        if buffer:
            # pad the tail chunk with identity partials so ONE compiled
            # scan-fold program serves every run regardless of batch count —
            # no recompile treadmill, warmups always hit; the validity flags
            # make the device skip the padding steps
            n_real = len(buffer)
            empty = _empty_batch_like(data, columns)
            ident = compute_partial(n, empty)
            buffer.extend([ident] * (chunk - n_real))
            states = fold_chunk(states, buffer, n_real=n_real)
        if program is not None:
            try:
                compiled = sum(
                    p._cache_size()
                    for p in {id(p): p for _, p in program}.values()
                )
                with _MONITOR_LOCK:
                    monitor.jit_compiles = max(
                        monitor.jit_compiles,
                        max(prog._cache_size() for _, prog in program),
                    )
                    monitor.program_compiles += max(
                        0, compiled - ingest_compiled_before
                    )
            except Exception:  # noqa: BLE001
                pass
        if elastic is not None:
            # replay the batches lost with dead shards: recompute exactly
            # those partials (same batch indices, so index-keyed analyzer
            # logic replays identically) and fold them on whatever mesh
            # survived. Loops because a shard can die during replay too.
            def replay_pending():
                todo = set(elastic.take_lost_batches())
                _trace.add_event("mesh_replay", batches=len(todo))
                _logger.warning(
                    "replaying %d batches lost with dead mesh shards",
                    len(todo),
                )
                # a FRESH memo token per replay round: the pass token's
                # cross-batch skip (the HLL dictionary memo) may have
                # credited an entry to a batch the DEAD shard owned —
                # replaying that batch under the old token would skip the
                # entry and silently undercount. Within one round the
                # fresh token may share (the first replayed batch that
                # sees an entry re-contributes it into a SURVIVING
                # shard); a loss during replay starts another round with
                # another fresh token.
                replay_token = object()
                replay_buf: List[Tuple] = []
                replay_idx: List[int] = []

                def flush_replay(n_real: int):
                    group = list(replay_buf)
                    if n_real < chunk:
                        ident = compute_partial(n, _empty_batch_like(data, columns))
                        group.extend([ident] * (chunk - n_real))
                    flags = np.zeros(chunk, dtype=bool)
                    flags[:n_real] = True
                    with monitor.timed("ingest_fold"):
                        elastic.fold(
                            stack_group(group), flags, batch_indices=replay_idx
                        )
                    replay_buf.clear()
                    replay_idx.clear()

                last_todo = max(todo)
                for index, batch in enumerate(
                    data.batches(bs, columns=columns, pad_to_batch_size=False)
                ):
                    if index > last_todo:
                        break  # replay cost scales with len(todo), not rows
                    if index not in todo:
                        continue
                    replay_buf.append(
                        compute_partial(index, batch, token=replay_token)
                    )
                    replay_idx.append(index)
                    if len(replay_buf) == chunk:
                        flush_replay(chunk)
                if replay_buf:
                    flush_replay(len(replay_buf))

            # butterfly-merge the per-device states into one (the
            # treeReduce analog, riding ICI); on a broken mesh the merge
            # itself recovers (salvage + re-shard, host merge last) — and
            # a loss DURING the merge queues the dead shard's batches, so
            # loop until a merge completes with nothing left to replay
            while True:
                while elastic.pending_replay:
                    replay_pending()
                states = elastic.finish()
                if not elastic.pending_replay:
                    break
        if checkpointer is not None:
            checkpointer.complete(ckpt_epoch)
        with monitor.timed("state_fetch"):
            host_side = _fetch_states_packed(
                states, analyzers=analyzers if slim_fetch else None
            )
        if not self._cancelled.is_set():
            cost_ledger.flush(monitor)
        return host_side, host_states
