"""ScanEngine: the fused single-pass executor.

Replaces the reference's `runScanningAnalyzers` fused `data.agg(...)` scan
(reference `analyzers/runners/AnalysisRunner.scala:289-336`): all requested
scan-shareable analyzers fold each padded batch into their states inside ONE
jit'd XLA program (fusion by the compiler, not row offsets), while grouping /
host-accumulated analyzers consume the same batch on the host — so the whole
run makes exactly one pass over the data.

``RunMonitor`` is the SparkMonitor analog (reference test fixture
`SparkMonitor.scala:39-76`): pass/batch/program counts are first-class
observables so tests can assert scan-sharing invariants, not just values.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analyzers.base import ScanShareableAnalyzer
from ..analyzers.grouping import FrequenciesAndNumRows, GroupingAnalyzer
from ..config import DEFAULT_BATCH_SIZE
from ..data import Dataset
from .features import FeatureBuilder


@dataclass
class RunMonitor:
    """Counts execution events for scan-sharing assertions. Also records
    which ingest tier a run executed on (``placement``) and the probed feed
    bandwidth that drove the decision, so every run's results are
    attributable to a code path."""

    passes: int = 0
    batches: int = 0
    device_updates: int = 0
    jit_compiles: int = 0
    placement: Optional[str] = None
    feed_bandwidth_mbps: Optional[float] = None

    def reset(self) -> None:
        self.passes = 0
        self.batches = 0
        self.device_updates = 0
        self.jit_compiles = 0
        self.placement = None
        self.feed_bandwidth_mbps = None


#: jit'd fused programs keyed by (analyzer battery, mesh) — analyzers are
#: frozen dataclasses, so identical batteries across runs reuse the SAME
#: compiled XLA program instead of re-tracing a fresh closure (re-compiles
#: cost tens of seconds for large batteries; values are kept for the process
#: lifetime, the analog of Spark's codegen cache)
_PROGRAM_CACHE: Dict[Tuple, Any] = {}


def _fused_program(analyzers: Tuple[ScanShareableAnalyzer, ...], mesh):
    key = (analyzers, None if mesh is None else tuple(mesh.devices.flat))
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    if mesh is not None:
        from ..parallel import sharded_update

        program = sharded_update(analyzers, mesh)
    else:
        def fused_update(states: Tuple, features: Dict[str, jax.Array]) -> Tuple:
            return tuple(a.update(s, features) for a, s in zip(analyzers, states))

        program = jax.jit(fused_update, donate_argnums=0)
    _PROGRAM_CACHE[key] = program
    return program


@jax.jit
def _pack_leaves_f64(leaves):
    """Concatenate every state leaf into ONE f64 device buffer. Fetching a
    state pytree leaf-by-leaf costs a full device round-trip per buffer,
    which on remote-tunnel devices (~100ms each) dominates the entire scan;
    one packed fetch costs a single round trip regardless of battery size.
    f64 represents every state dtype in use exactly (f32/f16 subsets; bool /
    (u)int8/16/32 exactly; int64 counters exactly up to 2^53 — counters are
    row counts, far below that). 64-bit *bitcasts* would be bit-perfect but
    the TPU x64-emulation rewriter does not implement them."""
    return jnp.concatenate(
        [jnp.ravel(leaf).astype(jnp.float64) for leaf in leaves]
    )


@jax.jit
def _pack_leaves_u8(leaves):
    """32-bit-mode packing: bitcast each (<=32-bit) leaf to raw bytes —
    bit-exact, and int32 values above f32's 2^24 integer range survive."""
    parts = []
    for leaf in leaves:
        if leaf.dtype == jnp.bool_:
            leaf = leaf.astype(jnp.uint8)
        parts.append(jnp.ravel(jax.lax.bitcast_convert_type(leaf, jnp.uint8)))
    return jnp.concatenate(parts)


def _empty_batch_like(data: Dataset, columns):
    """A 0-valid-row batch with the dataset's schema (identity partials)."""
    names = list(columns) if columns is not None else data.schema.names
    empty = data.arrow.slice(0, 0)
    for b in Dataset(empty).batches(1, columns=names):
        return b
    raise AssertionError("batches() always yields at least one batch")


def _fetch_states_packed(states: Tuple) -> List[Any]:
    """Device states -> host numpy pytrees via one packed D2H transfer."""
    leaves, treedef = jax.tree_util.tree_flatten(states)
    if not leaves:
        return list(states)
    leaves = [jnp.asarray(l) for l in leaves]
    x64 = jax.config.jax_enable_x64
    out_leaves = []
    if x64:
        flat = np.asarray(_pack_leaves_f64(leaves))
        offset = 0
        for leaf in leaves:
            part = flat[offset:offset + leaf.size]
            out_leaves.append(
                part.reshape(leaf.shape).astype(np.dtype(leaf.dtype.name))
            )
            offset += leaf.size
    else:
        raw = np.asarray(_pack_leaves_u8(leaves)).tobytes()
        offset = 0
        for leaf in leaves:
            dtype = np.dtype(leaf.dtype.name)
            host = np.frombuffer(raw, dtype=dtype, count=leaf.size, offset=offset)
            out_leaves.append(host.reshape(leaf.shape).copy())
            offset += leaf.size * dtype.itemsize
    return list(jax.tree_util.tree_unflatten(treedef, out_leaves))


#: cached result of the device-feed bandwidth probe (MB/s), per process
_FEED_BANDWIDTH_MBPS: Optional[float] = None

#: feed bandwidth below which raw column streaming to the device loses to
#: host-side partial aggregation (a TPU-VM PCIe/DMA link runs at GB/s; a
#: remote tunnel runs at tens of MB/s)
_FEED_BANDWIDTH_THRESHOLD_MBPS = 500.0


def probe_feed_bandwidth() -> float:
    """Measured round-trip bandwidth (MB/s) of the default-device feed link,
    cached per process. A put+get round trip forces a REAL transfer — put
    alone can report completion before bytes move on relayed transports.

    The first transfer of a process can pay one-time backend/tunnel
    initialization; an untimed warm-up plus best-of-3 keeps a transient
    stall from silently flipping every later auto-placement decision."""
    global _FEED_BANDWIDTH_MBPS
    if _FEED_BANDWIDTH_MBPS is None:
        arr = np.zeros(1 << 19, dtype=np.float64)  # 4 MB
        import time

        np.asarray(jax.device_put(arr))  # untimed warm-up
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            d = jax.device_put(arr)
            np.asarray(d)
            elapsed = max(time.perf_counter() - t0, 1e-9)
            best = max(best, 2 * arr.nbytes / elapsed / 1e6)
        _FEED_BANDWIDTH_MBPS = best
    return _FEED_BANDWIDTH_MBPS


_INGEST_CACHE: Dict[Tuple, Any] = {}

#: batches folded per ingest-program call; fixed so the program shape (and
#: therefore the compile) is independent of the run's batch count
_INGEST_CHUNK = 32


def _ingest_program(analyzers: Tuple[ScanShareableAnalyzer, ...]):
    """jit'd fold of stacked host partials into device states via lax.scan —
    the device-side half of the host ingest tier (the merge tree the TPU
    owns; batch count appears only as the scan length)."""
    cached = _INGEST_CACHE.get(analyzers)
    if cached is not None:
        return cached

    def body(states, partial_slice):
        new = tuple(
            a.ingest_partial(s, p)
            for a, s, p in zip(analyzers, states, partial_slice)
        )
        return new, None

    def fold(states, stacked):
        out, _ = jax.lax.scan(body, states, stacked)
        return out

    program = jax.jit(fold, donate_argnums=0)
    _INGEST_CACHE[analyzers] = program
    return program


class ScanEngine:
    """One shared pass: device-fused scan analyzers + host accumulators.

    ``placement`` decides where the per-row work happens:

    - ``"device"``: stream raw column batches to the accelerator; the fused
      XLA program does everything (the default on TPU-VM-class feed links).
    - ``"host"``: the native C ingest tier computes per-batch partial states
      next to the data and the device folds the tiny partials — the same
      partial-aggregate/merge split Spark runs executor-side (reference
      `AnalysisRunner.scala:303-318`). Chosen when raw streaming would be
      feed-bandwidth-bound.
    - ``"auto"`` (default, or env DEEQU_TPU_PLACEMENT): probe the feed link
      once per process and pick.
    """

    def __init__(
        self,
        scan_analyzers: Sequence[ScanShareableAnalyzer],
        monitor: Optional[RunMonitor] = None,
        sharding: Optional[Any] = None,
        placement: Optional[str] = None,
    ):
        import os

        self.scan_analyzers = list(scan_analyzers)
        self.monitor = monitor or RunMonitor()
        self.mesh = sharding  # a jax.sharding.Mesh -> row-sharded GSPMD scan
        self.placement = placement or os.environ.get("DEEQU_TPU_PLACEMENT", "auto")
        self.builder = FeatureBuilder(
            [s for a in self.scan_analyzers for s in a.feature_specs()]
        )
        analyzers = self.scan_analyzers

        if not analyzers:
            self._update = None
        else:
            self._update = _fused_program(tuple(analyzers), self.mesh)

    def _resolve_placement(self) -> str:
        placement = self._resolve_placement_inner()
        self.monitor.placement = placement
        return placement

    def _resolve_placement_inner(self) -> str:
        if self.mesh is not None or not self.scan_analyzers:
            return "device"  # sharded scans stream (partials are host-local)
        if not all(a.supports_host_partial for a in self.scan_analyzers):
            return "device"
        if self.placement == "host":
            return "host"
        if self.placement == "auto":
            bw = probe_feed_bandwidth()
            self.monitor.feed_bandwidth_mbps = bw
            if bw < _FEED_BANDWIDTH_THRESHOLD_MBPS:
                return "host"
        return "device"

    def required_columns(self) -> List[str]:
        return self.builder.required_columns

    def _prepare(self, batch):
        """Host side of one batch: feature build + device placement. Runs on
        the prefetch thread so it overlaps the previous batch's device work
        (numpy / pyarrow / the native C++ kernels all release the GIL)."""
        features = self.builder.build(batch)
        if self.mesh is not None:
            from ..parallel import shard_features

            features = shard_features(
                features, self.mesh, batch_rows=len(batch.row_mask)
            )
        else:
            features = jax.device_put(features)
        return features

    def run(
        self,
        data: Dataset,
        batch_size: Optional[int] = None,
        host_accumulators: Optional[Dict[Any, Any]] = None,
        host_update_fns: Optional[Dict[Any, Any]] = None,
        columns: Optional[Sequence[str]] = None,
    ) -> Tuple[List[Any], Dict[Any, Any]]:
        """Run the shared pass. Returns (device states per scan analyzer,
        host accumulator states keyed as given)."""
        monitor = self.monitor
        monitor.passes += 1
        bs = batch_size or min(DEFAULT_BATCH_SIZE, max(int(data.num_rows), 1))
        if self.mesh is not None:
            n_dev = self.mesh.devices.size
            bs = ((bs + n_dev - 1) // n_dev) * n_dev  # shardable batches
        states: Tuple = tuple(a.init_state() for a in self.scan_analyzers)
        host_states = dict(host_accumulators or {})
        update_fns = host_update_fns or {}
        if self._update is None and not host_states:
            return [], {}
        if self._update is not None and self._resolve_placement() == "host":
            return self._run_host_tier(
                data, bs, host_states, update_fns, columns, states
            )
        cache_size_fn = getattr(self._update, "_cache_size", None)

        # pipelined pass: a single prefetch thread pulls batch i+1 and builds
        # its features while the (async-dispatched) device program chews on
        # batch i — the analog of Spark overlapping scan IO with aggregation
        batches = data.batches(bs, columns=columns)

        def produce():
            try:
                batch = next(batches)
            except StopIteration:
                return None
            features = self._prepare(batch) if self._update is not None else None
            return batch, features

        with ThreadPoolExecutor(max_workers=1) as pool:
            pending = pool.submit(produce)
            while True:
                item = pending.result()
                if item is None:
                    break
                pending = pool.submit(produce)
                batch, features = item
                monitor.batches += 1
                if features is not None:
                    states = self._update(states, features)
                    monitor.device_updates += 1
                for key, fn in update_fns.items():
                    host_states[key] = fn(host_states[key], batch)
        if cache_size_fn is not None:
            try:
                monitor.jit_compiles = max(monitor.jit_compiles, cache_size_fn())
            except Exception:  # noqa: BLE001
                pass
        host_side = _fetch_states_packed(states)
        return host_side, host_states

    def _run_host_tier(
        self, data, bs, host_states, update_fns, columns, states
    ) -> Tuple[List[Any], Dict[Any, Any]]:
        """Host ingest tier: per-batch partial states next to the data, then
        chunked device folds of the stacked partials (+ one packed state
        fetch) — total device traffic is O(state size), independent of row
        count.

        Per-batch partials are computed on a thread pool spanning all cores:
        the native C kernels and numpy release the GIL, so this is the
        executor-side parallelism of the reference's partial aggregation
        (`AnalysisRunner.scala:303-318`) realized with threads instead of
        Spark tasks. Partials are folded IN BATCH ORDER (the KLL sampler
        offsets key on the batch index), so results are identical to the
        sequential fold regardless of scheduling. Grouping-analyzer
        accumulators (`update_fns`) fold on the submitting thread, overlapped
        with the pool's work."""
        import os

        from ..analyzers.base import HostBatchContext

        monitor = self.monitor
        analyzers = tuple(self.scan_analyzers)
        chunk = _INGEST_CHUNK
        program = _ingest_program(analyzers)

        def compute_partial(index: int, batch) -> Tuple:
            ctx = HostBatchContext(batch, batch_index=index)
            return tuple(a.host_partial(ctx) for a in analyzers)

        def fold_chunk(states, group: List[Tuple]):
            stacked = tuple(
                jax.tree_util.tree_map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]),
                    *[p[i] for p in group],
                )
                for i in range(len(analyzers))
            )
            monitor.device_updates += 1
            return program(states, stacked)  # async dispatch: fold overlaps

        from collections import deque

        workers = max(2, os.cpu_count() or 1)
        window = workers + chunk  # in-flight bound: O(window) live batches
        pending: deque = deque()
        buffer: List[Tuple] = []
        n = 0

        def drain_one(states):
            buffer.append(pending.popleft().result())
            if len(buffer) == chunk:
                states = fold_chunk(states, list(buffer))
                buffer.clear()
            return states

        with ThreadPoolExecutor(max_workers=workers) as pool:
            for index, batch in enumerate(
                data.batches(bs, columns=columns, pad_to_batch_size=False)
            ):
                monitor.batches += 1
                n += 1
                pending.append(pool.submit(compute_partial, index, batch))
                for key, fn in update_fns.items():
                    host_states[key] = fn(host_states[key], batch)
                # backpressure: never let un-drained batches outgrow the
                # window, so peak memory stays O(window), not O(dataset)
                while len(pending) > window:
                    states = drain_one(states)
            # consume the rest in submission order (partials fold in batch
            # order, so results equal the sequential fold exactly)
            while pending:
                states = drain_one(states)
        if buffer:
            # pad the tail chunk with identity partials so ONE compiled
            # scan-fold program serves every run regardless of batch count —
            # no recompile treadmill, warmups always hit
            empty = _empty_batch_like(data, columns)
            ident = compute_partial(n, empty)
            buffer.extend([ident] * (chunk - len(buffer)))
            states = fold_chunk(states, buffer)
        host_side = _fetch_states_packed(states)
        return host_side, host_states
