"""ScanEngine: the fused single-pass executor.

Replaces the reference's `runScanningAnalyzers` fused `data.agg(...)` scan
(reference `analyzers/runners/AnalysisRunner.scala:289-336`): all requested
scan-shareable analyzers fold each padded batch into their states inside ONE
jit'd XLA program (fusion by the compiler, not row offsets), while grouping /
host-accumulated analyzers consume the same batch on the host — so the whole
run makes exactly one pass over the data.

``RunMonitor`` is the SparkMonitor analog (reference test fixture
`SparkMonitor.scala:39-76`): pass/batch/program counts are first-class
observables so tests can assert scan-sharing invariants, not just values.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..analyzers.base import ScanShareableAnalyzer
from ..analyzers.grouping import FrequenciesAndNumRows, GroupingAnalyzer
from ..config import DEFAULT_BATCH_SIZE
from ..data import Dataset
from .features import FeatureBuilder


@dataclass
class RunMonitor:
    """Counts execution events for scan-sharing assertions."""

    passes: int = 0
    batches: int = 0
    device_updates: int = 0
    jit_compiles: int = 0

    def reset(self) -> None:
        self.passes = 0
        self.batches = 0
        self.device_updates = 0
        self.jit_compiles = 0


#: jit'd fused programs keyed by (analyzer battery, mesh) — analyzers are
#: frozen dataclasses, so identical batteries across runs reuse the SAME
#: compiled XLA program instead of re-tracing a fresh closure (re-compiles
#: cost tens of seconds for large batteries; values are kept for the process
#: lifetime, the analog of Spark's codegen cache)
_PROGRAM_CACHE: Dict[Tuple, Any] = {}


def _fused_program(analyzers: Tuple[ScanShareableAnalyzer, ...], mesh):
    key = (analyzers, None if mesh is None else tuple(mesh.devices.flat))
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    if mesh is not None:
        from ..parallel import sharded_update

        program = sharded_update(analyzers, mesh)
    else:
        def fused_update(states: Tuple, features: Dict[str, jax.Array]) -> Tuple:
            return tuple(a.update(s, features) for a, s in zip(analyzers, states))

        program = jax.jit(fused_update, donate_argnums=0)
    _PROGRAM_CACHE[key] = program
    return program


class ScanEngine:
    """One shared pass: device-fused scan analyzers + host accumulators."""

    def __init__(
        self,
        scan_analyzers: Sequence[ScanShareableAnalyzer],
        monitor: Optional[RunMonitor] = None,
        sharding: Optional[Any] = None,
    ):
        self.scan_analyzers = list(scan_analyzers)
        self.monitor = monitor or RunMonitor()
        self.mesh = sharding  # a jax.sharding.Mesh -> row-sharded GSPMD scan
        self.builder = FeatureBuilder(
            [s for a in self.scan_analyzers for s in a.feature_specs()]
        )
        analyzers = self.scan_analyzers

        if not analyzers:
            self._update = None
        else:
            self._update = _fused_program(tuple(analyzers), self.mesh)

    def required_columns(self) -> List[str]:
        return self.builder.required_columns

    def run(
        self,
        data: Dataset,
        batch_size: Optional[int] = None,
        host_accumulators: Optional[Dict[Any, Any]] = None,
        host_update_fns: Optional[Dict[Any, Any]] = None,
        columns: Optional[Sequence[str]] = None,
    ) -> Tuple[List[Any], Dict[Any, Any]]:
        """Run the shared pass. Returns (device states per scan analyzer,
        host accumulator states keyed as given)."""
        monitor = self.monitor
        monitor.passes += 1
        bs = batch_size or min(DEFAULT_BATCH_SIZE, max(int(data.num_rows), 1))
        if self.mesh is not None:
            n_dev = self.mesh.devices.size
            bs = ((bs + n_dev - 1) // n_dev) * n_dev  # shardable batches
        states: Tuple = tuple(a.init_state() for a in self.scan_analyzers)
        host_states = dict(host_accumulators or {})
        update_fns = host_update_fns or {}
        if self._update is None and not host_states:
            return [], {}
        cache_size_fn = getattr(self._update, "_cache_size", None)
        for batch in data.batches(bs, columns=columns):
            monitor.batches += 1
            if self._update is not None:
                features = self.builder.build(batch)
                if self.mesh is not None:
                    from ..parallel import shard_features

                    features = shard_features(
                        features, self.mesh, batch_rows=len(batch.row_mask)
                    )
                states = self._update(states, features)
                monitor.device_updates += 1
            for key, fn in update_fns.items():
                host_states[key] = fn(host_states[key], batch)
        if cache_size_fn is not None:
            try:
                monitor.jit_compiles = max(monitor.jit_compiles, cache_size_fn())
            except Exception:  # noqa: BLE001
                pass
        # bring device states to host numpy for merging/persistence/finalize;
        # device_get batches the copies (one async copy per leaf, then one
        # wait) — a per-leaf np.asarray would pay a full device round-trip
        # per scalar, which dominates everything on remote-tunnel devices
        host_side = list(jax.device_get(states))
        return host_side, host_states
