"""Re-export shim; the taxonomy lives in `deequ_tpu.exceptions` to avoid
package-init cycles."""

from ..exceptions import *  # noqa: F401,F403
from ..exceptions import (  # noqa: F401
    EmptyStateException,
    IllegalAnalyzerParameterException,
    MetricCalculationException,
    MetricCalculationPreconditionException,
    MetricCalculationRuntimeException,
    NoColumnsSpecifiedException,
    NoSuchColumnException,
    NumberOfSpecifiedColumnsException,
    WrongColumnTypeException,
    wrap_if_necessary,
)
