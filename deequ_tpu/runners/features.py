"""Host feature frontend: turns column batches into device-ready numeric
arrays (the union of all analyzers' FeatureSpecs, computed once per batch).

This is the scan-sharing mechanism: deequ shares one Spark scan between N
analyzers via fused aggregation columns with row offsets (reference
`analyzers/runners/AnalysisRunner.scala:303-318`); here N analyzers share one
host pass + one fused XLA program, and the features dict is their shared
input. String-typed work (regex, lengths, type inference, hashing) happens
here, vectorized on host, so the device program stays pure fixed-shape
numerics.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Sequence

import numpy as np

from ..analyzers.base import FeatureSpec
from ..data import Batch, ColumnKind
from ..expr import evaluate_predicate
from ..ops.hashing import hash_column
from ..ops.hll import hll_pack_features


def _hll_packed(col) -> np.ndarray:
    """uint16 HLL ingest feature for one column — native C++ single pass
    (hash + clz + pack) when built, numpy fallback otherwise."""
    from ..native import native_hll_pack_numeric, native_hll_pack_strings
    from ..ops.hashing import DEFAULT_SEED

    if _is_string_dict(col):
        # hash the DISTINCT values once per dataset, gather per row
        return hll_pack_features(dict_hashes(col), col.mask)
    if col.kind == ColumnKind.STRING:
        if native_hll_pack_strings is not None:
            src = col.string_source
            if not isinstance(src, np.ndarray) or src.dtype == object:
                return native_hll_pack_strings(src, col.mask, DEFAULT_SEED)
    elif col.kind == ColumnKind.BOOLEAN or col.kind.is_numeric:
        if native_hll_pack_numeric is not None:
            vals = col.values
            if vals.dtype == np.bool_ or (
                np.issubdtype(vals.dtype, np.integer) and vals.dtype != np.int64
            ):
                vals = vals.astype(np.int64)
            if np.issubdtype(vals.dtype, np.number):
                return native_hll_pack_numeric(vals, col.mask, DEFAULT_SEED)
    hashes = hash_column(col.values, col.mask, col.kind)
    return hll_pack_features(hashes, col.mask)

# reference regexes (`analyzers/catalyst/StatefulDataType.scala:36-38`);
# decision order: null -> fractional -> integral -> boolean -> string
# (`StatefulDataType.update`, same file). re.ASCII + fullmatch reproduce the
# Java Matcher semantics (ASCII \d, whole-string match incl. no trailing
# newline) and keep the native C++ kernel bit-identical.
_FRACTIONAL_RE = re.compile(r"(-|\+)? ?\d*\.\d*", re.ASCII)
_INTEGRAL_RE = re.compile(r"(-|\+)? ?\d*", re.ASCII)
_BOOLEAN_RE = re.compile(r"true|false")

TYPE_NULL, TYPE_FRACTIONAL, TYPE_INTEGRAL, TYPE_BOOLEAN, TYPE_STRING = range(5)


def classify_type_codes(values, mask: np.ndarray, kind: ColumnKind) -> np.ndarray:
    """Per-value inferred-type codes 0..4 (Unknown/Fractional/Integral/
    Boolean/String). Non-string columns map directly from their kind, which
    matches the reference's behavior of casting values to strings first
    (e.g. 1.5 -> "1.5" matches FRACTIONAL). ``values`` may be a pyarrow
    string array (buffer-direct native path, no object materialization)."""
    n = len(values)
    if kind == ColumnKind.STRING:
        from ..native import native_classify_types

        if native_classify_types is not None:
            return native_classify_types(values, mask)
        values = _as_object_array(values)
        out = np.full(n, TYPE_NULL, dtype=np.int32)
        for i in range(n):
            if not mask[i]:
                continue
            v = values[i]
            if v is None:
                continue
            if _FRACTIONAL_RE.fullmatch(v):
                out[i] = TYPE_FRACTIONAL
            elif _INTEGRAL_RE.fullmatch(v):
                out[i] = TYPE_INTEGRAL
            elif _BOOLEAN_RE.fullmatch(v):
                out[i] = TYPE_BOOLEAN
            else:
                out[i] = TYPE_STRING
        return out
    if kind == ColumnKind.FRACTIONAL:
        code = TYPE_FRACTIONAL
    elif kind == ColumnKind.INTEGRAL:
        code = TYPE_INTEGRAL
    elif kind == ColumnKind.BOOLEAN:
        code = TYPE_BOOLEAN
    else:
        code = TYPE_STRING
    return np.where(mask, np.int32(code), np.int32(TYPE_NULL)).astype(np.int32)


from ..ops.hashing import as_object_array as _as_object_array  # noqa: E402


def string_lengths(values, mask: np.ndarray) -> np.ndarray:
    from ..native import native_string_lengths

    if native_string_lengths is not None:
        return native_string_lengths(values, mask)
    values = _as_object_array(values)
    out = np.zeros(len(values), dtype=np.int32)
    for i in np.flatnonzero(mask):
        v = values[i]
        if v is not None:
            out[i] = len(v)
    return out


def regex_matches(values, mask: np.ndarray, pattern: str) -> np.ndarray:
    """Unanchored regex search per value, nulls -> False (the reference uses
    `regexp_extract(col, pattern, 0) != ""`, `analyzers/PatternMatch.scala:
    46-52` — note a successful empty-string match also counts as False there,
    which we reproduce). ``values`` may be a pyarrow string array, in which
    case the GIL-free PCRE2 kernel runs over the Arrow buffers directly
    (undecidable rows are re-checked under Python `re`)."""
    from ..native import native_pattern_match

    if native_pattern_match is not None and (
        not isinstance(values, np.ndarray) or values.dtype == object
    ):
        try:
            out = native_pattern_match(values, mask, pattern)
        except Exception:  # noqa: BLE001 - e.g. non-UTF-8-able objects
            out = None
        if out is not None:
            return out
    values = _as_object_array(values)
    compiled = re.compile(pattern)
    out = np.zeros(len(values), dtype=bool)
    for i in np.flatnonzero(mask):
        v = values[i]
        if v is None:
            continue
        m = compiled.search(str(v))
        out[i] = bool(m) and m.group(0) != ""
    return out


def dict_regex_matches(col, pattern: str) -> np.ndarray:
    """Per-row regex matches for a dictionary STRING column: each DISTINCT
    entry is matched once per dataset (cached in col.aux, keyed by
    pattern) under Python `re` — exact semantics at O(distinct) cost —
    then gathered by code. Null/padding rows -> False."""
    key = ("regex", pattern)
    per_entry = col.aux.get(key)
    if per_entry is None:
        ones = np.ones(col.num_categories, dtype=bool)
        per_entry = regex_matches(col.dictionary_source, ones, pattern)
        col.aux[key] = per_entry
    num_cats = col.num_categories
    if not num_cats:
        return np.zeros(len(col.codes), dtype=bool)
    safe = np.where(col.codes < num_cats, col.codes, 0)
    return per_entry[safe] & col.mask


def column_regex_matches(col, pattern: str) -> np.ndarray:
    """The one regex entry point for a Column: dictionary fast path when
    possible, else buffer-direct native / Python fallback."""
    if _is_string_dict(col):
        return dict_regex_matches(col, pattern)
    if col.kind == ColumnKind.STRING and col.arrow is not None:
        return regex_matches(col.arrow, col.mask, pattern)
    return regex_matches(col.values, col.mask, pattern)


def dict_entry_type_codes(col) -> np.ndarray:
    """Type codes of each DISTINCT dictionary value, classified once per
    dataset (cached in col.aux across batches)."""
    tc = col.aux.get("type_codes")
    if tc is None:
        ones = np.ones(col.num_categories, dtype=bool)
        tc = classify_type_codes(col.dictionary_source, ones, ColumnKind.STRING)
        col.aux["type_codes"] = tc
    return tc


def dict_type_codes(col) -> np.ndarray:
    """Per-row type codes for a dictionary STRING column: classify the
    DISTINCT values once, gather by code. Null/padding rows -> TYPE_NULL."""
    tc = dict_entry_type_codes(col)
    num_cats = col.num_categories
    safe = np.where(col.codes < num_cats, col.codes, 0)
    out = tc[safe] if num_cats else np.zeros(len(col.codes), dtype=np.int32)
    out = np.where(col.mask, out, TYPE_NULL).astype(np.int32)
    return out


def dict_string_lengths(col) -> np.ndarray:
    ld = col.aux.get("lengths")
    if ld is None:
        ones = np.ones(col.num_categories, dtype=bool)
        ld = string_lengths(col.dictionary_source, ones)
        col.aux["lengths"] = ld
    num_cats = col.num_categories
    safe = np.where(col.codes < num_cats, col.codes, 0)
    out = ld[safe] if num_cats else np.zeros(len(col.codes), dtype=np.int32)
    return np.where(col.mask, out, 0).astype(np.int32)


def dict_entry_hashes(col) -> np.ndarray:
    """xxhash64 of each DISTINCT dictionary value, cached per dataset —
    the one hash pass every dictionary consumer (per-row hashes, HLL
    register pairs) derives from."""
    hd = col.aux.get("hashes")
    if hd is None:
        ones = np.ones(col.num_categories, dtype=bool)
        hd = hash_column(col.dictionary_source, ones, col.kind)
        col.aux["hashes"] = hd
    return hd


def dict_hashes(col) -> np.ndarray:
    """Per-row xxhash64 via the cached distinct-value hashes + a gather.
    Masked rows carry arbitrary hashes — every consumer masks before use."""
    hd = dict_entry_hashes(col)
    num_cats = col.num_categories
    if not num_cats:
        return np.zeros(len(col.codes), dtype=np.uint64)
    safe = np.where(col.codes < num_cats, col.codes, 0)
    return hd[safe]


def _is_string_dict(col) -> bool:
    return (
        col.has_dictionary
        and col.codes is not None
        and col.kind == ColumnKind.STRING
    )


class FeatureBuilder:
    """Computes the union of requested features for each batch."""

    def __init__(self, specs: Iterable[FeatureSpec]):
        # dedupe by key, keep spec objects (payload needed for predicates)
        self.specs: Dict[str, FeatureSpec] = {}
        for s in specs:
            self.specs.setdefault(s.key, s)

    @property
    def required_columns(self) -> List[str]:
        # predicates may reference any column — the runner accounts for that
        # in `_columns_needed`, not here
        return sorted({s.column for s in self.specs.values() if s.column is not None})

    def build(self, batch: Batch) -> Dict[str, np.ndarray]:
        features: Dict[str, np.ndarray] = {}
        pred_columns: Dict[str, np.ndarray] | None = None
        for key, spec in self.specs.items():
            if spec.kind == "rows":
                features[key] = batch.row_mask
            elif spec.kind == "num":
                col = batch.column(spec.column)
                if np.issubdtype(col.values.dtype, np.number):
                    # zero-copy passthrough: masked-out positions may carry
                    # arbitrary bytes (Arrow leaves null slots undefined) —
                    # every device consumer masks before use, so no host
                    # copy is needed; genuine NaN/inf at valid positions
                    # propagate (Spark semantics)
                    features[key] = col.values
                else:
                    features[key] = col.numeric_f64()
            elif spec.kind == "mask":
                col = batch.column(spec.column)
                features[key] = col.mask
            elif spec.kind == "len":
                col = batch.column(spec.column)
                if _is_string_dict(col):
                    features[key] = dict_string_lengths(col)
                else:
                    features[key] = string_lengths(col.string_source, col.mask)
            elif spec.kind == "match":
                features[key] = column_regex_matches(
                    batch.column(spec.column), spec.payload
                )
            elif spec.kind == "type":
                col = batch.column(spec.column)
                if _is_string_dict(col):
                    features[key] = dict_type_codes(col)
                else:
                    features[key] = classify_type_codes(
                        col.string_source if col.kind == ColumnKind.STRING else col.values,
                        col.mask,
                        col.kind,
                    )
            elif spec.kind == "hash":
                col = batch.column(spec.column)
                if _is_string_dict(col):
                    # gather from the per-dataset cached DISTINCT-value
                    # hashes (masked rows carry arbitrary hashes — the
                    # frequency engine sentinel-keys them before use)
                    features[key] = dict_hashes(col)
                elif col.kind == ColumnKind.STRING:
                    features[key] = hash_column(
                        col.string_source, col.mask, col.kind
                    )
                else:
                    features[key] = hash_column(col.values, col.mask, col.kind)
            elif spec.kind == "hll":
                features[key] = _hll_packed(batch.column(spec.column))
            elif spec.kind == "codes":
                col = batch.column(spec.column)
                if col.codes is None:
                    raise ValueError(
                        f"column {spec.column} is not dictionary-encoded; the "
                        "codes feature is only valid on dictionary sources"
                    )
                features[key] = col.codes
            elif spec.kind == "pred":
                if pred_columns is None:
                    pred_columns = _predicate_columns(batch)
                mask = evaluate_predicate(spec.payload, pred_columns, len(batch.row_mask))
                features[key] = mask & batch.row_mask
            else:
                raise ValueError(f"unknown feature kind {spec.kind}")
        return features


def dry_run_batch(schema) -> Batch:
    """A synthetic all-null 1-row batch used to validate an analyzer's
    features (predicate syntax, column refs, regex compilation) before the
    real pass, so a bad analyzer yields a failure metric instead of killing
    the shared scan."""
    from ..data import Column

    columns = {}
    for cs in schema.columns:
        mask = np.zeros(1, dtype=bool)
        if cs.kind.is_numeric or cs.kind == ColumnKind.BOOLEAN:
            values = np.zeros(1, dtype=np.float64)
        else:
            values = np.array([None], dtype=object)
        columns[cs.name] = Column(cs.name, cs.kind, values, mask)
    return Batch(columns, np.zeros(1, dtype=bool), 0)


class _LazyPredicateColumns:
    """Mapping of column name -> predicate operand, materialized ON ACCESS
    and cached: a predicate battery only touches the columns it references,
    so untouched columns (e.g. high-cardinality strings during a
    constraint-evaluation pass) never pay object conversion."""

    def __init__(self, batch: Batch):
        self._batch = batch
        self._cache: Dict[str, Any] = {}

    def __contains__(self, name: str) -> bool:
        return name in self._batch.columns

    def keys(self):
        return self._batch.columns.keys()

    def items(self):
        return ((name, self[name]) for name in self.keys())

    def __getitem__(self, name: str):
        cached = self._cache.get(name)
        if cached is None:
            cached = self._cache[name] = _predicate_column(
                self._batch.column(name)
            )
        return cached


def _predicate_column(col):
    from ..expr import DictColumn

    if col.kind.is_numeric or col.kind == ColumnKind.BOOLEAN:
        return col.numeric_f64()
    if col.has_dictionary and col.codes is not None:
        # lazy dictionary operand: membership/comparisons/functions
        # evaluate on the DISTINCT entries and gather by code; the
        # entry table (with its None sentinel) caches per dataset
        num_cats = col.num_categories
        entries = col.aux.get("pred_entries")
        if entries is None or len(entries) != num_cats + 1:
            entries = np.empty(num_cats + 1, dtype=object)
            if num_cats:
                entries[:num_cats] = col.dictionary
            entries[num_cats] = None
            col.aux["pred_entries"] = entries
        codes = np.where(
            col.mask & (col.codes >= 0) & (col.codes < num_cats),
            col.codes,
            num_cats,
        ).astype(np.int32)
        return DictColumn(entries, codes)
    vals = col.values
    if vals.dtype != object:
        vals = vals.astype(object)
    vals = vals.copy()
    vals[~col.mask] = None
    return vals


def _predicate_columns(batch: Batch) -> "_LazyPredicateColumns":
    return _LazyPredicateColumns(batch)
