"""Partition-aware incremental verification: the delta planner.

The reference's killer production feature beyond raw scan speed is
algebraic state reuse: ``AnalysisRunner.runOnAggregatedStates`` +
StateLoader/StatePersister let a growing dataset be verified by folding
only new partitions (SURVEY L3/L4; PAPER.md "incremental computation").
This module is that feature composed from parts this engine already has —
checksummed persisted states, bit-exact merge-of-merges, the
aggregated-states runner — plus a planner that decides, per partition,
whether any data needs touching at all:

==============  ==========================================================
decision        when / what happens
==============  ==========================================================
``scan``        partition never seen: scan it, persist its states, commit
                its manifest
``invalidated`` stored but stale — content checksum mismatch (the data
                changed), schema-contract fingerprint mismatch (the
                schema changed), battery outgrew the stored coverage, or
                the stored payload is corrupt (quarantined typed) — the
                partition re-scans and overwrites
``reuse``       stored and current: its states LOAD, its data is never
                touched
``dropped``     stored but absent from the incoming set (retention
                deleted it): it simply does not join the merge — metrics
                stay consistent because suite metrics are always a
                re-merge of exactly the incoming partitions
==============  ==========================================================

Fresh-partition scans run through the ordinary resilient engine path
(``do_analysis_run`` — tier failover, isolation, watchdog all apply) and,
under the service plane, ride the fleet scheduler's sub-mesh sharding
(the job's leased ``ctx.mesh`` arrives here as ``sharding``). Stored +
fresh states then merge through the same ``merge_states_batched``
machinery ``run_on_aggregated_states`` uses, into suite-level metrics.

A 100M-row table that grew 1% verifies by scanning 1% of its rows; the
profiler and the suggestion runner ride the same stored states
(:func:`profile_partitioned` / :func:`suggest_partitioned`).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

_logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# fingerprints and content checksums
# ---------------------------------------------------------------------------


def contract_fingerprint(schema) -> str:
    """The schema-contract fingerprint a partition's states are keyed
    under: column names + kinds, canonical-JSON checksummed. Column ORDER
    is part of the schema identity here (the engine's feature layout
    follows it); dictionary-encoding is NOT (it is a per-batch transport
    detail the drift guard owns)."""
    from ..integrity import checksum_json

    return checksum_json(
        {"columns": [[c.name, c.kind.value] for c in schema.columns]}
    )


def analyzer_key(analyzer) -> str:
    """The stable identity a partition manifest records per analyzer —
    ``repr`` of a frozen analyzer dataclass is deterministic across
    processes (the FS state provider already keys blobs on it)."""
    return repr(analyzer)


def dataset_content_checksum(data) -> str:
    """Content checksum of a materialized partition payload: every
    column's arrow buffers hashed with the integrity-plane digest and
    combined canonically. Runs at memory bandwidth (no scan, no device),
    but it DOES touch the bytes — callers wanting the zero-touch contract
    pass their own version token (file etag, snapshot id, ingest offset)
    instead.

    Each chunk's OFFSET and LENGTH join the digest: a zero-copy slice's
    ``buffers()`` are the un-trimmed PARENT buffers, so two different
    slices of one table would otherwise hash identically and stale
    stored states could silently serve the wrong window. The offset
    makes the digest change whenever the logical window moves (the safe
    direction — at worst an equal-content re-slice re-scans once)."""
    from ..integrity import checksum_bytes, checksum_json

    per_column: Dict[str, List[str]] = {}
    table = data.arrow
    for name in table.column_names:
        digests: List[str] = []
        for chunk in table.column(name).chunks:
            digests.append(f"@{chunk.offset}+{len(chunk)}:{chunk.type}")
            for buf in chunk.buffers():
                digests.append(
                    "-" if buf is None else checksum_bytes(memoryview(buf))
                )
        per_column[name] = digests
    return checksum_json({"rows": int(data.num_rows), "columns": per_column})


# ---------------------------------------------------------------------------
# partition inputs
# ---------------------------------------------------------------------------


class PartitionInput:
    """One incoming partition: a name, a payload (anything
    ``ingest.as_dataset`` accepts, or a zero-arg callable producing one,
    or ``None`` when only the version token is known), and an optional
    ``checksum`` version token. With a callable + checksum, an unchanged
    partition is planned and reused without the payload ever being
    produced — the zero-data-touched contract."""

    __slots__ = ("name", "_payload", "checksum", "_data")

    def __init__(self, name: str, payload: Any = None, checksum: Optional[str] = None):
        self.name = str(name)
        self._payload = payload
        self.checksum = None if checksum is None else str(checksum)
        self._data = None

    @property
    def materialized(self) -> bool:
        return self._data is not None

    @property
    def eager(self) -> bool:
        """Whether the payload is directly at hand (not a deferred
        callable): reading its schema costs nothing the caller didn't
        already pay."""
        return self._data is not None or (
            self._payload is not None and not callable(self._payload)
        )

    def data(self):
        """Materialize the payload (memoized). Raises ``ValueError`` when
        the partition carries no payload at all (a reuse-only input asked
        to re-scan — e.g. after a corruption quarantine)."""
        if self._data is None:
            payload = self._payload
            if callable(payload):
                payload = payload()
            if payload is None:
                raise ValueError(
                    f"partition {self.name!r} must be re-scanned but "
                    "carries no payload (pass data or a loader callable)"
                )
            from ..ingest.columnar import as_dataset

            self._data = as_dataset(payload)
        return self._data

    def release(self) -> None:
        """Drop the memoized Dataset of a CALLABLE payload (re-derivable
        on demand): the scan loop calls this after each partition's
        commit so a full-invalidation run holds one partition's decoded
        payload at a time, not all of them. Eager payloads stay — the
        caller holds the reference either way."""
        if callable(self._payload):
            self._data = None

    def resolve_checksum(self) -> Optional[str]:
        """The version token: caller-supplied, else a content digest of
        the materialized payload, else None (unversioned — planned as
        always-scan)."""
        if self.checksum is not None:
            return self.checksum
        if self._payload is not None and not callable(self._payload):
            self.checksum = dataset_content_checksum(self.data())
        return self.checksum


def normalize_partitions(
    partitions, checksums: Optional[Mapping[str, str]] = None
) -> "List[PartitionInput]":
    """Accepts a mapping name -> payload (payload may be a Dataset/arrow/
    dict/callable/None or an explicit ``PartitionInput``), or a sequence
    of ``PartitionInput``. ``checksums`` supplies version tokens by
    name."""
    checksums = dict(checksums or {})
    out: List[PartitionInput] = []
    if isinstance(partitions, Mapping):
        items = partitions.items()
    else:
        items = [(p.name, p) for p in partitions]
    seen = set()
    for name, payload in items:
        if name in seen:
            raise ValueError(f"duplicate partition name {name!r}")
        seen.add(name)
        if isinstance(payload, PartitionInput):
            if payload.name != name:
                raise ValueError(
                    f"partition mapping key {name!r} does not match the "
                    f"PartitionInput's own name {payload.name!r}"
                )
            if checksums.get(name) is not None and payload.checksum is None:
                payload.checksum = str(checksums[name])
            out.append(payload)
        else:
            out.append(PartitionInput(name, payload, checksums.get(name)))
    return out


# ---------------------------------------------------------------------------
# the delta plan
# ---------------------------------------------------------------------------


@dataclass
class DeltaPlan:
    """What the planner decided for one incremental run."""

    dataset: str
    fingerprint: str
    scan: List[str] = field(default_factory=list)
    reuse: List[str] = field(default_factory=list)
    #: subset of ``scan`` that had stored states which went stale (content
    #: change, fingerprint mismatch, battery growth, corruption)
    invalidated: List[str] = field(default_factory=list)
    #: stored partitions absent from the incoming set — excluded from the
    #: merge (and deletable by retention)
    dropped: List[str] = field(default_factory=list)
    #: partition -> why it scans / was invalidated
    reasons: Dict[str, str] = field(default_factory=dict)
    #: reused partition -> its manifest row count (zero data touched)
    reuse_rows: Dict[str, int] = field(default_factory=dict)

    @property
    def rows_reused(self) -> int:
        return sum(self.reuse_rows.values())

    @property
    def fully_reused(self) -> bool:
        return not self.scan

    def as_dict(self) -> Dict[str, Any]:
        return {
            "dataset": self.dataset,
            "scan": list(self.scan),
            "reuse": list(self.reuse),
            "invalidated": list(self.invalidated),
            "dropped": list(self.dropped),
            "reasons": dict(self.reasons),
        }


def plan_delta(
    store,
    dataset: str,
    partitions: Sequence[PartitionInput],
    fingerprint: str,
    analyzer_keys: Sequence[str],
    monitor=None,
) -> DeltaPlan:
    """Diff the incoming partition set against the store. Every decision
    lands as a trace event (one ``incremental_plan`` span per run) and on
    the RunMonitor's partition counters."""
    from ..exceptions import CorruptStateError
    from ..observability import trace as _trace

    plan = DeltaPlan(dataset=str(dataset), fingerprint=fingerprint)
    incoming = {p.name for p in partitions}
    with _trace.span(
        "incremental_plan", kind="incremental", dataset=str(dataset),
        partitions=len(partitions),
    ) as sp:
        for p in partitions:
            reason = None
            manifest = None
            try:
                manifest = store.get(dataset, p.name)
            except CorruptStateError as exc:
                # the manifest itself is rot: quarantined by the store;
                # treat exactly like a changed partition — re-scan it
                reason = f"corrupt-manifest: {exc}"
            if manifest is None and reason is None:
                reason = "new"
            elif reason is None:
                if manifest.fingerprint != fingerprint:
                    reason = "stale-fingerprint"
                elif not manifest.covers(analyzer_keys):
                    reason = "battery-grew"
                else:
                    checksum = p.resolve_checksum()
                    if checksum is None:
                        reason = "unversioned"
                    elif manifest.content_checksum != checksum:
                        reason = "content-changed"
            if reason is None:
                plan.reuse.append(p.name)
                plan.reuse_rows[p.name] = manifest.num_rows
                sp.add_event("partition_reuse", partition=p.name,
                             rows=manifest.num_rows)
            else:
                plan.scan.append(p.name)
                plan.reasons[p.name] = reason
                # "unversioned" is not staleness — the partition simply
                # cannot be validated, so it re-scans every run without
                # counting as an invalidation
                if reason not in ("new", "unversioned") and (
                    manifest is not None or "corrupt" in reason
                ):
                    plan.invalidated.append(p.name)
                sp.add_event("partition_scan", partition=p.name,
                             reason=reason)
        for name in store.list_partitions(dataset):
            if name not in incoming:
                plan.dropped.append(name)
                sp.add_event("partition_dropped", partition=name)
        sp.add_event(
            "plan", scan=len(plan.scan), reuse=len(plan.reuse),
            invalidated=len(plan.invalidated), dropped=len(plan.dropped),
        )
    if monitor is not None:
        monitor.bump("partitions_scanned", len(plan.scan))
        monitor.bump("partitions_reused", len(plan.reuse))
        monitor.bump("partitions_invalidated", len(plan.invalidated))
        monitor.bump("partitions_dropped", len(plan.dropped))
    return plan


# ---------------------------------------------------------------------------
# the incremental runner
# ---------------------------------------------------------------------------


class IncrementalRunReport:
    """Plan + cost accounting of one incremental run, attached to its
    result (``result.incremental``)."""

    def __init__(self, plan: DeltaPlan, rows_scanned: int, rows_total: int):
        self.plan = plan
        self.rows_scanned = int(rows_scanned)
        self.rows_total = int(rows_total)

    @property
    def reuse_ratio(self) -> float:
        """Fraction of merged partitions served from stored states."""
        n = len(self.plan.scan) + len(self.plan.reuse)
        return (len(self.plan.reuse) / n) if n else 0.0

    @property
    def rows_touched_fraction(self) -> float:
        return (
            self.rows_scanned / self.rows_total if self.rows_total else 0.0
        )

    def as_dict(self) -> Dict[str, Any]:
        d = self.plan.as_dict()
        d.update(
            rows_scanned=self.rows_scanned,
            rows_total=self.rows_total,
            reuse_ratio=round(self.reuse_ratio, 4),
            rows_touched_fraction=round(self.rows_touched_fraction, 4),
        )
        return d


def _scan_partition(
    store,
    dataset: str,
    part: PartitionInput,
    analyzers,
    fingerprint: str,
    keys: Sequence[str],
    *,
    batch_size=None,
    monitor=None,
    sharding=None,
    placement=None,
) -> Tuple[Any, int]:
    """One fresh partition: invalidate-first, scan persisting per-analyzer
    states, commit the manifest. Returns (AnalyzerContext, rows)."""
    from ..observability import trace as _trace
    from .analysis_runner import AnalysisRunner

    data = part.data()
    with _trace.span(
        "partition_scan", kind="incremental", dataset=str(dataset),
        partition=part.name, rows=int(data.num_rows),
    ):
        store.invalidate(dataset, part.name)
        provider = store.provider(dataset, part.name)
        ctx = AnalysisRunner.do_analysis_run(
            data, analyzers,
            save_states_with=provider,
            batch_size=batch_size, monitor=monitor,
            sharding=sharding, placement=placement,
        )
        store.commit(
            dataset, part.name,
            fingerprint=fingerprint,
            content_checksum=part.resolve_checksum(),
            num_rows=int(data.num_rows),
            analyzer_keys=keys,
            schema=[(c.name, c.kind.value) for c in data.schema.columns],
        )
    return ctx, int(data.num_rows)


class _TeePersister:
    """Fan one persist out to several persisters (rollup cache + the
    caller's save_states_with); None members are skipped."""

    def __init__(self, *persisters):
        self._persisters = [p for p in persisters if p is not None]

    def persist(self, analyzer, state) -> None:
        for p in self._persisters:
            p.persist(analyzer, state)


def _manifest_safe(store, dataset: str, name: str):
    """``store.get`` that treats a corrupt manifest as absent — the
    planner handles corruption with its typed re-scan path; auxiliary
    reads (schema resolution, row accounting) must not crash first."""
    from ..exceptions import CorruptStateError

    try:
        return store.get(dataset, name)
    except CorruptStateError:
        return None


def _schema_from_manifests(store, dataset: str, names: Sequence[str]):
    """Reconstruct a Schema from stored manifests (the fully-reused path's
    zero-data-touched schema source)."""
    from ..data import ColumnKind, ColumnSchema, Schema

    for name in names:
        manifest = _manifest_safe(store, dataset, name)
        if manifest is not None and manifest.schema:
            return Schema(
                tuple(
                    ColumnSchema(n, ColumnKind(k))
                    for n, k in manifest.schema
                )
            )
    return None


def _resolve_schema(store, dataset: str, parts: Sequence[PartitionInput]):
    """See run_incremental: eager payload > stored manifest > forced
    materialization of the first payload."""
    for p in parts:
        if p.eager:
            return p.data().schema
    schema = _schema_from_manifests(store, dataset, [p.name for p in parts])
    if schema is None:
        schema = parts[0].data().schema
    return schema


def run_incremental(
    store,
    dataset: str,
    partitions,
    analyzers: Sequence[Any],
    *,
    checksums: Optional[Mapping[str, str]] = None,
    batch_size=None,
    monitor=None,
    sharding=None,
    placement=None,
    save_states_with=None,
    metrics_repository=None,
    save_or_append_results_with_key=None,
    delete_dropped: bool = False,
):
    """The analysis half of an incremental run: plan the delta, scan only
    the fresh/changed partitions, merge stored + fresh states into ONE
    AnalyzerContext. Returns ``(AnalyzerContext, IncrementalRunReport)``.

    Failure semantics: a stored partition whose state blob is corrupt
    (torn .npz, checksum trip) QUARANTINES and falls back to re-scanning
    that partition only — the run degrades by one partition scan, never
    crashes, unless the partition's payload is unavailable (then the
    typed :class:`CorruptStateError` surfaces to the caller, who holds
    the only copy of the remedy)."""
    from ..exceptions import CorruptStateError
    from ..observability import record_failure
    from .analysis_runner import AnalysisRunner, collect_required_analyzers
    from .engine import RunMonitor

    monitor = monitor if monitor is not None else RunMonitor()
    parts = normalize_partitions(partitions, checksums)
    if not parts:
        from .context import AnalyzerContext

        empty_plan = DeltaPlan(dataset=str(dataset), fingerprint="")
        return AnalyzerContext.empty(), IncrementalRunReport(empty_plan, 0, 0)
    # dedupe the battery exactly like the runner will
    unique = list(dict.fromkeys(analyzers))
    keys = [analyzer_key(a) for a in unique]

    # the schema (and therefore the fingerprint) comes from the cheapest
    # INCOMING source: an eagerly-passed payload first — the incoming
    # schema is what fingerprint staleness is judged against, so a stored
    # manifest may only supply it when every payload is deferred (the
    # zero-touch reuse path, where an unchanged version token implies an
    # unchanged schema) — else the first payload materializes
    schema = _resolve_schema(store, dataset, parts)
    fingerprint = contract_fingerprint(schema)

    plan = plan_delta(store, dataset, parts, fingerprint, keys, monitor)
    by_name = {p.name: p for p in parts}

    rows_scanned = 0
    scan_queue = list(plan.scan)
    scanned = set()
    while scan_queue:
        name = scan_queue.pop(0)
        if name in scanned:
            continue
        scanned.add(name)
        part = by_name[name]
        _, rows = _scan_partition(
            store, dataset, part, unique, fingerprint, keys,
            batch_size=batch_size, monitor=monitor, sharding=sharding,
            placement=placement,
        )
        part.release()  # one decoded partition in memory at a time
        rows_scanned += rows

    # merge: stored (reused) + freshly-persisted states, all through the
    # store's checksummed loaders — the aggregated-states path. A corrupt
    # blob here (torn after commit) quarantines the partition and re-scans
    # it, exactly once per partition.
    def merged_context():
        # merge in the INCOMING partition order, independent of the
        # scan/reuse split: float merges associate by order, so a
        # corrupt-rescue re-scan must not reshuffle the fold (parity
        # against the aligned full scan is bit-exact only because this
        # order equals the data order)
        include = set(plan.reuse) | scanned
        names = [p.name for p in parts if p.name in include]
        # rollup prefix: when the stored rollup folds an exact PREFIX of
        # this run's partition sequence (same order, same content
        # checksums, all still reused, same fingerprint, battery
        # covered), the merge starts from it and folds only the suffix —
        # O(suffix) state loads instead of O(N). A left fold makes this
        # bitwise identical to folding every partition.
        prefix_len = 0
        rollup = store.rollup_get(dataset)
        if (
            rollup is not None
            and rollup.fingerprint == fingerprint
            and rollup.covers(keys)
            and len(rollup.folded) <= len(names)
        ):
            # prefix entries match on (name, content token) — NOT on the
            # scan/reuse split: a partition re-scanned with an UNCHANGED
            # token (a corrupt-blob rescue, a manifest loss) contributed
            # the same bits the rollup already folded, so the rollup
            # still serves it
            if all(
                names[i] == n
                and c is not None
                and by_name[n].checksum == c
                for i, (n, c) in enumerate(rollup.folded)
            ):
                prefix_len = len(rollup.folded)
        suffix = names[prefix_len:]
        merge_state["prefix"] = prefix_len
        loaders = (
            [store.rollup_provider(dataset)] if prefix_len else []
        ) + [store.loader(dataset, n) for n in suffix]
        write_rollup = suffix or not prefix_len
        rollup_persister = None
        if write_rollup:
            # invalidate-FIRST: the manifest must never describe blobs a
            # crash left half-overwritten
            store.rollup_invalidate(dataset)
            rollup_persister = store.rollup_provider(dataset)
        context = AnalysisRunner.run_on_aggregated_states(
            schema, unique, loaders,
            save_states_with=_TeePersister(
                rollup_persister, save_states_with
            ),
            metrics_repository=metrics_repository,
            save_or_append_results_with_key=save_or_append_results_with_key,
        )
        if write_rollup:
            store.rollup_commit(
                dataset,
                fingerprint=fingerprint,
                analyzer_keys=keys,
                folded=[(n, by_name[n].checksum) for n in names],
                num_rows=rows_scanned + plan.rows_reused,
            )
        return context

    merge_state = {"prefix": 0}
    retried = set()
    while True:
        try:
            context = merged_context()
            break
        except CorruptStateError as exc:
            record_failure(exc)
            if merge_state["prefix"]:
                # the corruption may live in the ROLLUP cache's own
                # blobs: drop the cache and re-merge from the
                # per-partition states (the source of truth) before
                # blaming a partition
                _logger.warning(
                    "merge with the rollup prefix tripped a corruption "
                    "(%s); invalidating the rollup cache and re-merging "
                    "from partition states", exc,
                )
                store.rollup_invalidate(dataset)
                merge_state["prefix"] = 0
                continue
            victim = _partition_of_corruption(
                store, dataset, list(plan.reuse) + sorted(scanned), unique
            )
            if victim is None or victim in retried:
                raise
            retried.add(victim)
            monitor.bump("partitions_invalidated")
            if getattr(store, "monitor", None) is not monitor:
                # the store counts on its own monitor when it has one;
                # this run's ledger records the quarantine either way
                monitor.bump("corrupt_quarantined")
            store.quarantine_states(dataset, victim, str(exc))
            if victim in plan.reuse:
                plan.reuse.remove(victim)
                plan.reuse_rows.pop(victim, None)
            plan.invalidated.append(victim)
            plan.scan.append(victim)
            plan.reasons[victim] = "corrupt-state"
            _logger.warning(
                "stored states of partition %s/%s are corrupt; "
                "quarantined and re-scanning that partition only",
                dataset, victim,
            )
            scanned.add(victim)
            _, rows = _scan_partition(
                store, dataset, by_name[victim], unique, fingerprint, keys,
                batch_size=batch_size, monitor=monitor, sharding=sharding,
                placement=placement,
            )
            rows_scanned += rows

    # counted AFTER the merge commits: a corruption-aborted attempt that
    # re-merged without the rollup must not report rollup-served
    # partitions it did not serve
    monitor.bump("partitions_rolled_up", merge_state["prefix"])

    if delete_dropped:
        for name in plan.dropped:
            store.delete(dataset, name)

    report = IncrementalRunReport(
        plan, rows_scanned, rows_scanned + plan.rows_reused
    )
    return context, report


def _partition_of_corruption(store, dataset, names, analyzers):
    """Which partition's stored states trip the typed corruption error —
    probed by loading each partition's states in isolation (cheap: state
    blobs, not data)."""
    from ..exceptions import CorruptStateError

    for name in names:
        loader = store.loader(dataset, name)
        for a in analyzers:
            try:
                loader.load(a)
            except CorruptStateError:
                return name
            except Exception:  # noqa: BLE001 - only corruption routes here
                continue
    return None


# ---------------------------------------------------------------------------
# profiler / suggestion runner on stored states
# ---------------------------------------------------------------------------


def _profile_battery(schema, kll_parameters=None, predefined_types=None,
                     histogram_columns: Sequence[str] = ()):
    """The schema-derivable profiler battery (the profiler's pass-1 set):
    Size + per-column Completeness/ApproxCountDistinct, DataType for
    string columns, the numeric analyzers for schema-typed numerics, and
    Histograms for the given low-cardinality columns. Numeric-LOOKING
    string columns (whose stats the serial profiler computes over an
    inference-casted view) are profiled for type/completeness/
    distinctness here but not numeric stats — documented in README
    "Incremental verification"."""
    from ..analyzers import (
        ApproxCountDistinct,
        Completeness,
        DataType,
        Histogram,
        Size,
    )
    from ..data import ColumnKind
    from ..profiles import FRACTIONAL, INTEGRAL, _numeric_analyzers

    predefined_types = dict(predefined_types or {})
    battery: List[Any] = [Size()]
    for c in schema.columns:
        battery.append(Completeness(c.name))
        battery.append(ApproxCountDistinct(c.name))
        if c.kind == ColumnKind.STRING and c.name not in predefined_types:
            battery.append(DataType(c.name))
        elif c.kind.is_numeric and predefined_types.get(
            c.name, INTEGRAL
        ) in (INTEGRAL, FRACTIONAL):
            battery += _numeric_analyzers(c.name, kll_parameters)
    battery += [Histogram(name) for name in sorted(histogram_columns)]
    return battery


def profile_partitioned(
    store,
    dataset: str,
    partitions,
    *,
    checksums: Optional[Mapping[str, str]] = None,
    restrict_to_columns: Optional[Sequence[str]] = None,
    low_cardinality_histogram_threshold: Optional[int] = None,
    kll_parameters=None,
    predefined_types: Optional[Mapping[str, str]] = None,
    batch_size=None,
    monitor=None,
    sharding=None,
    placement=None,
):
    """Column profiles over a partitioned dataset, riding the SAME stored
    states the verification plane persists: unchanged partitions
    contribute their stored profiler states with zero data touched; only
    new/changed partitions scan. Returns ``(ColumnProfiles,
    IncrementalRunReport)``.

    The battery is the schema-derivable profiler set (see
    `_profile_battery`); numeric-string inference casting — the serial
    profiler's pass 2 — is out of scope for state reuse and documented
    as such."""
    from ..profiles import (
        DEFAULT_CARDINALITY_THRESHOLD,
        _create_profiles,
        _extract_generic_statistics,
        _extract_numeric_statistics,
        _find_target_columns_for_histograms,
    )
    from ..analyzers.grouping import Histogram

    threshold = (
        DEFAULT_CARDINALITY_THRESHOLD
        if low_cardinality_histogram_threshold is None
        else int(low_cardinality_histogram_threshold)
    )
    parts = normalize_partitions(partitions, checksums)
    schema = _resolve_schema(store, dataset, parts)
    relevant = [
        c.name for c in schema.columns
        if restrict_to_columns is None or c.name in restrict_to_columns
    ]
    if restrict_to_columns is not None:
        for name in restrict_to_columns:
            if name not in schema:
                raise ValueError(f"Unable to find column {name}")

    # low-cardinality histogram columns must be decidable without a scan:
    # dictionary-encoded columns qualify by dictionary size when a payload
    # is at hand, else by the Histogram states already stored
    hist_cols: List[str] = []
    sample = next((p for p in parts if p.eager), None)
    if sample is not None:
        hist_cols = [
            name for name in relevant
            if (size := sample.data().dictionary_size(name)) is not None
            and size <= threshold
        ]
    else:
        known = store.list_partitions(dataset)
        if known:
            manifest = _manifest_safe(store, dataset, known[0])
            if manifest is not None:
                hist_cols = [
                    name for name in relevant
                    if analyzer_key(Histogram(name)) in manifest.analyzer_keys
                ]

    battery = _profile_battery(
        schema, kll_parameters=kll_parameters,
        predefined_types=predefined_types, histogram_columns=hist_cols,
    )
    if restrict_to_columns is not None:
        battery = [
            a for a in battery
            if getattr(a, "column", None) in (None, *relevant)
            and all(c in relevant for c in getattr(a, "columns", ()))
        ]
    context, report = run_incremental(
        store, dataset, parts, battery,
        batch_size=batch_size, monitor=monitor, sharding=sharding,
        placement=placement,
    )
    generic = _extract_generic_statistics(
        relevant, schema, context, dict(predefined_types or {})
    )
    numeric_stats = _extract_numeric_statistics(context)
    histograms: Dict[str, Any] = {}
    eligible = set(
        _find_target_columns_for_histograms(schema, generic, threshold)
    ) | set(hist_cols)
    for analyzer, metric in context.metric_map.items():
        if (
            isinstance(analyzer, Histogram)
            and metric.value.is_success
            and analyzer.column in eligible
        ):
            histograms[analyzer.column] = metric.value.get()
    profiles = _create_profiles(relevant, generic, numeric_stats, histograms)
    return profiles, report


def suggest_partitioned(
    store,
    dataset: str,
    partitions,
    constraint_rules,
    *,
    checksums: Optional[Mapping[str, str]] = None,
    restrict_to_columns: Optional[Sequence[str]] = None,
    low_cardinality_histogram_threshold: Optional[int] = None,
    kll_parameters=None,
    predefined_types: Optional[Mapping[str, str]] = None,
    batch_size=None,
    monitor=None,
):
    """Constraint suggestions over a partitioned dataset riding the same
    stored states (profile incrementally, then apply the rules). Returns
    ``(ConstraintSuggestionResult, IncrementalRunReport)``."""
    from ..suggestions import apply_rules

    profiles, report = profile_partitioned(
        store, dataset, partitions,
        checksums=checksums,
        restrict_to_columns=restrict_to_columns,
        low_cardinality_histogram_threshold=low_cardinality_histogram_threshold,
        kll_parameters=kll_parameters,
        predefined_types=predefined_types,
        batch_size=batch_size,
        monitor=monitor,
    )
    return apply_rules(profiles, constraint_rules), report
