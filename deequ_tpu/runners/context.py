"""AnalyzerContext: the result of an analysis run
(reference `analyzers/runners/AnalyzerContext.scala:29-105`)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from ..analyzers.base import Analyzer
from ..metrics import DoubleMetric, Metric


@dataclass(frozen=True)
class AnalyzerContext:
    metric_map: Dict[Analyzer, Metric] = field(default_factory=dict)

    @staticmethod
    def empty() -> "AnalyzerContext":
        return AnalyzerContext({})

    def all_metrics(self) -> List[Metric]:
        return list(self.metric_map.values())

    def __add__(self, other: "AnalyzerContext") -> "AnalyzerContext":
        merged = dict(self.metric_map)
        merged.update(other.metric_map)
        return AnalyzerContext(merged)

    def metric(self, analyzer: Analyzer) -> Optional[Metric]:
        return self.metric_map.get(analyzer)

    def success_metrics(
        self, for_analyzers: Optional[Sequence[Analyzer]] = None
    ) -> Dict[Analyzer, Metric]:
        return {
            a: m
            for a, m in self.metric_map.items()
            if (not for_analyzers or a in for_analyzers) and m.value.is_success
        }

    def success_metrics_as_records(
        self, for_analyzers: Optional[Sequence[Analyzer]] = None
    ) -> List[dict]:
        """Flattened (entity, instance, name, value) records
        (reference `AnalyzerContext.successMetricsAsDataFrame`,
        `AnalyzerContext.scala:48-77`)."""
        records = []
        for metric in self.success_metrics(for_analyzers).values():
            for flat in metric.flatten():
                if flat.value.is_success:
                    records.append(
                        {
                            "entity": flat.entity.value,
                            "instance": flat.instance,
                            "name": flat.name,
                            "value": flat.value.get(),
                        }
                    )
        return records

    def success_metrics_as_dataframe(self, for_analyzers=None):
        import pandas as pd

        records = self.success_metrics_as_records(for_analyzers)
        return pd.DataFrame(records, columns=["entity", "instance", "name", "value"])

    def success_metrics_as_json(self, for_analyzers=None) -> str:
        return json.dumps(self.success_metrics_as_records(for_analyzers))
