from .analysis_runner import AnalysisRunner
from .builder import Analysis, AnalysisRunBuilder
from .context import AnalyzerContext
from .engine import RunMonitor, ScanEngine
from .incremental import (
    DeltaPlan,
    IncrementalRunReport,
    PartitionInput,
    contract_fingerprint,
    profile_partitioned,
    run_incremental,
    suggest_partitioned,
)
from .exceptions import (
    EmptyStateException,
    MetricCalculationException,
    MetricCalculationPreconditionException,
    MetricCalculationRuntimeException,
    NoSuchColumnException,
    WrongColumnTypeException,
    wrap_if_necessary,
)

__all__ = [
    "Analysis",
    "AnalysisRunBuilder",
    "AnalysisRunner",
    "AnalyzerContext",
    "DeltaPlan",
    "EmptyStateException",
    "IncrementalRunReport",
    "PartitionInput",
    "contract_fingerprint",
    "profile_partitioned",
    "run_incremental",
    "suggest_partitioned",
    "MetricCalculationException",
    "MetricCalculationPreconditionException",
    "MetricCalculationRuntimeException",
    "NoSuchColumnException",
    "RunMonitor",
    "ScanEngine",
    "WrongColumnTypeException",
    "wrap_if_necessary",
]
