"""AnalysisRunner: the scheduler.

Reference flow (`analyzers/runners/AnalysisRunner.scala:97-203`):
dedupe vs repository cache -> precondition partition -> split
{scanning, grouping, KLL} -> fused scan + per-grouping-set frequency jobs ->
assemble AnalyzerContext -> optional repository save.

TPU-native differences: KLL updates are batched fixed-shape device ops, so
they join the SAME fused pass as every other scan analyzer (the reference
needs a dedicated RDD pass, `KLLRunner.scala:87-122`); grouping frequency
tables accumulate on host during that same pass — a full run touches the
data exactly once.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analyzers.base import Analyzer, Preconditions, ScanShareableAnalyzer
from ..analyzers.grouping import (
    FrequenciesAndNumRows,
    GroupingAnalyzer,
    Histogram,
)
from ..analyzers.state_provider import StateLoader, StatePersister
from ..data import Dataset
from ..metrics import Metric
from .context import AnalyzerContext
from .engine import RunMonitor, ScanEngine
from .exceptions import MetricCalculationException


def collect_required_analyzers(checks, required_analyzers=()) -> List[Analyzer]:
    """Every analyzer a verification run needs: the explicitly required
    ones plus each check's, in first-encounter order. Shared by the suite,
    the aggregated-states path and the service plane (which also derives
    the placement-cache signature from it), so the three can never disagree
    about what a set of checks computes."""
    analyzers: List[Analyzer] = list(required_analyzers)
    for check in checks:
        analyzers.extend(check.required_analyzers())
    return analyzers


class AnalysisRunner:
    """Static entry points (reference `AnalysisRunner.onData/run`)."""

    @staticmethod
    def on_data(data: Dataset) -> "AnalysisRunBuilder":
        from .builder import AnalysisRunBuilder

        return AnalysisRunBuilder(data)

    # ------------------------------------------------------------------

    @staticmethod
    def do_analysis_run(data: Dataset, analyzers: Sequence[Analyzer], **kwargs) -> AnalyzerContext:
        """Tracing shell around :meth:`_do_analysis_run`: every pass this
        run triggers — the primary fused scan, bisection re-passes, tier
        failovers — nests under ONE ``analysis_run`` span, so a degraded
        run reads as a connected tree (see ``deequ_tpu.observability``)."""
        if len(analyzers) == 0:
            return AnalyzerContext.empty()
        from ..observability import trace as _trace

        with _trace.span(
            "analysis_run", kind="analysis", analyzers=len(analyzers)
        ):
            return AnalysisRunner._do_analysis_run(data, analyzers, **kwargs)

    @staticmethod
    def _do_analysis_run(
        data: Dataset,
        analyzers: Sequence[Analyzer],
        *,
        aggregate_with: Optional[StateLoader] = None,
        save_states_with: Optional[StatePersister] = None,
        metrics_repository: Optional[Any] = None,
        reuse_existing_results_for_key: Optional[Any] = None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key: Optional[Any] = None,
        batch_size: Optional[int] = None,
        monitor: Optional[RunMonitor] = None,
        sharding: Optional[Any] = None,
        placement: Optional[str] = None,
        checkpointer: Optional[Any] = None,
    ) -> AnalyzerContext:
        if len(analyzers) == 0:
            return AnalyzerContext.empty()

        # dedupe identical analyzers, preserving order
        seen = set()
        unique: List[Analyzer] = []
        for a in analyzers:
            if a not in seen:
                seen.add(a)
                unique.append(a)

        # reuse existing results from the repository
        # (reference `AnalysisRunner.scala:115-134`)
        results_loaded = AnalyzerContext.empty()
        analyzers_to_run = unique
        if metrics_repository is not None and reuse_existing_results_for_key is not None:
            existing = metrics_repository.load_by_key(reuse_existing_results_for_key)
            if existing is not None:
                loaded = {
                    a: m for a, m in existing.metric_map.items() if a in seen
                }
                results_loaded = AnalyzerContext(loaded)
                analyzers_to_run = [a for a in unique if a not in loaded]
            if fail_if_results_missing and analyzers_to_run:
                raise MetricCalculationException(
                    "Could not find all necessary results in the MetricsRepository, "
                    f"the calculation of the metrics for these analyzers would be needed: "
                    f"{', '.join(str(a) for a in analyzers_to_run)}"
                )

        # precondition partition (reference `AnalysisRunner.scala:137-145`)
        schema = data.schema
        passed: List[Analyzer] = []
        failures: Dict[Analyzer, Metric] = {}
        for a in analyzers_to_run:
            exc = Preconditions.find_first_failing(schema, a.preconditions())
            if exc is None:
                passed.append(a)
            else:
                failures[a] = a.to_failure_metric(exc)

        # validate each analyzer's features on a synthetic 1-row batch so a
        # bad predicate/regex fails only that analyzer, not the shared scan
        from .features import FeatureBuilder, dry_run_batch

        dry = dry_run_batch(schema)
        validated = []
        for a in passed:
            if isinstance(a, ScanShareableAnalyzer):
                try:
                    FeatureBuilder(a.feature_specs()).build(dry)
                except Exception as exc:  # noqa: BLE001
                    failures[a] = a.to_failure_metric(exc)
                    continue
            validated.append(a)
        passed = validated
        precondition_failures = AnalyzerContext(failures)

        # split: device-fused scan / grouping sets / host accumulators
        from ..analyzers.grouping import (
            DeviceFrequencyScan,
            DeviceFrequencyTableScan,
            ScanShareableFrequencyBasedAnalyzer,
            device_freq_enabled,
            device_freq_max_cardinality,
            plan_table_scan,
            probably_low_cardinality,
        )

        dict_card_limit = device_freq_max_cardinality()

        # host-exclusive analyzers (e.g. exact-quantile mode, whose
        # accumulator is unbounded and has no fixed-shape device fold) opt
        # out of the fused scan even though their class is scan-shareable.
        # Their raw-value states are deliberately NOT in the persistence
        # registry, so a configured checkpointer would blow up on its first
        # save; drop it with a warning instead (the same degradation the
        # mesh path applies), keeping the run correct end to end.
        if checkpointer is not None and any(
            getattr(a, "host_exclusive", False) for a in passed
        ):
            import logging

            logging.getLogger(__name__).warning(
                "ingest checkpointing is not supported with host-exclusive "
                "analyzers (e.g. exact-quantile mode, whose raw-value "
                "states are not persistable); running without checkpoints"
            )
            checkpointer = None
        scanning = [
            a
            for a in passed
            if isinstance(a, ScanShareableAnalyzer)
            and not getattr(a, "host_exclusive", False)
        ]
        scanning_set = set(scanning)
        grouping = [a for a in passed if isinstance(a, GroupingAnalyzer)]
        # binning-free Histograms over small-dictionary columns share the
        # device frequency scan instead of accumulating a host group-by per
        # batch (their metric is derived from the same counts; keys are
        # Spark-string-cast at finalize). The reference always runs its own
        # groupBy per Histogram (`analyzers/Histogram.scala:54-96`).
        device_hist = [
            a
            for a in passed
            if isinstance(a, Histogram)
            and a.binning_func is None
            and (size := data.dictionary_size(a.column)) is not None
            and size <= dict_card_limit
        ]
        device_hist_set = set(device_hist)
        host_accum = [
            a
            for a in passed
            if hasattr(a, "host_init")
            and not isinstance(a, GroupingAnalyzer)
            and a not in device_hist_set
            and a not in scanning_set
        ]
        others = [
            a
            for a in passed
            if a not in scanning_set
            and a not in grouping
            and a not in host_accum
            and a not in device_hist_set
        ]

        grouping_sets: Dict[Tuple[str, ...], List[GroupingAnalyzer]] = {}
        for g in grouping:
            grouping_sets.setdefault(tuple(g.grouping_columns()), []).append(g)

        # single-column grouping sets over dictionary-encoded columns whose
        # dictionary is small ride the fused DEVICE scan as a segment_sum
        # (SURVEY §7 step 6's low-cardinality hybrid)
        device_freq: Dict[Tuple[str, ...], DeviceFrequencyScan] = {}
        device_dicts: Dict[Tuple[str, ...], Any] = {}
        for cols in list(grouping_sets) + [(a.column,) for a in device_hist]:
            if cols in device_freq:
                continue
            if len(cols) == 1:
                dictionary = data.dictionary_values(cols[0])
                if dictionary is not None and len(dictionary) <= dict_card_limit:
                    device_freq[cols] = DeviceFrequencyScan(cols[0], len(dictionary))
                    device_dicts[cols] = dictionary
        # a histogram column whose dictionary out-sizes the device path
        # falls back to the host accumulator
        for a in device_hist:
            if (a.column,) not in device_freq:
                device_hist_set.discard(a)
                host_accum.append(a)
        device_hist = [a for a in device_hist if a in device_hist_set]

        # every OTHER grouping set rides the device frequency TABLE engine
        # (hashed fixed-shape count tables folded in the fused pass,
        # ROADMAP item 3) when it safely can:
        #  - every member reduces the COUNT MULTISET alone (Histogram /
        #    MutualInformation read keys and stay on the dict/host paths);
        #  - nothing downstream needs value-keyed states — no persistence,
        #    aggregation or checkpointing (hashed tables and value-keyed
        #    host states must never merge);
        #  - x64 is on (uint64 keys) and the pass will run the DEVICE tier
        #    (on a feed-starved link streaming 8B/row of raw keys loses to
        #    the in-place host group-by).
        # Overflowing tables fall back per set after the pass; the host
        # accumulator (and its _SpillStore) is the last-resort tier.
        import jax as _jax

        from .engine import effective_batch_size as _ebs

        slim = (
            aggregate_with is None
            and save_states_with is None
            and checkpointer is None
        )
        table_freq: Dict[Tuple[str, ...], DeviceFrequencyTableScan] = {}
        if (
            slim
            and grouping_sets
            and device_freq_enabled()
            and _jax.config.jax_enable_x64
            and _device_tier_expected(scanning, placement)
        ):
            batch_rows = _ebs(data, batch_size)
            if sharding is not None:
                from ..parallel import mesh_batch_quantum

                q = mesh_batch_quantum(int(sharding.devices.size))
                batch_rows = ((batch_rows + q - 1) // q) * q
            for cols, members in grouping_sets.items():
                if cols in device_freq:
                    continue
                if not all(
                    isinstance(a, ScanShareableFrequencyBasedAnalyzer)
                    for a in members
                ):
                    continue
                if probably_low_cardinality(data, cols):
                    # below the sweep knee the host value_counts fast
                    # path beats the device table ~3x — keep the
                    # pre-engine routing for confidently-small sets
                    continue
                scan = plan_table_scan(
                    schema, cols, int(data.num_rows), batch_rows,
                    sharded=sharding is not None,
                )
                if scan is not None:
                    table_freq[cols] = scan

        # one shared pass over the data — executed through the reliability
        # layer: a device-infrastructure failure fails the battery over to
        # the host tier (OOMs first bisect the batch size), and an
        # analyzer-level fault bisects the battery until exactly the faulty
        # analyzers degrade to typed Failure metrics while the rest
        # complete (the fused-engine restoration of the reference's
        # per-expression degradation, `AnalysisRunner.scala:320-323`)
        scan_battery = (
            scanning + list(device_freq.values()) + list(table_freq.values())
        )
        run_monitor = monitor or RunMonitor()
        if table_freq:
            run_monitor.bump("device_freq_sets", len(table_freq))

        def make_host_states():
            hs: Dict[Any, Any] = {}
            hu: Dict[Any, Any] = {}
            for cols in grouping_sets:
                if cols in device_freq or cols in table_freq:
                    continue
                key = ("__grouping__", cols)
                hs[key] = FrequenciesAndNumRows.empty(list(cols))
                hu[key] = lambda st, batch: st.update(batch)
            for a in host_accum:
                hs[a] = a.host_init()
                hu[a] = a.host_update
            return hs, hu

        host_keys = list(make_host_states()[0])
        need_pass = bool(scan_battery) or bool(host_keys)
        metrics: Dict[Analyzer, Metric] = {}
        if need_pass:
            from ..reliability import run_scan_resilient
            from .engine import effective_batch_size

            full_battery = tuple(scan_battery)
            # slim fetch (the hoisted ``slim``): when nothing downstream
            # needs the full states (no persistence, no cross-run
            # aggregation, no checkpoint), each analyzer ships only its
            # metric-bearing leaves back over the feed link
            # (engine._fetch_states_packed's analyzers arg)

            outer_sharding = sharding
            _KEEP_SHARDING = object()

            def run_pass(
                part, hs, hu, *, placement=None, batch_size=None,
                sharding=_KEEP_SHARDING,
            ):
                # the reliability ladder overrides ``sharding`` only after
                # a shard loss escaped the engine: the pass then re-runs
                # whole on a mesh rebuilt over the surviving devices
                pass_sharding = (
                    outer_sharding if sharding is _KEEP_SHARDING else sharding
                )
                engine = ScanEngine(
                    list(part), monitor=run_monitor, sharding=pass_sharding,
                    placement=placement,
                )
                g_sets = [
                    key[1] for key in hs
                    if isinstance(key, tuple) and key and key[0] == "__grouping__"
                ]
                h_accum = [key for key in hs if not isinstance(key, tuple)]
                cols = _columns_needed(engine, g_sets, h_accum, schema)
                # checkpoints belong to the primary full-battery fold only:
                # bisection re-passes must not clobber its resume point
                ckpt = checkpointer if tuple(part) == full_battery else None
                return engine.run(
                    data, batch_size=batch_size, host_accumulators=hs,
                    host_update_fns=hu, columns=cols, checkpointer=ckpt,
                    slim_fetch=slim,
                )

            outcome = run_scan_resilient(
                run_pass, full_battery, make_host_states, run_monitor,
                batch_size=effective_batch_size(data, batch_size),
                placement=placement, sharding=sharding,
            )

            # drain the device frequency tables. A set whose table
            # overflowed (compactions dropped groups — drain returns None)
            # or whose scan degraded re-runs through the host accumulator
            # in ONE dedicated last-resort pass; _SpillStore sits below
            # that, exactly the old default path, now reached only when
            # the device tiers are exhausted.
            table_shared: Dict[Tuple[str, ...], Any] = {}
            fallback_states: Dict[Any, Any] = {}
            fallback_errors: Dict[Any, BaseException] = {}
            fallback_sets: List[Tuple[str, ...]] = []
            fallback_losses: List[str] = []
            if table_freq:
                for cols, scan in table_freq.items():
                    state = outcome.states.get(scan)
                    drained = None if state is None else scan.drain(state)
                    if drained is None:
                        fallback_sets.append(cols)
                        if state is not None:
                            run_monitor.bump("freq_overflow_fallbacks")
                            fallback_losses.append(
                                f"{cols}: ~{int(state.lost_groups)} groups / "
                                f"{int(state.lost_rows)} rows dropped"
                            )
                        else:
                            fallback_losses.append(f"{cols}: pass degraded")
                    else:
                        table_shared[cols] = drained
            if fallback_sets:
                import logging

                logging.getLogger(__name__).warning(
                    "device frequency table overflowed (or degraded) for "
                    "grouping sets [%s]; re-running them through the host "
                    "accumulator tier", "; ".join(fallback_losses),
                )

                def make_fallback_states():
                    hs: Dict[Any, Any] = {}
                    hu: Dict[Any, Any] = {}
                    for cols in fallback_sets:
                        key = ("__grouping__", cols)
                        hs[key] = FrequenciesAndNumRows.empty(list(cols))
                        hu[key] = lambda st, batch: st.update(batch)
                    return hs, hu

                fb = run_scan_resilient(
                    run_pass, (), make_fallback_states, run_monitor,
                    batch_size=effective_batch_size(data, batch_size),
                    placement=placement, sharding=sharding,
                )
                fallback_states = fb.host_states
                fallback_errors = fb.host_errors

            # scanning analyzers: load old state -> merge -> persist -> metric
            # (reference `Analyzer.calculateMetric`, `Analyzer.scala:107-128`)
            # — a monitored phase, so state-merge/persist/metric cost is
            # attributable (and span-backed) like every engine phase
            with run_monitor.timed("metric_derivation"):
                for a in scanning:
                    if a in outcome.states:
                        metrics[a] = _finalize(
                            a, outcome.states[a], aggregate_with, save_states_with
                        )
                    else:
                        metrics[a] = a.to_failure_metric(outcome.errors[a])
                device_freq_states = {
                    cols: outcome.states.get(scan)
                    for cols, scan in device_freq.items()
                }

                def shared_frequencies(cols):
                    """The grouping state for ``cols``, or the typed error
                    that took its producer down (device scan, device
                    frequency table, or host accumulator)."""
                    if cols in device_freq:
                        scan = device_freq[cols]
                        if device_freq_states[cols] is None:
                            return None, outcome.errors[scan]
                        return (
                            scan.to_frequencies(
                                device_freq_states[cols], device_dicts[cols]
                            ),
                            None,
                        )
                    if cols in table_freq:
                        if cols in table_shared:
                            return table_shared[cols], None
                        key = ("__grouping__", cols)
                        if key in fallback_states:
                            return fallback_states[key], None
                        return None, fallback_errors.get(
                            key, outcome.errors.get(table_freq[cols])
                        )
                    key = ("__grouping__", cols)
                    if key in outcome.host_errors:
                        return None, outcome.host_errors[key]
                    return outcome.host_states[key], None

                for cols, members in grouping_sets.items():
                    shared, err = shared_frequencies(cols)
                    for a in members:
                        if err is not None:
                            metrics[a] = a.to_failure_metric(err)
                        else:
                            metrics[a] = _finalize(
                                a, shared, aggregate_with, save_states_with
                            )
                for a in host_accum:
                    if a in outcome.host_errors:
                        metrics[a] = a.to_failure_metric(outcome.host_errors[a])
                    else:
                        metrics[a] = _finalize(
                            a, outcome.host_states[a], aggregate_with,
                            save_states_with,
                        )
                from ..analyzers.grouping import (
                    device_counts_to_histogram_frequencies,
                )

                for a in device_hist:
                    cols = (a.column,)
                    if device_freq_states[cols] is None:
                        metrics[a] = a.to_failure_metric(
                            outcome.errors[device_freq[cols]]
                        )
                        continue
                    shared = device_counts_to_histogram_frequencies(
                        device_freq[cols],
                        device_freq_states[cols],
                        device_dicts[cols],
                    )
                    metrics[a] = _finalize(
                        a, shared, aggregate_with, save_states_with
                    )
            if slim:
                # explicit spill-dir cleanup: pass-local grouping/histogram
                # tables are dead once their metrics are derived — release
                # any _SpillStore directory NOW instead of at GC time. A
                # non-slim run may have handed the state OBJECT to a
                # persister (InMemoryStateProvider keeps the reference), so
                # those rely on the GC finalizer backstop.
                for st in (
                    *outcome.host_states.values(),
                    *fallback_states.values(),
                ):
                    if isinstance(st, FrequenciesAndNumRows):
                        st.close()
        for a in others:
            metrics[a] = a.to_failure_metric(
                MetricCalculationException(f"No execution strategy for analyzer {a}")
            )

        context = results_loaded + precondition_failures + AnalyzerContext(metrics)

        if metrics_repository is not None and save_or_append_results_with_key is not None:
            _save_or_append(metrics_repository, save_or_append_results_with_key, context)
        return context

    # ------------------------------------------------------------------

    @staticmethod
    def run_on_aggregated_states(
        schema,
        analyzers: Sequence[Analyzer],
        state_loaders: Sequence[StateLoader],
        *,
        save_states_with: Optional[StatePersister] = None,
        metrics_repository: Optional[Any] = None,
        save_or_append_results_with_key: Optional[Any] = None,
    ) -> AnalyzerContext:
        """Compute metrics purely from merged persisted states — no data pass
        (reference `AnalysisRunner.runOnAggregatedStates`,
        `AnalysisRunner.scala:385-460`)."""
        if len(analyzers) == 0 or len(state_loaders) == 0:
            return AnalyzerContext.empty()

        passed: List[Analyzer] = []
        failures: Dict[Analyzer, Metric] = {}
        for a in analyzers:
            exc = Preconditions.find_first_failing(schema, a.preconditions())
            if exc is None:
                passed.append(a)
            else:
                failures[a] = a.to_failure_metric(exc)

        from ..analyzers.base import merge_states_batched

        metrics: Dict[Analyzer, Metric] = {}
        for a in passed:
            merged = merge_states_batched(
                a, [loader.load(a) for loader in state_loaders]
            )
            if save_states_with is not None and merged is not None:
                save_states_with.persist(a, merged)
            try:
                metrics[a] = a.compute_metric_from(merged)
            except Exception as exc:  # noqa: BLE001
                metrics[a] = a.to_failure_metric(exc)

        context = AnalyzerContext(failures) + AnalyzerContext(metrics)
        if metrics_repository is not None and save_or_append_results_with_key is not None:
            _save_or_append(metrics_repository, save_or_append_results_with_key, context)
        return context


def _finalize(
    analyzer: Analyzer,
    state: Any,
    aggregate_with: Optional[StateLoader],
    save_states_with: Optional[StatePersister],
) -> Metric:
    from ..analyzers.base import merge_states_batched

    try:
        if aggregate_with is not None:
            loaded = aggregate_with.load(analyzer)
            state = merge_states_batched(analyzer, [loaded, state])
        if save_states_with is not None and state is not None:
            save_states_with.persist(analyzer, state)
        return analyzer.compute_metric_from(state)
    except Exception as exc:  # noqa: BLE001
        return analyzer.to_failure_metric(exc)


def _device_tier_expected(scanning, placement) -> bool:
    """Whether the shared pass will stream batches to the DEVICE tier —
    the gate for the device frequency table engine (its raw per-row hash
    keys cost ~8B/row/column on the feed link; on a host-tier pass the
    in-place group-by is strictly better). Delegates to the engine's own
    ``resolve_scan_placement`` so the gate can never drift from where the
    pass actually runs."""
    from ..utils import env_str
    from .engine import (
        _FEED_BANDWIDTH_THRESHOLD_MBPS,
        PLACEMENT_ENV,
        probe_feed_bandwidth,
        resolve_scan_placement,
    )

    if scanning:
        return resolve_scan_placement(scanning, placement) == "device"
    # no scan battery to ride: adding the (device-only) frequency scans
    # would CREATE a device pass, which only pays off when the feed link
    # is fast or the caller explicitly asked for the device tier
    effective = placement or env_str(PLACEMENT_ENV, "auto")
    if effective == "host":
        return False
    if effective == "device":
        return True
    return probe_feed_bandwidth() >= _FEED_BANDWIDTH_THRESHOLD_MBPS


def _columns_needed(engine: ScanEngine, grouping_sets, host_accum, schema) -> Optional[List[str]]:
    """Restrict batch materialization to columns any analyzer touches; None
    (= all columns) when a predicate may reference arbitrary columns."""
    if any(spec.kind == "pred" for spec in engine.builder.specs.values()):
        return None
    cols = set(engine.required_columns())
    for set_cols in grouping_sets:
        cols.update(set_cols)
    for a in host_accum:
        if getattr(a, "where", None) is not None:
            # a host-accumulated where-filter evaluates its predicate over
            # raw batch columns, which may reference any column
            return None
        cols.add(a.column)
    if not cols:
        return []
    return [c for c in schema.names if c in cols]


def _save_or_append(repository, key, context: AnalyzerContext) -> None:
    """Append semantics (reference `AnalysisRunner.scala:205-223`)."""
    existing = repository.load_by_key(key)
    combined = (existing or AnalyzerContext.empty()) + context
    repository.save(key, combined)
