"""Global configuration for the TPU data-quality engine.

The reference (deequ) relies on JVM doubles everywhere; to hold the +-1e-6
metric-parity target we default to float64 accumulators, which requires
jax_enable_x64. Set DEEQU_TPU_NO_X64=1 before import to opt out (accumulators
then fall back to float32 + compensated summation where implemented).
"""

from __future__ import annotations

import os

import jax

if not os.environ.get("DEEQU_TPU_NO_X64"):
    jax.config.update("jax_enable_x64", True)

# persistent XLA compilation cache: fused analyzer programs are large (tens
# of seconds to compile) and identical across processes/runs
if not os.environ.get("DEEQU_TPU_NO_COMPILE_CACHE"):
    _cache_dir = os.environ.get(
        "DEEQU_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/deequ_tpu_xla")
    )
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 - cache is best-effort
        pass

import jax.numpy as jnp  # noqa: E402  (after x64 setup)

#: dtype used for floating-point accumulator states (sums, moments, ...)
ACC_DTYPE = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
#: dtype used for integer counters
COUNT_DTYPE = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

#: default number of rows per device batch fed to the fused update program
DEFAULT_BATCH_SIZE = 1 << 20

# ---------------------------------------------------------------------------
# Device scan-program bundling + slim state fetch (read per call, not at
# import, so tests and operators can flip them without re-importing jax)
# ---------------------------------------------------------------------------

#: env var sizing the signature-keyed device scan bundles: a battery is
#: partitioned into (analyzer-class, state-shape) bundles of at most this
#: many analyzers, each compiled as ONE small PackedScanProgram that is
#: REUSED across columns, batteries and runs (a 50-column profile compiles
#: ~10 small programs instead of one monolithic one). "0" restores the
#: monolithic one-program-per-battery behavior (maximum fusion, maximum
#: cold-compile stall).
SCAN_BUNDLE_ENV = "DEEQU_TPU_SCAN_BUNDLE"
DEFAULT_SCAN_BUNDLE = 8


def scan_bundle_size() -> int:
    raw = os.environ.get(SCAN_BUNDLE_ENV)
    if raw is None:
        return DEFAULT_SCAN_BUNDLE
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_SCAN_BUNDLE


#: env var disabling the slim state fetch ("0" = always fetch full states).
#: When enabled (default), a run that neither persists nor aggregates
#: states ships only each analyzer's METRIC-BEARING state leaves over the
#: device feed link (see Analyzer.metric_leaves); the remaining leaves are
#: reconstructed host-side from identity values the metric never reads.
SLIM_FETCH_ENV = "DEEQU_TPU_SLIM_FETCH"


def slim_fetch_enabled() -> bool:
    return os.environ.get(SLIM_FETCH_ENV, "1") != "0"


# ---------------------------------------------------------------------------
# Device frequency engine (implemented in deequ_tpu.analyzers.grouping; the
# env knobs are documented here with the other operator-facing switches and
# re-exported below). All three follow the warn-and-fallback convention:
# an unparseable value warns once and keeps the default, never crashes.
#
# - DEEQU_TPU_DEVICE_FREQ: "0" disables the device-resident frequency
#   TABLE engine (hashed fixed-shape count tables for arbitrary-cardinality
#   grouping sets); grouping then accumulates through the host group-by.
#   The dense dictionary path is unaffected.
# - DEEQU_TPU_FREQ_TABLE_SLOTS: distinct-group capacity per grouping set
#   (default 2^22; rounded up to a power of two, capped per run at the row
#   count). Sets whose cardinality exceeds it overflow EXACTLY and re-run
#   on the host last-resort tier.
# - DEEQU_TPU_DEVICE_FREQ_MAX_CARDINALITY: dictionary-size ceiling of the
#   dense per-code device counting path (default 2^16).
# - DEEQU_TPU_FREQ_BUFFER_ENTRIES: raw key-buffer cap (default 2^25 = 256MB
#   of u64 keys; rounded up to a power of two). Runs whose padded row count
#   fits ride the RESIDENT trace (memcpy-speed appends, zero in-pass
#   compactions, exact at any cardinality); larger runs use the
#   conditional-compaction trace.
# - DEEQU_TPU_FREQ_HOST_ROUTE: "0" disables the cardinality pre-routing
#   probe — every eligible grouping set takes the device table even when a
#   cheap probe says the host group-by's value_counts fast path would win
#   (confidently-low-cardinality sets at >2M rows).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Ingestion plane (implemented in deequ_tpu.ingest; the env knob is
# documented here with the other operator-facing switches and re-exported
# below). Follows the warn-and-fallback convention: an unparseable value
# warns once and keeps the default.
#
# - DEEQU_TPU_PREFETCH_DEPTH: staged batches in the double-buffered
#   host->device feed pipeline (default 2: one batch folding on device,
#   one staged with its transfer in flight, one being built). "0" removes
#   the feed thread entirely — batches build and transfer inline on the
#   consumer thread, the measured "serial" baseline of PERF.md's overlap
#   numbers. Batch shapes stay pow2-bucketed upstream, so a deeper
#   pipeline never provokes a recompile.
# - DEEQU_TPU_FEED_STALL_S: seconds the fold tolerates a SILENT feed
#   thread before declaring it wedged with a typed FeedStallError
#   (default 120; <= 0 disables). A tripped deadline fails the pass over
#   to the host tier exactly like a thrown device fault.
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Cross-session fold coalescing + tiny-delta host fast path (implemented in
# deequ_tpu.service.coalesce; the env knobs are documented here with the
# other operator-facing switches and re-exported below). All follow the
# warn-and-fallback convention: an unparseable value warns once and keeps
# the default.
#
# - DEEQU_TPU_COALESCE: "0" disables the whole coalescing plane — every
#   streaming ingest takes exactly the pre-coalescing serial path (the
#   true escape hatch; default on).
# - DEEQU_TPU_COALESCE_MAX_WIDTH: max sessions stacked into one coalesced
#   device launch (default 16; launches bucket their width to powers of
#   two so the compiled-shape space stays log-bounded).
# - DEEQU_TPU_FAST_PATH_MAX_ROWS: fixed row ceiling for the host fast
#   path. Default -1 = route from the MEASURED per-analyzer-class
#   crossover (host-kernel rates observed on every fast fold vs the
#   device fixed cost observed on every coalesced launch); 0 forces every
#   eligible fold onto the coalesced device path.
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Fleet scheduler (implemented in deequ_tpu.service.fleet; the env knobs
# are documented here with the other operator-facing switches and
# re-exported below). Both follow the warn-and-fallback convention.
#
# - DEEQU_TPU_FLEET: "0" disables fleet scheduling entirely — single-chip
#   routing, byte-for-byte the pre-fleet service path (the escape hatch);
#   "1" forces it on even on the CPU backend (virtual-device drills and
#   tests); unset = ON exactly when the backend is a real accelerator
#   with more than one chip. When on, every tenant's batch scans shard
#   across that tenant's DISJOINT sub-mesh slice of the device mesh, and
#   fleet-sized streaming deltas fold shard-local + butterfly-merge at
#   coalesce-drain boundaries.
# - DEEQU_TPU_FLEET_STREAM_MIN_ROWS: minimum micro-batch rows before a
#   streaming fold shards over the tenant's sub-mesh (default 65536 —
#   below it the single-chip coalesced/fast paths beat the collective's
#   latency; 0 shards every eligible fold, the fleet drills use it).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Partition-aware incremental verification (implemented in
# deequ_tpu.repository.partition_store + deequ_tpu.runners.incremental;
# the env knobs are documented here with the other operator-facing
# switches and re-exported below). Both follow the warn-and-fallback
# convention where numeric.
#
# - DEEQU_TPU_PARTITION_STORE: root path (local or any deequ_tpu.io URI —
#   s3://, gs://, memory://) of the service-default PartitionStateStore.
#   When set, VerificationService plans incremental runs against it and
#   streaming sessions flush their cumulative states into it as a
#   partition on close. Unset = no default store (pass one explicitly).
# - DEEQU_TPU_PARTITION_WINDOW_MONTHS: default listing window, in month
#   buckets, for partition listings with no explicit window (0 =
#   unlimited). The store's directory layout is time-partitioned
#   (YYYY-MM buckets for date-named partitions), so a year of daily
#   partitions lists in O(window) directory walks; this knob bounds the
#   default walk for dropped-partition detection on very old stores.
#   Unparseable values warn once and keep the default (0).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Fleet watch — the standing fleet-scale anomaly plane (implemented in
# deequ_tpu.service.fleetwatch; the env knobs are documented here with the
# other operator-facing switches and re-exported below). All three follow
# the warn-and-fallback convention via the shared utils parsers.
#
# - DEEQU_TPU_FLEETWATCH: "0" detaches the standing watch from scheduler
#   harvests (explicit FleetWatch.harvest_now() still scores); default on.
#   When attached, every completed job of a WATCHED tenant triggers one
#   debounced scoring pass over every watched tenant's metric history.
# - DEEQU_TPU_FLEETWATCH_WINDOW_MONTHS: metric-history window each
#   harvest scores, in month buckets (default 12; 0 = unbounded). Rides
#   the PartitionedMetricsRepository's O(queried window) loads, so a year
#   of per-run history never costs a full-history deserialize per score.
# - DEEQU_TPU_FLEETWATCH_BUNDLE: maximum series stacked into one batched
#   detect_batch call (default 16384 — a 10k-tenant fleet scores in ONE
#   call per strategy bundle; larger fleets chunk).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Engine placement / host tier / profiling (implemented in
# deequ_tpu.runners.engine + .analysis_runner; documented here with the
# other operator-facing switches — the invariant linter's env-knob check
# (tools/statlint) requires every DEEQU_TPU_* knob read anywhere in the
# package to be discoverable from this file).
#
# - DEEQU_TPU_PLACEMENT: default ingest-tier placement when a run passes
#   none — "auto" (probe the feed link), "host", or "device".
# - DEEQU_TPU_HOST_TIER_WORKERS: host ingest tier partial-worker pool
#   size (default: all cores; 0/unset = default; warn-and-fallback).
# - DEEQU_TPU_DEVICE_FEATURE_CACHE: HBM budget in GB for the
#   device-resident feature cache; unset/"0" disables (warn-and-fallback).
# - DEEQU_TPU_PROFILE_DIR: directory receiving a jax.profiler trace of
#   every pass; unset = profiling off.
# - DEEQU_TPU_NO_NATIVE: "1" disables the native host kernels entirely
#   (pure-Python fallbacks); read at deequ_tpu.native.lib import.
# - DEEQU_TPU_ADAPTIVE_DICT_ENCODE: "0" disables ingest-time adaptive
#   dictionary encoding of low-cardinality string columns (data module).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Host group-by spill tier (implemented in deequ_tpu.analyzers.grouping's
# host accumulator; documented here for discoverability). All three follow
# the warn-and-fallback convention via utils.env_number/env_flag.
#
# - DEEQU_TPU_MAX_FREQUENCY_ENTRIES: host frequency-table entry budget
#   before the accumulator spills to disk (0 = unbounded, the default).
# - DEEQU_TPU_FREQUENCY_SPILL: "0" disables the disk spill tier (the
#   budget then degrades the analyzer instead of spilling).
# - DEEQU_TPU_FREQUENCY_SPILL_PARTITIONS: hash partitions of the spill
#   store's disk layout (default 64; minimum 1).
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Deterministic fault injection (implemented in deequ_tpu.reliability.faults;
# documented here for discoverability — tools/chaos_soak.py drives these).
#
# - DEEQU_TPU_FAULTS: JSON list of FaultSpec dicts arming a process-wide
#   fault plan. Deliberately NOT warn-and-fallback: a chaos plan that does
#   not parse must raise, not silently run the drill fault-free.
# - DEEQU_TPU_FAULT_SEED: rng seed for p-based fault specs (default 0).
#   Same raise-loudly contract as the plan: a bad seed would silently
#   change the drill's deterministic fault sequence.
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Scan watchdog (implemented in deequ_tpu.reliability.watchdog; the env
# knob is documented here with the other operator-facing switches)
# ---------------------------------------------------------------------------

#: env var: per-pass watchdog deadline in seconds. Unset = derive from the
#: measured per-batch rate of completed passes on the same tier (a 10x
#: multiple with a 30s floor; disabled until a first rate exists). Any
#: value <= 0 disables the watchdog. A pass exceeding its deadline is
#: cancelled with a typed ScanStallError and fails over to the other tier
#: exactly like a thrown device fault.
SCAN_DEADLINE_ENV = "DEEQU_TPU_SCAN_DEADLINE_S"


# ---------------------------------------------------------------------------
# Elastic mesh fault tolerance (implemented in deequ_tpu.parallel.elastic /
# .health; the env knobs are documented here with the other operator-facing
# switches and re-exported below). Both follow the warn-and-fallback
# convention: an unparseable value warns once and keeps the default.
#
# - DEEQU_TPU_MESH_LADDER: comma-separated descending device counts the
#   re-shard ladder walks after a shard loss (default "8,4,2,1"). When no
#   rung fits the survivors, the fold drops to the host tier with the
#   salvaged canonical states — folded work is never lost.
# - DEEQU_TPU_SHARD_HEARTBEAT_S: seconds between heartbeat probes of a live
#   mesh fold, and each probe's per-shard deadline (default 5.0; <= 0
#   disables the periodic heartbeat). A shard missing its heartbeat is
#   declared lost exactly like a thrown ShardLossError.
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Tracing / flight recorder (implemented in deequ_tpu.observability; the env
# knobs are documented here with the other operator-facing switches)
# ---------------------------------------------------------------------------

# Single source of truth lives where the values are READ (the modules
# below); re-exported here so every operator-facing knob is discoverable
# from config:
#
# - DEEQU_TPU_TRACE: span tracing. Default ON ("1"/unset); "0" disables
#   entirely; a float in (0, 1) samples that fraction of root traces
#   deterministically (unparseable values warn once and keep the default).
#   Measured overhead of default-on tracing is <2% on the bench scan stage
#   (PERF.md "Tracing overhead").
# - DEEQU_TPU_TRACE_RING: capacity of the flight-recorder ring of recent
#   finished spans (default 4096) — what /trace serves and what
#   typed-failure post-mortem dumps snapshot.
# - DEEQU_TPU_TRACE_JOURNAL: directory receiving this process's span
#   JOURNAL (``spans-<host>.jsonl``, line-buffered, one span per line as
#   it finishes) — the per-host half of a cross-process merged trace
#   (observability.export.merge_journals). Unset = no journal.
# - DEEQU_TPU_TRACE_HOST: the host label stamped on this process's
#   journal filename and header (default ``pid<pid>``); what the merged
#   Perfetto artifact names the process track.
# - DEEQU_TPU_FLIGHT_DIR: directory receiving flight-record JSONL
#   artifacts dumped on typed failures (DeviceFailure / ScanStallError /
#   CorruptStateError / SchemaDriftError). Unset = per-process temp dir.
from .ingest.prefetch import (  # noqa: E402,F401
    FEED_STALL_ENV,
    PREFETCH_DEPTH_ENV,
)
from .service.coalesce import (  # noqa: E402,F401
    COALESCE_ENV,
    COALESCE_MAX_WIDTH_ENV,
    FAST_PATH_MAX_ROWS_ENV,
)
from .service.fleet import (  # noqa: E402,F401
    FLEET_ENV,
    FLEET_STREAM_MIN_ROWS_ENV,
)
from .repository.partition_store import (  # noqa: E402,F401
    PARTITION_STORE_ENV,
    PARTITION_WINDOW_ENV,
)
from .service.fleetwatch import (  # noqa: E402,F401
    FLEETWATCH_BUNDLE_ENV,
    FLEETWATCH_ENV,
    FLEETWATCH_WINDOW_ENV,
)
from .observability.recorder import FLIGHT_DIR_ENV  # noqa: E402,F401
from .parallel.elastic import MESH_LADDER_ENV  # noqa: E402,F401
from .parallel.health import HEARTBEAT_ENV as SHARD_HEARTBEAT_ENV  # noqa: E402,F401
from .observability.trace import TRACE_ENV, TRACE_RING_ENV  # noqa: E402,F401
from .analyzers.grouping import (  # noqa: E402,F401
    DEVICE_FREQ_ENV,
    DEVICE_FREQ_MAX_CARDINALITY_ENV,
    FREQ_BUFFER_ENTRIES_ENV,
    FREQ_HOST_ROUTE_ENV,
    FREQ_TABLE_SLOTS_ENV,
)

# ---------------------------------------------------------------------------
# Cluster tier (implemented in deequ_tpu.cluster + repository/lease.py; the
# env knobs are documented here with the other operator-facing switches)
# ---------------------------------------------------------------------------
#
# - DEEQU_TPU_CLUSTER_VNODES: virtual nodes per host on the front tier's
#   consistent-hash ring (default 64; minimum 1). More points smooth the
#   per-host key distribution at slightly larger ring rebuild cost; a
#   membership change always re-homes only ~1/N of the key space.
# - DEEQU_TPU_CLUSTER_HEARTBEAT_S: seconds between a worker's heartbeat
#   writes into the shared membership directory (default 0.5; minimum
#   0.05). Heartbeats are atomic tmp+rename file writes on the same
#   shared filesystem the partition store uses.
# - DEEQU_TPU_CLUSTER_HOST_TTL_S: seconds without a beat before the front
#   tier declares a host LOST (default 3.0; minimum 0.1) and runs
#   recovery: ring re-hash to survivors, session adoption from the
#   partition store, journal replay of the folds the last flush missed.
#   Size it to several heartbeat periods to ride out scheduler hiccups.
# - DEEQU_TPU_CLUSTER_LEASE_TTL_S: seconds a compaction lease on a
#   PartitionedMetricsRepository stays valid without renewal (default
#   30.0; minimum 0.1). The lease elects ONE compactor among concurrent
#   writers (atomic create + epoch-fenced takeover of stale holders); a
#   refused or lost lease leaves loose entries readable — never deleted.
#
# All four parse via the shared warn-once utils.env_* readers:
# unparseable or out-of-range values log once and keep the default.
from .cluster.membership import (  # noqa: E402,F401
    HEARTBEAT_ENV as CLUSTER_HEARTBEAT_ENV,
    HOST_TTL_ENV as CLUSTER_HOST_TTL_ENV,
)
from .cluster.ring import VNODES_ENV as CLUSTER_VNODES_ENV  # noqa: E402,F401
from .repository.lease import (  # noqa: E402,F401
    LEASE_TTL_ENV as CLUSTER_LEASE_TTL_ENV,
)

# ---------------------------------------------------------------------------
# Tenant isolation plane (deequ_tpu.service.catalog + deequ_tpu.ingest.
# rowgate + the cluster front tier's journal bound)
# ---------------------------------------------------------------------------
#
# - DEEQU_TPU_CLUSTER_JOURNAL_MAX_FOLDS: payloads a session's loss-replay
#   journal may hold before the front tier force-flushes the session to
#   the partition store and clears it (default 256; minimum 1). The
#   journal replays the window since the last flush after a host loss; a
#   producer that never calls flush() would otherwise grow it one
#   payload per fold, unbounded, for the session's whole life.
# - DEEQU_TPU_CATALOG_HOT_TTL_S: seconds a catalog-opened session may sit
#   idle in the HOT tier before the plane's sweep() closes it back to
#   COLD (default 300.0; minimum 1.0). Cold tenants cost one registry
#   row, not a session — registration scales past active capacity.
# - DEEQU_TPU_CATALOG_POLL_S: debounce on the fold-boundary version poll
#   of a hot tenant's catalog document (default 2.0; minimum 0.0). A
#   catalog edit becomes effective within one poll interval at the next
#   fold boundary — no restart; 0 polls every fold.
# - DEEQU_TPU_ROWGATE_QUARANTINE_MAX_ROWS: total rows a quarantine
#   sidecar retains per (tenant, dataset) before further rejects are
#   counted but dropped (default 100000; minimum 0). Bounds the disk a
#   misbehaving producer can consume with nonconforming rows.
#
# All four parse via the shared warn-once utils.env_* readers:
# unparseable or out-of-range values log once and keep the default.
from .cluster.front import (  # noqa: E402,F401
    CLUSTER_JOURNAL_MAX_FOLDS_ENV,
)
from .ingest.rowgate import (  # noqa: E402,F401
    QUARANTINE_MAX_ROWS_ENV as ROWGATE_QUARANTINE_MAX_ROWS_ENV,
)
from .service.catalog import (  # noqa: E402,F401
    CATALOG_HOT_TTL_ENV,
    CATALOG_POLL_ENV,
)

# ---------------------------------------------------------------------------
# Self-tuning control plane (deequ_tpu.tuning: boot-time calibration,
# per-substrate profiles, online shadow-route re-fitting)
# ---------------------------------------------------------------------------
#
# - DEEQU_TPU_AUTOTUNE: "0" disables the whole tuning plane — no profile
#   load at service start, no online controller, and every registered
#   knob resolves to its static default, byte-for-byte the untuned
#   routing behavior (the escape hatch; pinned by tests/test_tuning.py).
#   Default on.
# - DEEQU_TPU_TUNING_PROFILE_DIR: directory holding the checksummed
#   per-substrate calibration profiles (default: a deequ_tpu_tuning
#   directory beside the DEEQU_TPU_COMPILE_CACHE XLA cache). One file
#   per substrate fingerprint; corrupt or stale files are quarantined
#   into .quarantine/ and the service boots on static defaults.
# - DEEQU_TPU_TUNING_SHADOW_FRACTION: fraction of eligible folds the
#   online controller routes under a CANDIDATE knob setting while an
#   experiment runs (default 0.05; clamped to [0, 0.5] — the incumbent
#   always keeps majority traffic; 0 starves candidates of evidence, so
#   nothing is ever promoted).
# - DEEQU_TPU_TUNING_MIN_SAMPLES: measured folds each experiment arm
#   needs before a promotion/demotion verdict (default 32; minimum 1).
# - DEEQU_TPU_TUNING_BAND: the bench_diff-style tolerance band — a
#   candidate promotes only when its measured rows/s beats the incumbent
#   by MORE than this fraction, and the floor guardrail demotes tuned
#   knobs when the live rate falls this far below the measured
#   static-default rate (default 0.25, the bench_diff CI tolerance).
#
# Every tunable routing constant (fast-path ceiling, coalesce width,
# fleet sharding floor, prefetch depth, frequency-engine capacities, the
# probably_low_cardinality probe thresholds, the CrossoverRouter cost
# seeds) is registered in deequ_tpu/tuning/knobs.py; the env vars above
# and each knob's own DEEQU_TPU_* override parse via the shared
# warn-once utils.env_* readers, and operator env ALWAYS outranks tuned
# values. New DEEQU_TPU_FREQ_* overrides registered there:
#
# - DEEQU_TPU_FREQ_HOST_ROUTE_MAX_DISTINCT: union-distinct ceiling for
#   probably_low_cardinality to answer "host" (default 32768; min 1).
# - DEEQU_TPU_FREQ_PROBE_ROWS: rows per head/mid/tail probe slice
#   (default 65536; minimum 1).
# - DEEQU_TPU_FREQ_HOST_ROUTE_MIN_ROWS: row floor below which the probe
#   never routes host (default 2097152; minimum 0).
from .tuning.knobs import (  # noqa: E402,F401
    AUTOTUNE_ENV,
    TUNING_BAND_ENV,
    TUNING_MIN_SAMPLES_ENV,
    TUNING_PROFILE_DIR_ENV,
    TUNING_SHADOW_FRACTION_ENV,
)
