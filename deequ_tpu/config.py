"""Global configuration for the TPU data-quality engine.

The reference (deequ) relies on JVM doubles everywhere; to hold the +-1e-6
metric-parity target we default to float64 accumulators, which requires
jax_enable_x64. Set DEEQU_TPU_NO_X64=1 before import to opt out (accumulators
then fall back to float32 + compensated summation where implemented).
"""

from __future__ import annotations

import os

import jax

if not os.environ.get("DEEQU_TPU_NO_X64"):
    jax.config.update("jax_enable_x64", True)

# persistent XLA compilation cache: fused analyzer programs are large (tens
# of seconds to compile) and identical across processes/runs
if not os.environ.get("DEEQU_TPU_NO_COMPILE_CACHE"):
    _cache_dir = os.environ.get(
        "DEEQU_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/deequ_tpu_xla")
    )
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 - cache is best-effort
        pass

import jax.numpy as jnp  # noqa: E402  (after x64 setup)

#: dtype used for floating-point accumulator states (sums, moments, ...)
ACC_DTYPE = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
#: dtype used for integer counters
COUNT_DTYPE = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

#: default number of rows per device batch fed to the fused update program
DEFAULT_BATCH_SIZE = 1 << 20
