"""Failure taxonomy for metric computation.

Mirrors the reference's typed exception hierarchy
(`analyzers/runners/MetricCalculationException.scala:19-78`): every analyzer
error is captured as a Failure *metric*, never an aborted run — partial
results are a feature (`analyzers/Analyzer.scala:94-103`).
"""

from __future__ import annotations


class MetricCalculationException(Exception):
    """Base for all metric-calculation failures."""


class MetricCalculationPreconditionException(MetricCalculationException):
    """Schema precondition failed before any data was scanned."""


class MetricCalculationRuntimeException(MetricCalculationException):
    """Failure while computing the metric from data."""


class NoSuchColumnException(MetricCalculationPreconditionException):
    pass


class WrongColumnTypeException(MetricCalculationPreconditionException):
    pass


class NoColumnsSpecifiedException(MetricCalculationPreconditionException):
    pass


class NumberOfSpecifiedColumnsException(MetricCalculationPreconditionException):
    pass


class IllegalAnalyzerParameterException(MetricCalculationPreconditionException):
    pass


class EmptyStateException(MetricCalculationRuntimeException):
    """All input values were null/filtered — no state to finalize."""


class DeviceFailureException(MetricCalculationRuntimeException):
    """The accelerator tier failed for INFRASTRUCTURE reasons (XLA runtime
    error, lost device, relay/tunnel fault) rather than anything about the
    data or the analyzer. The reliability layer treats this class as
    tier-recoverable: the same battery re-runs on the host ingest tier,
    which shares no device state with the failed pass."""


class DeviceOOMException(DeviceFailureException):
    """The device ran out of memory executing a pass. Recoverable by batch
    bisection (smaller padded batches shrink the live feature set) before
    the general host-tier failover applies."""


class PoisonedBatchException(MetricCalculationRuntimeException):
    """A specific input batch cannot be processed (corrupt encoding,
    malformed values past the dry-run validation). Carries the batch index
    so operators can quarantine the slice."""

    def __init__(self, batch_index: int, message: str = ""):
        self.batch_index = batch_index
        super().__init__(
            f"batch {batch_index} is poisoned{': ' + message if message else ''}"
        )


class AnalyzerFaultException(MetricCalculationRuntimeException):
    """A fault attributable to ONE analyzer inside a fused battery. The
    isolation machinery bisects the battery until the faulty analyzer is
    alone in its partition, degrades it to a typed Failure metric, and
    completes the rest."""


class UnsupportedFormatVersionError(Exception):
    """A persisted payload (metrics-history JSON or .npz state blob) carries
    a format version this build does not understand. Raised INSTEAD of
    silently misreading a layout from a newer build (SURVEY §7 hard part 5:
    incremental-state serialization stability across versions)."""

    def __init__(self, kind: str, found: int, supported: int):
        self.kind = kind
        self.found = found
        self.supported = supported
        super().__init__(
            f"{kind} format version {found} is not supported by this build "
            f"(max supported: {supported}). Upgrade deequ_tpu to read this "
            f"payload, or re-materialize it with the current build."
        )


def wrap_if_necessary(exception: BaseException) -> MetricCalculationException:
    """Wrap arbitrary errors into the taxonomy
    (reference `MetricCalculationException.scala:70-78`)."""
    if isinstance(exception, MetricCalculationException):
        return exception
    wrapped = MetricCalculationRuntimeException(str(exception))
    wrapped.__cause__ = exception
    return wrapped
