"""Failure taxonomy for metric computation.

Mirrors the reference's typed exception hierarchy
(`analyzers/runners/MetricCalculationException.scala:19-78`): every analyzer
error is captured as a Failure *metric*, never an aborted run — partial
results are a feature (`analyzers/Analyzer.scala:94-103`).
"""

from __future__ import annotations


class MetricCalculationException(Exception):
    """Base for all metric-calculation failures."""


class MetricCalculationPreconditionException(MetricCalculationException):
    """Schema precondition failed before any data was scanned."""


class MetricCalculationRuntimeException(MetricCalculationException):
    """Failure while computing the metric from data."""


class NoSuchColumnException(MetricCalculationPreconditionException):
    pass


class WrongColumnTypeException(MetricCalculationPreconditionException):
    pass


class NoColumnsSpecifiedException(MetricCalculationPreconditionException):
    pass


class NumberOfSpecifiedColumnsException(MetricCalculationPreconditionException):
    pass


class IllegalAnalyzerParameterException(MetricCalculationPreconditionException):
    pass


class EmptyStateException(MetricCalculationRuntimeException):
    """All input values were null/filtered — no state to finalize."""


class DeviceFailureException(MetricCalculationRuntimeException):
    """The accelerator tier failed for INFRASTRUCTURE reasons (XLA runtime
    error, lost device, relay/tunnel fault) rather than anything about the
    data or the analyzer. The reliability layer treats this class as
    tier-recoverable: the same battery re-runs on the host ingest tier,
    which shares no device state with the failed pass."""


class DeviceOOMException(DeviceFailureException):
    """The device ran out of memory executing a pass. Recoverable by batch
    bisection (smaller padded batches shrink the live feature set) before
    the general host-tier failover applies."""


class PoisonedBatchException(MetricCalculationRuntimeException):
    """A specific input batch cannot be processed (corrupt encoding,
    malformed values past the dry-run validation). Carries the batch index
    so operators can quarantine the slice."""

    def __init__(self, batch_index: int, message: str = ""):
        self.batch_index = batch_index
        super().__init__(
            f"batch {batch_index} is poisoned{': ' + message if message else ''}"
        )


class AnalyzerFaultException(MetricCalculationRuntimeException):
    """A fault attributable to ONE analyzer inside a fused battery. The
    isolation machinery bisects the battery until the faulty analyzer is
    alone in its partition, degrades it to a typed Failure metric, and
    completes the rest."""


class CorruptStateError(MetricCalculationRuntimeException, ValueError):
    """A persisted payload (state blob, repository entry, checkpoint) failed
    its integrity check: the stored xxhash64 content checksum does not match
    the bytes on disk, or the payload is structurally torn. The data plane
    treats this as RECOVERABLE, never fatal: corrupt checkpoints fall back
    to a fresh fold (the resume point is lost, the results are not), corrupt
    repository entries are quarantined to a ``.quarantine/`` sidecar instead
    of poisoning query loaders, and corrupt state blobs degrade exactly the
    analyzers that needed them to typed ``Failure`` metrics. The reference
    assumes torn/garbled state rather than hoping against it — its per-type
    binary codecs pin byte layouts precisely (`StateProvider.scala:187-311`);
    the checksum is our equivalent tripwire."""

    def __init__(self, kind: str, source: str, detail: str = ""):
        self.kind = kind
        self.source = source
        super().__init__(
            f"corrupt {kind} at {source}"
            + (f": {detail}" if detail else "")
        )


class SchemaDriftError(MetricCalculationRuntimeException):
    """A streaming micro-batch's schema drifted from the session's
    :class:`~deequ_tpu.service.drift.SchemaContract` (column added/dropped/
    retyped beyond a compatible widening). Raised BEFORE the batch folds,
    so persisted algebraic states are never contaminated by mixed-schema
    merges. Carries the structured drift list for operator triage."""

    def __init__(self, session: str, drifts):
        self.session = session
        self.drifts = list(drifts)
        super().__init__(
            f"schema drift in session {session}: " + "; ".join(self.drifts)
        )


class ShardLossError(DeviceFailureException):
    """A shard of a multi-device mesh was lost mid-pass: a dead device, a
    dead ``jax.distributed`` process, or a heartbeat-declared stall. Unlike
    a plain :class:`DeviceFailureException` (one sick accelerator, recover
    on the host), a shard loss is MESH-recoverable: the surviving shards'
    algebraic states are mergeable by construction, so the elastic layer
    (`deequ_tpu.parallel.elastic`) salvages them, rebuilds the mesh over
    the surviving devices one ladder rung down, and resumes the fold —
    ``classify_failure`` maps this class to ``"mesh"`` so an escaped loss
    re-shards BEFORE the host-tier failover applies.

    ``lost`` holds the mesh positions (indices into ``mesh.devices.flat``)
    declared dead; ``survivors`` optionally carries the surviving device
    objects so a pass-level retry can rebuild a mesh without re-probing."""

    def __init__(self, lost, site: str = "", survivors=None, detail: str = ""):
        self.lost = tuple(int(i) for i in lost)
        self.site = site
        self.survivors = None if survivors is None else list(survivors)
        super().__init__(
            f"mesh shard loss at {site or '<mesh>'}: shard(s) "
            f"{list(self.lost)} lost"
            + (f": {detail}" if detail else "")
        )


class ShardStallError(ShardLossError):
    """A shard stopped making progress (heartbeat probe exceeded
    ``DEEQU_TPU_SHARD_HEARTBEAT_S``) without raising. Declared lost after
    the probe deadline — the hang-not-crash failure mode on a mesh, handled
    exactly like a thrown shard loss (salvage + re-shard), mirroring how
    :class:`ScanStallError` piggybacks on the device-failover path."""


class MalformedFrameError(MetricCalculationRuntimeException, ValueError):
    """A frame on the ingestion plane failed to decode: torn Arrow IPC
    bytes, a schema message that is not a schema, or a payload whose
    declared checksum does not match the bytes received. Raised BEFORE
    anything folds, so a corrupt producer can never contaminate a
    session's persisted states — the frame is rejected typed and the
    stream position it occupied is reported for operator triage."""

    def __init__(self, source: str, detail: str = "", frame_index: int = -1):
        self.source = source
        self.frame_index = int(frame_index)
        where = f" (frame {frame_index})" if frame_index >= 0 else ""
        super().__init__(
            f"malformed ingest frame from {source}{where}"
            + (f": {detail}" if detail else "")
        )


class FeedDisconnectError(MetricCalculationRuntimeException):
    """An ingest stream ended mid-frame: the producer disconnected, the
    socket died, or the payload was truncated below its declared length.
    Frames that decoded COMPLETELY before the disconnect have already
    folded (each is one atomic micro-batch merge); the torn tail frame
    never touches state. Carries how far the stream got so a resuming
    producer knows what committed."""

    def __init__(self, source: str, frames_decoded: int = 0,
                 bytes_read: int = 0, detail: str = ""):
        self.source = source
        self.frames_decoded = int(frames_decoded)
        self.bytes_read = int(bytes_read)
        super().__init__(
            f"ingest feed from {source} disconnected mid-frame after "
            f"{frames_decoded} complete frame(s), {bytes_read} byte(s)"
            + (f": {detail}" if detail else "")
        )


class FeedStallError(DeviceFailureException):
    """The prefetching feed pipeline that stages host->device transfers
    stopped delivering batches (a wedged transfer thread, a starved
    source). Deliberately a ``DeviceFailureException`` subclass: the
    pipeline only exists on the device tier, so ``classify_failure``
    routes the pass to the host tier — whose chunk iteration shares none
    of the stalled machinery — exactly like a thrown device fault."""

    def __init__(self, site: str, detail: str = ""):
        self.site = site
        super().__init__(
            f"ingest feed pipeline stalled at {site}"
            + (f": {detail}" if detail else "")
        )


class ScanStallError(DeviceFailureException):
    """A device or host-tier pass exceeded its watchdog deadline without
    finishing OR failing — the hang-not-crash failure mode the exception-
    driven reliability layer cannot see. Deliberately a
    ``DeviceFailureException`` subclass: ``classify_failure`` then maps it
    to the tier-failover path (the battery re-runs on the other tier with
    fresh states) and the service's placement router puts the battery on
    probation, exactly like a thrown device fault."""

    def __init__(self, site: str, deadline_s: float, waited_s: float):
        self.site = site
        self.deadline_s = deadline_s
        self.waited_s = waited_s
        super().__init__(
            f"scan watchdog: {site} pass exceeded its {deadline_s:.1f}s "
            f"deadline (waited {waited_s:.1f}s); cancelling and failing over"
        )


class UnsupportedFormatVersionError(Exception):
    """A persisted payload (metrics-history JSON or .npz state blob) carries
    a format version this build does not understand. Raised INSTEAD of
    silently misreading a layout from a newer build (SURVEY §7 hard part 5:
    incremental-state serialization stability across versions)."""

    def __init__(self, kind: str, found: int, supported: int):
        self.kind = kind
        self.found = found
        self.supported = supported
        super().__init__(
            f"{kind} format version {found} is not supported by this build "
            f"(max supported: {supported}). Upgrade deequ_tpu to read this "
            f"payload, or re-materialize it with the current build."
        )


def wrap_if_necessary(exception: BaseException) -> MetricCalculationException:
    """Wrap arbitrary errors into the taxonomy
    (reference `MetricCalculationException.scala:70-78`)."""
    if isinstance(exception, MetricCalculationException):
        return exception
    wrapped = MetricCalculationRuntimeException(str(exception))
    wrapped.__cause__ = exception
    return wrapped


#: Typed exceptions that LIVE next to their subsystem (import cycles or
#: cohesion keep them out of this module) but are part of the package's
#: failure taxonomy: each is importable from here lazily, and the invariant
#: linter (tools/statlint, failure-registry check) requires every exception
#: class defined outside the registry modules (this file, service/errors.py,
#: runners/exceptions.py, reliability/faults.py) to be listed in this
#: mapping — a typed failure nobody can discover is not typed.
_SUBSYSTEM_EXCEPTIONS = {
    "SerializationError": "deequ_tpu.repository.serde",
    "ExpressionError": "deequ_tpu.expr",
    "FrequencyBudgetExceeded": "deequ_tpu.analyzers.grouping",
    "MeshExhaustedError": "deequ_tpu.parallel.elastic",
    "HostLossError": "deequ_tpu.cluster.membership",
    "CatalogError": "deequ_tpu.service.catalog",
    "FrameQuarantinedError": "deequ_tpu.ingest.rowgate",
}


def __getattr__(name: str):
    """PEP 562 lazy re-export of the subsystem exceptions (eager imports
    here would cycle: every subsystem imports this module)."""
    target = _SUBSYSTEM_EXCEPTIONS.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)
