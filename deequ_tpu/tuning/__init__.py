"""The self-tuning performance control plane (ROADMAP item 3).

Three layers close the loop the hand-measured constants left open:

- :mod:`~deequ_tpu.tuning.knobs` — the registry every tunable routing
  constant resolves through (env override > tuned > static default);
- :mod:`~deequ_tpu.tuning.calibrate` + :mod:`~deequ_tpu.tuning.profile`
  — boot-time micro-probes persisted as a checksummed per-substrate
  profile beside the XLA cache;
- :mod:`~deequ_tpu.tuning.controller` — the online re-fitter that
  shadow-routes candidates under live traffic and promotes only behind
  a bench_diff-style band, with a never-below-static floor guardrail.

``DEEQU_TPU_AUTOTUNE=0`` disables all of it: no profile load, no
controller, every knob read byte-identical to the static defaults.
"""

from __future__ import annotations

import logging
from typing import Optional

from . import knobs
from .controller import TuningController
from .profile import SubstrateProfile, load_profile, save_profile

logger = logging.getLogger(__name__)

__all__ = [
    "knobs", "TuningController", "SubstrateProfile",
    "load_profile", "save_profile", "bootstrap_service",
]


def bootstrap_service(service) -> Optional[TuningController]:
    """Wire the tuning plane into a VerificationService at construction.

    Always describes the ``deequ_service_tuning_*`` series (a disabled
    plane still exports zeros, so dashboards don't gap). With autotune
    enabled: load this substrate's profile if one exists — a corrupt or
    stale profile is already quarantined by the loader and degrades to
    static defaults with a warning, never a failed boot — apply its knob
    values, reseed the router from the (possibly tuned) seeds, and start
    the online controller on the scheduler's harvest tick.
    """
    from ..exceptions import CorruptStateError

    metrics = getattr(service, "metrics", None)
    if metrics is not None:
        TuningController._describe_series(metrics)
    if not knobs.autotune_enabled():
        return None

    profile = None
    try:
        profile = load_profile()
    except CorruptStateError as exc:
        logger.warning(
            "tuning profile rejected (%s); booting on static defaults", exc
        )
    if profile is not None:
        applied = profile.apply(source="profile")
        logger.info(
            "tuning profile %s applied: %d knob(s) tuned for this substrate",
            profile.fingerprint, len(applied),
        )

    router = getattr(getattr(service, "coalescer", None), "router", None)
    controller = TuningController(
        metrics=metrics, router=router, profile=profile
    )
    if metrics is not None:
        controller.register_gauges(metrics)
    if router is not None:
        router.reseed_from_knobs()
    scheduler = getattr(service, "scheduler", None)
    if scheduler is not None and hasattr(scheduler, "add_harvest_listener"):
        scheduler.add_harvest_listener(controller.on_harvest)
    return controller
