"""The tunable-knob registry: ONE audited surface for every routing
constant the hot paths read.

Before this module the crossover thresholds lived as hand-measured
literals scattered through the routing modules (the CrossoverRouter's
seeds in ``service/coalesce.py``, ``probably_low_cardinality``'s probe
sizes and 2M-row floor in ``analyzers/grouping.py``, the fleet sharding
floor, the prefetch depth, the frequency table/buffer capacities) — all
tuned on one CPU dev box and wrong by unknown factors on any other
substrate (ROADMAP item 3). Every one of them is now a registered
:class:`Knob` with

- a **name** (the registry key the calibrator and the online controller
  read/write through),
- an optional **env var** (the operator override; ALWAYS wins, parsed
  with the shared warn-once ``utils.env_number`` semantics the old
  readers used),
- the **static default** (the measured dev-box value the old literal
  carried — bit-for-bit the pre-registry behavior),
- **bounds** the calibrator/controller may never write outside of, and
- a **substrate-sensitivity** flag (whether boot-time calibration is
  expected to move it).

Resolution order of :func:`value`: env override > tuned value (only when
``DEEQU_TPU_AUTOTUNE`` is not "0") > static default. With
``DEEQU_TPU_AUTOTUNE=0`` the tuned layer is invisible and every read is
byte-identical to the pre-registry parser it replaced (pinned by
``tests/test_tuning.py``).

Tuned values enter through :func:`set_tuned` only — boot-time profile
application (``tuning.profile``) and shadow-route-guarded controller
promotions (``tuning.controller``) — and are clamped to the knob's
bounds, so a corrupt profile or a runaway controller can never push a
knob outside its audited range. The invariant linter's
``tuning-registry`` check (tools/statlint) flags any new hand-coded
routing threshold or registry-env read outside this module.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

#: "0" disables the whole self-tuning plane: no profile load, no tuned
#: values, no controller — every knob read is byte-identical to the
#: static-default behavior (the escape hatch, pinned by test)
AUTOTUNE_ENV = "DEEQU_TPU_AUTOTUNE"

#: directory holding persisted per-substrate calibration profiles
#: (default: a ``deequ_tpu_tuning`` directory beside the persistent XLA
#: compile cache, so a box that caches compiles also caches its measured
#: crossovers)
TUNING_PROFILE_DIR_ENV = "DEEQU_TPU_TUNING_PROFILE_DIR"

#: fraction of eligible folds the online controller routes under the
#: CANDIDATE setting while an experiment runs (default 0.05; 0 disables
#: shadow routing — candidates then never gather evidence and are never
#: promoted)
TUNING_SHADOW_FRACTION_ENV = "DEEQU_TPU_TUNING_SHADOW_FRACTION"
DEFAULT_TUNING_SHADOW_FRACTION = 0.05

#: measured folds each arm needs before a promotion/demotion decision
TUNING_MIN_SAMPLES_ENV = "DEEQU_TPU_TUNING_MIN_SAMPLES"
DEFAULT_TUNING_MIN_SAMPLES = 32

#: the bench_diff-style tolerance band: a candidate promotes only when
#: its measured rate beats the incumbent by MORE than this fraction, and
#: a promoted setting demotes back to static when it falls this far
#: below the static reference rate
TUNING_BAND_ENV = "DEEQU_TPU_TUNING_BAND"
DEFAULT_TUNING_BAND = 0.25


def autotune_enabled() -> bool:
    from ..utils import env_flag

    return env_flag(AUTOTUNE_ENV, True)


def shadow_fraction() -> float:
    from ..utils import env_number

    value = env_number(
        TUNING_SHADOW_FRACTION_ENV, DEFAULT_TUNING_SHADOW_FRACTION, float,
        minimum=0.0,
    )
    return min(value, 0.5)  # the incumbent must keep majority traffic


def tuning_min_samples() -> int:
    from ..utils import env_number

    return env_number(
        TUNING_MIN_SAMPLES_ENV, DEFAULT_TUNING_MIN_SAMPLES, int, minimum=1
    )


def tuning_band() -> float:
    from ..utils import env_number

    return env_number(
        TUNING_BAND_ENV, DEFAULT_TUNING_BAND, float, minimum=0.0
    )


@dataclass(frozen=True)
class Knob:
    """One registered tunable: its audit record and parse semantics."""

    name: str                    #: registry key (calibrator/controller id)
    env: Optional[str]           #: operator env override (None = internal)
    static_default: Any          #: the measured dev-box literal it replaced
    cast: Callable               #: int or float
    lo: Any                      #: tuned-value clamp floor
    hi: Any                      #: tuned-value clamp ceiling
    substrate_sensitive: bool    #: does calibration expect to move it?
    description: str             #: what the knob governs (audit surface)
    #: minimum the ENV parser enforces (warn-once + fallback below it);
    #: None = no env-side bound. Kept separate from ``lo`` because the
    #: old readers' env semantics (e.g. fast_path_max_rows accepts -1)
    #: must stay bit-identical.
    env_minimum: Any = None


def _registry() -> Dict[str, Knob]:
    k = Knob
    knobs = [
        # -- streaming fold routing (service/coalesce.py) ------------------
        k("fast_path_max_rows", "DEEQU_TPU_FAST_PATH_MAX_ROWS", -1, int,
          lo=-1, hi=1 << 30, substrate_sensitive=True, env_minimum=-1,
          description=(
              "Fixed host-fast-path row ceiling; -1 = route from the "
              "measured per-analyzer-class crossover, 0 = always device."
          )),
        k("coalesce_max_width", "DEEQU_TPU_COALESCE_MAX_WIDTH", 16, int,
          lo=1, hi=1024, substrate_sensitive=True, env_minimum=1,
          description=(
              "Max sessions stacked into one coalesced device launch "
              "(pow2-bucketed widths)."
          )),
        k("fleet_stream_min_rows", "DEEQU_TPU_FLEET_STREAM_MIN_ROWS",
          65536, int, lo=0, hi=1 << 30, substrate_sensitive=True,
          env_minimum=0,
          description=(
              "Minimum micro-batch rows before a streaming fold shards "
              "over the tenant's fleet sub-mesh."
          )),
        # -- ingest feed pipeline (ingest/prefetch.py) ---------------------
        k("prefetch_depth", "DEEQU_TPU_PREFETCH_DEPTH", 2, int,
          lo=0, hi=64, substrate_sensitive=True, env_minimum=0,
          description=(
              "Staged batches in the double-buffered host->device feed "
              "pipeline (0 = serial inline)."
          )),
        # -- device frequency engine (analyzers/grouping.py) ---------------
        k("freq_table_slots", "DEEQU_TPU_FREQ_TABLE_SLOTS", 1 << 22, int,
          lo=1 << 10, hi=1 << 26, substrate_sensitive=True, env_minimum=1,
          description=(
              "Distinct-group capacity per device frequency table "
              "(pow2-rounded)."
          )),
        k("freq_buffer_entries", "DEEQU_TPU_FREQ_BUFFER_ENTRIES",
          1 << 25, int, lo=1 << 16, hi=1 << 28, substrate_sensitive=True,
          env_minimum=1,
          description=(
              "Raw u64 key-buffer cap; runs fitting it ride the RESIDENT "
              "compaction-free trace."
          )),
        k("device_freq_max_cardinality",
          "DEEQU_TPU_DEVICE_FREQ_MAX_CARDINALITY", 1 << 16, int,
          lo=1 << 8, hi=1 << 22, substrate_sensitive=True, env_minimum=1,
          description=(
              "Dictionary-size ceiling of the dense per-code device "
              "counting path."
          )),
        # -- grouping host-route pre-probe (probably_low_cardinality) ------
        k("freq_host_route_max_distinct",
          "DEEQU_TPU_FREQ_HOST_ROUTE_MAX_DISTINCT", 1 << 15, int,
          lo=1 << 6, hi=1 << 22, substrate_sensitive=True, env_minimum=1,
          description=(
              "Union-distinct ceiling for confidently routing a grouping "
              "set to the host group-by instead of the device table "
              "(~ the measured sweep knee / 4)."
          )),
        k("freq_probe_rows", "DEEQU_TPU_FREQ_PROBE_ROWS", 1 << 16, int,
          lo=1 << 10, hi=1 << 22, substrate_sensitive=False, env_minimum=1,
          description=(
              "Rows per head/mid/tail slice of the cardinality "
              "pre-routing probe."
          )),
        k("freq_host_route_min_rows",
          "DEEQU_TPU_FREQ_HOST_ROUTE_MIN_ROWS", 1 << 21, int,
          lo=0, hi=1 << 30, substrate_sensitive=True, env_minimum=0,
          description=(
              "Row floor below which the probe never answers host: the "
              "engines' absolute cost gap only buys wall-clock at scale "
              "(the dev box measured ~2M rows)."
          )),
        # -- CrossoverRouter seeds (service/coalesce.py; internal: the
        # router EWMAs refine them from live folds, calibration replaces
        # them with measured substrate values) -----------------------------
        k("router_host_rows_per_s", None, 20e6, float,
          lo=1e3, hi=1e12, substrate_sensitive=True,
          description=(
              "Seed host-kernel rows/s per analyzer class before any "
              "fold is measured (seeded LOW deliberately)."
          )),
        k("router_device_fixed_s", None, 0.02, float,
          lo=1e-6, hi=10.0, substrate_sensitive=True,
          description=(
              "Seed fixed seconds per device launch+fetch before any "
              "coalesced launch is measured."
          )),
        k("router_device_rows_per_s", None, 100e6, float,
          lo=1e3, hi=1e13, substrate_sensitive=True,
          description="Seed device per-row throughput of the cost model."),
    ]
    return {knob.name: knob for knob in knobs}


REGISTRY: Dict[str, Knob] = _registry()

#: process-global tuned layer (profile application + controller
#: promotions); guarded — value() reads race controller writes
_TUNED_LOCK = threading.Lock()
_TUNED: Dict[str, Any] = {}
_TUNED_SOURCE: Dict[str, str] = {}


def knob(name: str) -> Knob:
    return REGISTRY[name]


def static_value(name: str) -> Any:
    return REGISTRY[name].static_default


def value(name: str) -> Any:
    """Resolve one knob: env override > tuned (autotune on) > static."""
    from ..utils import env_number

    k = REGISTRY[name]
    fallback = k.static_default
    if autotune_enabled():
        with _TUNED_LOCK:
            tuned = _TUNED.get(name)
        if tuned is not None:
            fallback = tuned
    if k.env is None:
        return fallback
    return env_number(k.env, fallback, k.cast, minimum=k.env_minimum)


def set_tuned(name: str, new_value: Any, source: str = "controller") -> Any:
    """Install a tuned value (clamped to the knob's bounds); returns the
    value actually installed. Raises KeyError for unregistered names —
    profiles carrying unknown knobs skip them with a warning upstream."""
    k = REGISTRY[name]
    clamped = min(max(k.cast(new_value), k.lo), k.hi)
    with _TUNED_LOCK:
        _TUNED[name] = clamped
        _TUNED_SOURCE[name] = source
    return clamped


def clear_tuned(name: Optional[str] = None) -> None:
    """Drop one tuned value (back to static), or all of them."""
    with _TUNED_LOCK:
        if name is None:
            _TUNED.clear()
            _TUNED_SOURCE.clear()
        else:
            _TUNED.pop(name, None)
            _TUNED_SOURCE.pop(name, None)


def any_tuned() -> bool:
    """Cheap per-fold predicate for the controller's hot path."""
    with _TUNED_LOCK:
        return bool(_TUNED)


def tuned_snapshot() -> Dict[str, Dict[str, Any]]:
    """{name: {value, source, static}} for every currently-tuned knob."""
    with _TUNED_LOCK:
        return {
            name: {
                "value": v,
                "source": _TUNED_SOURCE.get(name, "?"),
                "static": REGISTRY[name].static_default,
            }
            for name, v in _TUNED.items()
        }
