"""Versioned, checksummed per-substrate calibration profiles.

A :class:`SubstrateProfile` is what boot-time calibration measured on ONE
substrate — (backend, device kind, chip count, host fingerprint) — and
what a cold process on that same substrate loads at service start so it
boots with measured crossovers instead of the dev-box constants. Profiles
live beside the persistent XLA compile cache (same reasoning: the
expensive thing you computed about THIS box is worth keeping), one JSON
file per substrate fingerprint, so a home directory shared across a
heterogeneous fleet holds one profile per device kind without collisions.

The file carries the payload plus an xxhash64 content checksum
(:mod:`deequ_tpu.integrity`, the same digest every other durable artifact
uses) and a schema version. A profile that fails its checksum, fails to
parse, or carries a different schema version is **quarantined** — moved
to a ``.quarantine/`` sidecar so it can never poison a later boot — and
surfaces as the typed :class:`~deequ_tpu.exceptions.CorruptStateError`
that the data plane already treats as recoverable; the service-start
loader catches it and boots on static defaults. A profile for a
DIFFERENT substrate is simply absent, not corrupt.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..exceptions import CorruptStateError
from ..integrity import checksum_bytes
from . import knobs as _knobs

logger = logging.getLogger(__name__)

#: bump on any incompatible payload change; older files quarantine on load
PROFILE_VERSION = 1


def profile_dir() -> str:
    """Profile directory: ``DEEQU_TPU_TUNING_PROFILE_DIR`` or a
    ``deequ_tpu_tuning`` directory beside the XLA compile cache."""
    from ..utils import env_str

    configured = env_str(_knobs.TUNING_PROFILE_DIR_ENV, "")
    if configured:
        return os.path.expanduser(configured)
    cache = env_str(
        "DEEQU_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/deequ_tpu_xla")
    )
    return os.path.join(os.path.dirname(os.path.expanduser(cache)) or ".",
                        "deequ_tpu_tuning")


def substrate_key() -> Dict[str, Any]:
    """The identity a profile is keyed by. Includes a host hardware
    fingerprint: two CPU-backend boxes with different core counts are
    different substrates (the host fast path runs on those cores)."""
    import platform

    import jax

    devices = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "none",
        "chip_count": len(devices),
        "host": f"{platform.machine()}-{os.cpu_count()}cpu",
    }


def substrate_fingerprint(key: Optional[Dict[str, Any]] = None) -> str:
    payload = json.dumps(key or substrate_key(), sort_keys=True)
    return checksum_bytes(payload.encode("utf-8"))


@dataclass
class SubstrateProfile:
    """One substrate's measured calibration results."""

    substrate: Dict[str, Any]
    #: raw probe measurements (rates in rows/s, costs in seconds) — kept
    #: for the tuning report and for re-deriving knobs offline
    probes: Dict[str, float] = field(default_factory=dict)
    #: derived knob values, name -> value; every name must be registered
    knob_values: Dict[str, Any] = field(default_factory=dict)
    created_at: float = 0.0
    calibration_wall_s: float = 0.0
    version: int = PROFILE_VERSION

    @property
    def fingerprint(self) -> str:
        return substrate_fingerprint(self.substrate)

    def to_payload(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]) -> "SubstrateProfile":
        try:
            profile = cls(**payload)
        except TypeError as exc:
            raise CorruptStateError(
                "tuning profile", "payload",
                f"structurally torn: {exc}",
            ) from exc
        if profile.version != PROFILE_VERSION:
            raise CorruptStateError(
                "tuning profile", "payload",
                f"schema version {profile.version} != {PROFILE_VERSION} "
                "(stale profile from another build)",
            )
        return profile

    def apply(self, source: str = "profile") -> Dict[str, Any]:
        """Install this profile's knob values into the tuned layer
        (clamped to registry bounds). Unknown knob names are skipped with
        a warning — a profile written by a newer build with extra knobs
        must not fail the boot. Returns {name: installed_value}."""
        applied: Dict[str, Any] = {}
        for name, value in self.knob_values.items():
            if name not in _knobs.REGISTRY:
                logger.warning(
                    "tuning profile carries unknown knob %r; skipped", name
                )
                continue
            applied[name] = _knobs.set_tuned(name, value, source=source)
        return applied


def _profile_path(directory: str, fingerprint: str) -> str:
    return os.path.join(directory, f"profile-{fingerprint}.json")


def save_profile(profile: SubstrateProfile,
                 directory: Optional[str] = None) -> str:
    """Atomically persist (tmp + replace) under the substrate fingerprint;
    returns the path written."""
    directory = directory or profile_dir()
    os.makedirs(directory, exist_ok=True)
    if not profile.created_at:
        profile.created_at = time.time()
    payload = profile.to_payload()
    body = json.dumps(payload, sort_keys=True)
    record = {
        "payload": payload,
        "checksum": checksum_bytes(body.encode("utf-8")),
    }
    path = _profile_path(directory, profile.fingerprint)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(record, fh, sort_keys=True, indent=1)
    os.replace(tmp, path)
    return path


def _quarantine(path: str) -> Optional[str]:
    """Move a bad profile into ``.quarantine/`` (content-addressed name so
    repeat offenders don't pile up); best-effort."""
    try:
        with open(path, "rb") as fh:
            digest = checksum_bytes(fh.read())
        qdir = os.path.join(os.path.dirname(path), ".quarantine")
        os.makedirs(qdir, exist_ok=True)
        dest = os.path.join(qdir, f"{digest}-{os.path.basename(path)}")
        os.replace(path, dest)
        return dest
    except OSError:
        return None


def load_profile(directory: Optional[str] = None,
                 fingerprint: Optional[str] = None,
                 ) -> Optional[SubstrateProfile]:
    """Load THIS substrate's profile, verifying the content checksum and
    schema version.

    Returns None when no profile exists for the substrate (normal on a
    fresh box). Raises :class:`CorruptStateError` after quarantining the
    file when it exists but cannot be trusted — the caller decides the
    fallback (the service boots on static defaults).
    """
    directory = directory or profile_dir()
    fingerprint = fingerprint or substrate_fingerprint()
    path = _profile_path(directory, fingerprint)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as fh:
            record = json.load(fh)
        payload = record["payload"]
        stored = record["checksum"]
    except (OSError, ValueError, KeyError, TypeError) as exc:
        dest = _quarantine(path)
        raise CorruptStateError(
            "tuning profile", path,
            f"unreadable ({exc}); quarantined to {dest}",
        ) from exc
    body = json.dumps(payload, sort_keys=True)
    actual = checksum_bytes(body.encode("utf-8"))
    if actual != stored:
        dest = _quarantine(path)
        raise CorruptStateError(
            "tuning profile", path,
            f"failed its content checksum (stored {stored}, computed "
            f"{actual}); quarantined to {dest}",
        )
    try:
        return SubstrateProfile.from_payload(payload)
    except CorruptStateError:
        _quarantine(path)
        raise
