"""Boot-time calibration: micro-probes that measure THIS substrate.

``calibrate()`` reuses the engine's own execution paths as its
measurement harness — the same host-partial kernels, the same warmed
``do_analysis_run`` device dispatch, the same grouping engines the
CrossoverRouter and ``probably_low_cardinality`` route between — and
runs each probe a few times, keeping the **minimum** wall time (the
bench stages' convention: the min is the least-noisy estimate of the
true cost on a busy box). From the raw probe measurements it derives
values for every substrate-sensitive knob in the registry via the same
cost model the router uses, clamps them to the registry bounds, and
persists a checksummed :class:`~deequ_tpu.tuning.profile.SubstrateProfile`
beside the XLA cache.

Probe sizes are deliberately small (the default measures ~1.5M rows
total): calibration runs once per substrate, at boot or from bench's
``calibration`` stage, and must cost seconds — not the minutes a full
sweep costs. The derived values are SEEDS with honest error bars, not
gospel: the online controller refines them under live traffic, and the
shadow-route guardrail catches any probe that mis-measured.

CLI: ``python -m deequ_tpu.tuning.calibrate --json [--no-save] [--dir D]
[--rows N]`` — used by bench.py's detached calibration stage.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from . import knobs as _knobs
from .profile import SubstrateProfile, save_profile, substrate_key

#: default rows for the host-partial rate probes
_HOST_PROBE_ROWS = 1 << 18
#: rows for the small (fixed-cost-dominated) device probe
_DEVICE_SMALL_ROWS = 1 << 12
#: rows for the large (per-row-dominated) device probe
_DEVICE_LARGE_ROWS = 1 << 20
#: distinct groups in the grouping-knee probe datasets
_GROUP_PROBE_CARDINALITY = 1 << 10


def _timed(fn: Callable[[], Any], repeats: int) -> Tuple[float, Any]:
    """Min wall seconds over ``repeats`` calls (after the caller warmed
    any compile), plus the last result."""
    best = float("inf")
    result = None
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        result = fn()
        best = min(best, time.perf_counter() - t0)
    return best, result


def _pow2_at_most(value: float) -> int:
    """Largest power of two <= value (>= 1)."""
    return 1 << max(int(value).bit_length() - 1, 0)


def _probe_dataset(rows: int, cardinality: int = 0):
    from ..data import Dataset

    rng = np.random.default_rng(0xCA11B)
    cols: Dict[str, Any] = {"v": rng.standard_normal(rows)}
    if cardinality:
        cols["k"] = rng.integers(0, cardinality, size=rows)
    return Dataset.from_dict(cols)


def _probe_host_rates(rows: int, repeats: int) -> Dict[str, float]:
    """rows/s of each representative host-partial class on this box's
    cores — the numbers the router's observe_host EWMAs converge to."""
    from ..analyzers import Completeness, Maximum, Mean, Minimum, Sum
    from ..analyzers.base import HostBatchContext

    data = _probe_dataset(rows)
    batch = next(data.batches(rows, pad_to_batch_size=False))
    rates: Dict[str, float] = {}
    for analyzer in (Completeness("v"), Mean("v"), Sum("v"),
                     Minimum("v"), Maximum("v")):
        ctx = HostBatchContext(batch, batch_index=0)
        analyzer.host_partial(ctx)  # warm any lazy column materialization
        seconds, _ = _timed(
            lambda a=analyzer, c=ctx: a.host_partial(c), repeats
        )
        rates[f"host_rows_per_s_{type(analyzer).__name__}"] = (
            rows / max(seconds, 1e-9)
        )
    return rates


def _run_analysis(data, analyzers) -> float:
    from ..runners.analysis_runner import AnalysisRunner

    t0 = time.perf_counter()
    AnalysisRunner.do_analysis_run(data, analyzers)
    return time.perf_counter() - t0


def _probe_device_costs(repeats: int) -> Dict[str, float]:
    """Fixed dispatch seconds (small warm run), per-row rows/s (large warm
    run), and the marginal cost of stacking analyzers into one bundle."""
    from ..analyzers import Maximum, Mean, Minimum, Sum

    small = _probe_dataset(_DEVICE_SMALL_ROWS)
    large = _probe_dataset(_DEVICE_LARGE_ROWS)
    one = [Mean("v")]
    eight = [Mean("v"), Sum("v"), Minimum("v"), Maximum("v"),
             Mean("v", where="v > 0"), Sum("v", where="v > 0"),
             Minimum("v", where="v > 0"), Maximum("v", where="v > 0")]

    _run_analysis(small, one)  # compile warmup
    fixed_s, _ = _timed(lambda: _run_analysis(small, one), repeats)

    _run_analysis(large, one)
    large_s, _ = _timed(lambda: _run_analysis(large, one), repeats)
    per_row_s = max(large_s - fixed_s, 1e-9) / _DEVICE_LARGE_ROWS

    _run_analysis(small, eight)
    stacked_s, _ = _timed(lambda: _run_analysis(small, eight), repeats)
    stack_slope_s = max(stacked_s - fixed_s, 0.0) / (len(eight) - len(one))

    return {
        "device_fixed_s": fixed_s,
        "device_rows_per_s": 1.0 / per_row_s,
        "device_stack_slope_s": stack_slope_s,
    }


def _probe_staging_rate(repeats: int) -> Dict[str, float]:
    """Host->device transfer rows/s of the prefetch staging path."""
    import jax

    rows = _DEVICE_LARGE_ROWS
    host = np.random.default_rng(7).standard_normal(rows).astype(np.float32)

    def stage():
        jax.device_put(host).block_until_ready()

    stage()  # warm transfer machinery
    seconds, _ = _timed(stage, repeats)
    return {"staging_rows_per_s": rows / max(seconds, 1e-9)}


def _probe_grouping_knee(repeats: int) -> Dict[str, float]:
    """rows/s of the device frequency table vs the host group-by on the
    same grouping workload — the knee probably_low_cardinality routes on."""
    import os

    from ..analyzers import Uniqueness

    rows = 1 << 18
    data = _probe_dataset(rows, cardinality=_GROUP_PROBE_CARDINALITY)
    analyzers = [Uniqueness(["k"])]
    env = "DEEQU_TPU_DEVICE_FREQ"
    saved = os.environ.get(env)
    try:
        os.environ.pop(env, None)
        _run_analysis(data, analyzers)
        device_s, _ = _timed(lambda: _run_analysis(data, analyzers), repeats)
        os.environ[env] = "0"
        _run_analysis(data, analyzers)
        host_s, _ = _timed(lambda: _run_analysis(data, analyzers), repeats)
    finally:
        if saved is None:
            os.environ.pop(env, None)
        else:
            os.environ[env] = saved
    return {
        "group_device_rows_per_s": rows / max(device_s, 1e-9),
        "group_host_rows_per_s": rows / max(host_s, 1e-9),
    }


def derive_knobs(probes: Dict[str, float]) -> Dict[str, Any]:
    """Map raw probe measurements to knob values through the router's own
    cost model; every output is clamped to its registry bounds."""
    host_rates = [v for k, v in probes.items()
                  if k.startswith("host_rows_per_s_")]
    host_rate = float(np.median(host_rates)) if host_rates else (
        _knobs.static_value("router_host_rows_per_s"))
    fixed_s = probes.get(
        "device_fixed_s", _knobs.static_value("router_device_fixed_s"))
    device_rate = probes.get(
        "device_rows_per_s", _knobs.static_value("router_device_rows_per_s"))

    derived: Dict[str, Any] = {
        "router_host_rows_per_s": host_rate,
        "router_device_fixed_s": fixed_s,
        "router_device_rows_per_s": device_rate,
    }

    # A fleet shard only pays off once the batch amortizes several fixed
    # dispatches of cross-host merge traffic — sharding splits a DEVICE
    # fold, so the break-even is rows the device chews through in a few
    # fixed costs.
    derived["fleet_stream_min_rows"] = _pow2_at_most(
        max(0.25 * fixed_s * device_rate, 1.0))

    # Stacking stops paying when the marginal bundle cost approaches the
    # fixed dispatch it amortizes; below-resolution slopes keep the static
    # width (the probe cannot justify moving it either way).
    slope = probes.get("device_stack_slope_s", 0.0)
    if slope > 1e-7:
        derived["coalesce_max_width"] = _pow2_at_most(
            max(fixed_s / slope, 1.0))

    # Depth must cover the staging/compute rate gap with one spare slot;
    # a staging path faster than the device needs only the double buffer.
    staging = probes.get("staging_rows_per_s", 0.0)
    if staging > 0:
        derived["prefetch_depth"] = int(
            np.clip(round(device_rate / staging) + 1, 1, 8))

    g_host = probes.get("group_host_rows_per_s", 0.0)
    g_dev = probes.get("group_device_rows_per_s", 0.0)
    if g_host > 0 and g_dev > 0:
        # The host group-by needs this many rows before its rate advantage
        # (or the device's fixed cost) buys back the probe's own cost.
        derived["freq_host_route_min_rows"] = _pow2_at_most(
            max(8.0 * fixed_s * min(g_host, g_dev), 1.0))
        # Scale the distinct ceiling by the measured engine ratio: a box
        # whose host group-by keeps pace with the device can confidently
        # host-route proportionally larger key spaces.
        ratio = np.clip(g_host / g_dev, 0.25, 4.0)
        derived["freq_host_route_max_distinct"] = _pow2_at_most(
            _knobs.static_value("freq_host_route_max_distinct") * ratio)

    for name in list(derived):
        knob = _knobs.REGISTRY[name]
        derived[name] = min(max(knob.cast(derived[name]), knob.lo), knob.hi)
    return derived


def calibrate(save: bool = True,
              profile_dir: Optional[str] = None,
              rows: int = _HOST_PROBE_ROWS,
              repeats: int = 3) -> SubstrateProfile:
    """Run every probe, derive knob values, and (by default) persist the
    substrate profile. Returns the profile; ``profile.knob_values`` is NOT
    applied to the live registry here — that is the loader's decision."""
    from ..observability import trace

    t0 = time.perf_counter()
    probes: Dict[str, float] = {}
    with trace.span("tuning.calibrate", kind="tuning") as span:
        probes.update(_probe_host_rates(rows, repeats))
        probes.update(_probe_device_costs(repeats))
        probes.update(_probe_staging_rate(repeats))
        probes.update(_probe_grouping_knee(repeats))
        profile = SubstrateProfile(
            substrate=substrate_key(),
            probes=probes,
            knob_values=derive_knobs(probes),
            calibration_wall_s=time.perf_counter() - t0,
        )
        span.add_event(
            "calibrated",
            fingerprint=profile.fingerprint,
            wall_s=round(profile.calibration_wall_s, 3),
            knobs=len(profile.knob_values),
        )
        if save:
            path = save_profile(profile, profile_dir)
            span.add_event("profile_saved", path=path)
    return profile


def _main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="Calibrate deequ-tpu's tuning profile for this substrate"
    )
    parser.add_argument("--json", action="store_true",
                        help="print the profile as JSON on stdout")
    parser.add_argument("--no-save", action="store_true",
                        help="measure and print without persisting")
    parser.add_argument("--dir", default=None,
                        help="profile directory (default: beside XLA cache)")
    parser.add_argument("--rows", type=int, default=_HOST_PROBE_ROWS,
                        help="rows per host-partial probe")
    parser.add_argument("--repeats", type=int, default=3,
                        help="probe repeats (min wall time wins)")
    args = parser.parse_args(argv)

    profile = calibrate(save=not args.no_save, profile_dir=args.dir,
                        rows=args.rows, repeats=args.repeats)
    if args.json:
        print(json.dumps({
            "substrate": profile.substrate,
            "fingerprint": profile.fingerprint,
            "probes": profile.probes,
            "knobs": profile.knob_values,
            "wall_s": profile.calibration_wall_s,
        }, sort_keys=True))
    else:
        print(f"calibrated substrate {profile.fingerprint} "
              f"in {profile.calibration_wall_s:.2f}s")
        for name, value in sorted(profile.knob_values.items()):
            print(f"  {name:32s} {value} (static "
                  f"{_knobs.static_value(name)})")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
