"""The online tuning controller: shadow-route experiments with
bench_diff-style promotion bands and a never-below-static floor.

The boot-time profile seeds the knobs; this controller refines them
under LIVE traffic. It never flips a knob on a hunch: every change runs
as an :class:`Experiment` first —

- **shadow mode** (per-fold knobs, e.g. ``fast_path_max_rows``): a small
  deterministic fraction of folds (``DEEQU_TPU_TUNING_SHADOW_FRACTION``)
  is routed under the CANDIDATE setting while the incumbent keeps the
  rest; both arms accumulate measured rows/s EWMAs from the coalescer's
  own timing sites.
- **trial mode** (global knobs whose effect spans folds, e.g.
  ``coalesce_max_width``, ``fleet_stream_min_rows``): the candidate is
  installed tentatively and the global fold-rate EWMA before/after is
  the comparison — reverted immediately if it regresses.

A candidate **promotes** only when its measured rate beats the incumbent
by more than the tolerance band (``DEEQU_TPU_TUNING_BAND``, the same
default tolerance ``tools/bench_diff.py`` gates CI on) after both arms
hold enough samples; anything less — including "inconclusive" — rejects.
Separately, a standing **floor guardrail** remembers the measured rate
under static defaults and demotes any tuned knob whose live rate falls
below that floor, so a mis-tuned controller (or a poisoned profile) can
never hold the system below the static configuration. Every decision
appends to a bounded history, emits a trace event, and bumps the
described ``deequ_service_tuning_*`` export series — the whole loop is
auditable from the export plane (``tools/tuning_report.py``).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import knobs as _knobs

logger = logging.getLogger(__name__)

#: EWMA smoothing for arm rates — matches the CrossoverRouter's alpha so
#: both learners forget at the same horizon
_ALPHA = 0.2

#: decision-history ring size (tuning_report reads it; bounded so a
#: week-long soak cannot grow it without limit)
_MAX_DECISIONS = 256

#: give up on an experiment whose arms never both fill (e.g. traffic
#: stopped) after this many total recorded folds
_MAX_SAMPLES_FACTOR = 20


@dataclass
class ArmStats:
    """Measured rows/s EWMA of one experiment arm."""

    samples: int = 0
    rate_ewma: float = 0.0

    def record(self, rows: int, seconds: float) -> None:
        rate = rows / max(seconds, 1e-9)
        if self.samples == 0:
            self.rate_ewma = rate
        else:
            self.rate_ewma += _ALPHA * (rate - self.rate_ewma)
        self.samples += 1


@dataclass
class Experiment:
    """One candidate setting under evaluation for one knob."""

    knob: str
    candidate: Any
    mode: str                       #: "shadow" | "trial"
    incumbent_value: Any
    source: str = "controller"
    started_at: float = field(default_factory=time.time)
    incumbent: ArmStats = field(default_factory=ArmStats)
    shadow: ArmStats = field(default_factory=ArmStats)
    #: trial mode only: the rate EWMA captured before the tentative flip
    baseline_rate: float = 0.0


class TuningController:
    """Owns experiments, the decision history, and the static floor."""

    def __init__(self, metrics=None, router=None,
                 profile=None) -> None:
        self.metrics = metrics
        self.router = router
        self.profile = profile
        self._lock = threading.Lock()
        self._experiments: Dict[str, Experiment] = {}
        self.decisions: List[Dict[str, Any]] = []
        self._fold_seq = 0
        #: rows/s EWMA of ALL folds under the CURRENT settings
        self._live = ArmStats()
        #: rows/s EWMA last measured while every knob sat at static —
        #: the floor no tuned configuration may drop below
        self._static_rate = 0.0
        self._static_samples = 0
        #: harvest-listener debounce
        self._last_refit = 0.0
        self._refit_interval_s = 5.0
        if metrics is not None:
            self._describe_series(metrics)

    # -- export plane -------------------------------------------------------

    @staticmethod
    def _describe_series(metrics) -> None:
        metrics.describe(
            "deequ_service_tuning_proposals_total",
            "Tuning experiments started (knob candidates proposed by the "
            "profile, the re-fitter, or an operator drill).",
        )
        metrics.describe(
            "deequ_service_tuning_promotions_total",
            "Candidate knob settings promoted after beating the incumbent "
            "beyond the tolerance band on measured shadow/trial traffic.",
        )
        metrics.describe(
            "deequ_service_tuning_demotions_total",
            "Tuned knob settings demoted back toward static defaults — "
            "candidate lost its experiment, or the never-below-static "
            "floor guardrail fired.",
        )
        metrics.describe(
            "deequ_service_tuning_shadow_folds_total",
            "Folds routed under a candidate setting by the shadow-route "
            "experiment arm.",
        )

    def _bump(self, name: str, knob: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, 1.0, knob=knob)

    def register_gauges(self, metrics) -> None:
        metrics.set_gauge_fn(
            "deequ_service_tuning_active_experiments",
            lambda: float(len(self._experiments)),
            "Knob experiments currently gathering shadow/trial evidence.",
        )
        metrics.set_gauge_fn(
            "deequ_service_tuning_tuned_knobs",
            lambda: float(len(_knobs.tuned_snapshot())),
            "Knobs currently holding a tuned (non-static) value.",
        )

    # -- experiment lifecycle ----------------------------------------------

    def propose(self, knob: str, candidate: Any, mode: str = "shadow",
                source: str = "controller") -> bool:
        """Start an experiment for ``knob`` -> ``candidate``. One live
        experiment per knob; a no-op candidate (== current value) or an
        out-of-registry knob is refused. Returns True when started."""
        if knob not in _knobs.REGISTRY:
            return False
        current = _knobs.value(knob)
        k = _knobs.REGISTRY[knob]
        candidate = min(max(k.cast(candidate), k.lo), k.hi)
        if candidate == current:
            return False
        with self._lock:
            if knob in self._experiments:
                return False
            exp = Experiment(knob=knob, candidate=candidate, mode=mode,
                             incumbent_value=current, source=source)
            if mode == "trial":
                exp.baseline_rate = self._live.rate_ewma
                _knobs.set_tuned(knob, candidate, source="trial")
            self._experiments[knob] = exp
        self._bump("deequ_service_tuning_proposals_total", knob)
        self._trace("tuning_proposal", knob=knob, candidate=candidate,
                    incumbent=current, mode=mode, source=source)
        return True

    def choose(self, rows: int) -> Optional[str]:
        """Per-fold arm assignment for a live SHADOW experiment on
        ``fast_path_max_rows``: returns the candidate-routed decision
        ("host"/"device") for shadow folds, None for incumbent folds (the
        caller keeps its own routing). Deterministic fraction — fold
        sequence modulo the shadow period — so replays are replays."""
        with self._lock:
            exp = self._experiments.get("fast_path_max_rows")
            if exp is None or exp.mode != "shadow":
                return None
            self._fold_seq += 1
            fraction = _knobs.shadow_fraction()
            if fraction <= 0.0:
                return None
            period = max(int(round(1.0 / fraction)), 2)
            if self._fold_seq % period:
                return None
        self._bump("deequ_service_tuning_shadow_folds_total",
                   "fast_path_max_rows")
        ceiling = exp.candidate
        if ceiling < 0:
            return None  # candidate says "router decides": not a forced arm
        return "host" if 0 < rows <= ceiling else "device"

    def record(self, rows: int, seconds: float,
               arm: Optional[str] = None) -> None:
        """Feed one measured fold. ``arm`` is the knob name of the shadow
        experiment that forced this fold's route (None = normal fold)."""
        decisions = []
        with self._lock:
            self._live.record(rows, seconds)
            if not _knobs.any_tuned():
                # every knob at static: this IS the floor measurement
                self._static_rate = self._live.rate_ewma
                self._static_samples = self._live.samples
            for name, exp in list(self._experiments.items()):
                if exp.mode == "shadow":
                    (exp.shadow if arm == name else exp.incumbent).record(
                        rows, seconds)
                else:
                    exp.shadow.record(rows, seconds)
                verdict = self._evaluate_locked(exp)
                if verdict is not None:
                    decisions.append(self._conclude_locked(exp, verdict))
        for decision in decisions:
            self._publish(decision)
        self._check_floor()

    def _evaluate_locked(self, exp: Experiment) -> Optional[str]:
        """"promote" / "reject" / None (keep gathering)."""
        need = _knobs.tuning_min_samples()
        band = _knobs.tuning_band()
        if exp.mode == "shadow":
            if exp.shadow.samples >= need and exp.incumbent.samples >= need:
                wins = exp.shadow.rate_ewma > (
                    exp.incumbent.rate_ewma * (1.0 + band))
                return "promote" if wins else "reject"
            total = exp.shadow.samples + exp.incumbent.samples
            if total >= need * _MAX_SAMPLES_FACTOR:
                return "reject"  # starved arm: inconclusive forever
            return None
        # trial mode: candidate already live; compare the global rate
        # against the pre-flip baseline (no baseline -> need a floor
        # measurement first, judged against the static floor)
        if exp.shadow.samples < need:
            return None
        reference = exp.baseline_rate or self._static_rate
        if reference <= 0.0:
            return "reject"  # nothing to beat: refuse to fly blind
        return ("promote" if exp.shadow.rate_ewma
                > reference * (1.0 + band) else "reject")

    def _conclude_locked(self, exp: Experiment, verdict: str
                         ) -> Dict[str, Any]:
        del self._experiments[exp.knob]
        if verdict == "promote":
            installed = _knobs.set_tuned(exp.knob, exp.candidate,
                                         source=exp.source)
        else:
            # shadow candidates never touched the knob; trial candidates
            # are live and must roll back to the incumbent value
            if exp.mode == "trial":
                if exp.incumbent_value == _knobs.static_value(exp.knob):
                    _knobs.clear_tuned(exp.knob)
                else:
                    _knobs.set_tuned(exp.knob, exp.incumbent_value,
                                     source="rollback")
            installed = exp.incumbent_value
        decision = {
            "at": time.time(),
            "knob": exp.knob,
            "verdict": verdict,
            "mode": exp.mode,
            "candidate": exp.candidate,
            "incumbent": exp.incumbent_value,
            "installed": installed,
            "candidate_rate": (exp.shadow.rate_ewma),
            "incumbent_rate": (exp.incumbent.rate_ewma
                               if exp.mode == "shadow"
                               else (exp.baseline_rate or self._static_rate)),
            "source": exp.source,
        }
        self.decisions.append(decision)
        del self.decisions[:-_MAX_DECISIONS]
        return decision

    def _publish(self, decision: Dict[str, Any]) -> None:
        series = ("deequ_service_tuning_promotions_total"
                  if decision["verdict"] == "promote"
                  else "deequ_service_tuning_demotions_total")
        self._bump(series, decision["knob"])
        self._trace("tuning_decision", **{
            k: decision[k] for k in
            ("knob", "verdict", "mode", "candidate", "incumbent",
             "candidate_rate", "incumbent_rate")
        })
        logger.info(
            "tuning %s: %s %s -> %s (candidate %.3g rows/s vs incumbent "
            "%.3g rows/s)", decision["verdict"], decision["knob"],
            decision["incumbent"], decision["installed"],
            decision["candidate_rate"], decision["incumbent_rate"],
        )

    def _check_floor(self) -> None:
        """The never-below-static guardrail: demote every tuned knob when
        the live rate falls below the measured static floor by more than
        the band."""
        if not _knobs.any_tuned():
            return
        band = _knobs.tuning_band()
        need = _knobs.tuning_min_samples()
        with self._lock:
            tuned = _knobs.tuned_snapshot()
            if (not tuned or self._static_samples < need
                    or self._live.samples < self._static_samples + need):
                return
            if self._live.rate_ewma >= self._static_rate * (1.0 - band):
                return
            demoted = sorted(tuned)
            for name in demoted:
                _knobs.clear_tuned(name)
            self._experiments.clear()
            live_rate = self._live.rate_ewma
            floor = self._static_rate
            # the demotion resets the live EWMA's meaning; restart it so
            # the floor can re-arm from fresh static measurements
            self._live = ArmStats()
            decision = {
                "at": time.time(), "knob": ",".join(demoted),
                "verdict": "floor_demotion", "mode": "floor",
                "candidate": None, "incumbent": None, "installed": "static",
                "candidate_rate": live_rate, "incumbent_rate": floor,
                "source": "floor_guardrail",
            }
            self.decisions.append(decision)
            del self.decisions[:-_MAX_DECISIONS]
        for name in demoted:
            self._bump("deequ_service_tuning_demotions_total", name)
        self._trace("tuning_floor_demotion", knobs=",".join(demoted),
                    live_rate=live_rate, static_rate=floor)
        logger.warning(
            "tuning floor guardrail: live rate %.3g rows/s fell below the "
            "static reference %.3g rows/s; demoted %s to static defaults",
            live_rate, floor, ", ".join(demoted),
        )

    # -- scheduler hook -----------------------------------------------------

    def on_harvest(self, *_args, **_kwargs) -> None:
        """Harvest listener: debounced re-fit pass. Auto-proposals are
        gated on having a calibration profile — a profile-less default
        boot stays byte-identical to the static configuration."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_refit < self._refit_interval_s:
                return
            self._last_refit = now
        if self.profile is not None:
            self.refit()

    def refit(self) -> int:
        """Propose experiments for profile knobs the live registry does
        not hold yet (e.g. after a floor demotion cleared them, or a knob
        was never applied). Returns experiments started."""
        if self.profile is None:
            return 0
        started = 0
        tuned = _knobs.tuned_snapshot()
        for name, value in sorted(self.profile.knob_values.items()):
            if name not in _knobs.REGISTRY or name in tuned:
                continue
            if name.startswith("router_"):
                continue  # router seeds re-apply through reseed, not trials
            mode = "shadow" if name == "fast_path_max_rows" else "trial"
            if self.propose(name, value, mode=mode, source="refit"):
                started += 1
        return started

    # -- misc ---------------------------------------------------------------

    def _trace(self, event: str, **attrs: Any) -> None:
        try:
            from ..observability import trace

            trace.add_event(event, **attrs)
        except Exception:  # tracing must never take down the data path
            logger.debug("tuning trace emit failed", exc_info=True)

    def snapshot(self) -> Dict[str, Any]:
        """Controller state for the tuning report / chaos summary."""
        with self._lock:
            return {
                "live_rate_ewma": self._live.rate_ewma,
                "live_samples": self._live.samples,
                "static_rate_ewma": self._static_rate,
                "static_samples": self._static_samples,
                "experiments": {
                    name: {
                        "candidate": exp.candidate,
                        "mode": exp.mode,
                        "incumbent": exp.incumbent_value,
                        "incumbent_rate": exp.incumbent.rate_ewma,
                        "candidate_rate": exp.shadow.rate_ewma,
                        "incumbent_samples": exp.incumbent.samples,
                        "candidate_samples": exp.shadow.samples,
                    }
                    for name, exp in self._experiments.items()
                },
                "decisions": list(self.decisions),
                "tuned": _knobs.tuned_snapshot(),
            }
