"""Metrics repository: keyed history store of analysis results.

``ResultKey(data_set_date, tags)`` identifies one analysis run;
repositories store the full ``AnalyzerContext`` per key and support
tag/time/analyzer-filtered multi-result queries
(reference `repository/MetricsRepository.scala:25-51`,
`repository/MetricsRepositoryMultipleResultsLoader.scala:27-139`).
"""

from __future__ import annotations

import abc
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..analyzers import Analyzer
from ..runners.context import AnalyzerContext


@dataclass(frozen=True)
class ResultKey:
    """(reference `repository/MetricsRepository.scala:51`)."""

    data_set_date: int
    tags: Tuple[Tuple[str, str], ...] = ()

    def __init__(self, data_set_date: Optional[int] = None, tags=None):
        if data_set_date is None:
            data_set_date = ResultKey.current_milli_time()
        object.__setattr__(self, "data_set_date", int(data_set_date))
        if tags is None:
            tags = ()
        if isinstance(tags, dict):
            tags = tuple(sorted(tags.items()))
        object.__setattr__(self, "tags", tuple(tags))

    @property
    def tags_dict(self) -> Dict[str, str]:
        return dict(self.tags)

    @staticmethod
    def current_milli_time() -> int:
        return int(time.time() * 1000)


@dataclass(frozen=True)
class AnalysisResult:
    """(reference `repository/AnalysisResult.scala:25-40`)."""

    result_key: ResultKey
    analyzer_context: AnalyzerContext


class MetricsRepository(abc.ABC):
    """(reference `repository/MetricsRepository.scala:25-43`)."""

    @abc.abstractmethod
    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        ...

    @abc.abstractmethod
    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        ...

    @abc.abstractmethod
    def load(self) -> "MetricsRepositoryMultipleResultsLoader":
        ...


class MetricsRepositoryMultipleResultsLoader(abc.ABC):
    """Query builder over the whole history
    (reference `repository/MetricsRepositoryMultipleResultsLoader.scala`)."""

    def __init__(self):
        self._tag_values: Optional[Dict[str, str]] = None
        self._analyzers: Optional[List[Analyzer]] = None
        self._after: Optional[int] = None
        self._before: Optional[int] = None

    def with_tag_values(self, tag_values: Dict[str, str]):
        self._tag_values = dict(tag_values)
        return self

    def for_analyzers(self, analyzers: Sequence[Analyzer]):
        self._analyzers = list(analyzers)
        return self

    def after(self, date_time: int):
        self._after = date_time
        return self

    def before(self, date_time: int):
        self._before = date_time
        return self

    @abc.abstractmethod
    def _all_results(self) -> List[AnalysisResult]:
        ...

    def get(self) -> List[AnalysisResult]:
        out = []
        for result in self._all_results():
            key = result.result_key
            if self._after is not None and key.data_set_date < self._after:
                continue
            if self._before is not None and key.data_set_date > self._before:
                continue
            if self._tag_values is not None:
                tags = key.tags_dict
                if not all(tags.get(k) == v for k, v in self._tag_values.items()):
                    continue
            context = result.analyzer_context
            if self._analyzers is not None:
                wanted = set(self._analyzers)
                context = AnalyzerContext(
                    {a: m for a, m in context.metric_map.items() if a in wanted}
                )
            out.append(AnalysisResult(key, context))
        return out

    def get_success_metrics_as_records(self, with_tags: Sequence[str] = ()) -> List[dict]:
        """Union of per-result metric records, tags flattened into columns
        (reference `AnalysisResult.getSuccessMetricsAsDataFrame`)."""
        rows = []
        for result in self.get():
            tags = result.result_key.tags_dict
            for rec in result.analyzer_context.success_metrics_as_records():
                row = dict(rec)
                row["dataset_date"] = result.result_key.data_set_date
                for tag in with_tags:
                    row[tag] = tags.get(tag, "")
                rows.append(row)
        return rows

    def get_success_metrics_as_data_frame(self, with_tags: Sequence[str] = ()):
        import pandas as pd

        return pd.DataFrame(self.get_success_metrics_as_records(with_tags))

    def get_success_metrics_as_json(self, with_tags: Sequence[str] = ()) -> str:
        return json.dumps(self.get_success_metrics_as_records(with_tags))


from .memory import InMemoryMetricsRepository  # noqa: E402
from .fs import FileSystemMetricsRepository  # noqa: E402
from .partitioned import (  # noqa: E402
    PartitionedMetricsRepository,
    month_bucket,
)
from .partition_store import (  # noqa: E402
    PartitionManifest,
    PartitionStateStore,
    default_partition_store,
    partition_bucket,
)

__all__ = [
    "AnalysisResult",
    "FileSystemMetricsRepository",
    "InMemoryMetricsRepository",
    "MetricsRepository",
    "MetricsRepositoryMultipleResultsLoader",
    "PartitionManifest",
    "PartitionStateStore",
    "PartitionedMetricsRepository",
    "ResultKey",
    "default_partition_store",
    "month_bucket",
    "partition_bucket",
]
