"""In-memory metrics repository
(reference `repository/memory/InMemoryMetricsRepository.scala`)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..runners.context import AnalyzerContext
from . import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)


class InMemoryMetricsRepository(MetricsRepository):
    def __init__(self):
        self._results: Dict[ResultKey, AnalysisResult] = {}
        self._lock = threading.Lock()

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        # keep only successful metrics, mirroring the reference
        # (`InMemoryMetricsRepository.scala:44-52`)
        successful = AnalyzerContext(
            {a: m for a, m in analyzer_context.metric_map.items() if m.value.is_success}
        )
        with self._lock:
            self._results[result_key] = AnalysisResult(result_key, successful)

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        with self._lock:
            result = self._results.get(result_key)
        return result.analyzer_context if result is not None else None

    def load(self) -> "InMemoryMetricsRepositoryMultipleResultsLoader":
        return InMemoryMetricsRepositoryMultipleResultsLoader(self)

    def _snapshot(self) -> List[AnalysisResult]:
        with self._lock:
            return list(self._results.values())


class InMemoryMetricsRepositoryMultipleResultsLoader(MetricsRepositoryMultipleResultsLoader):
    def __init__(self, repository: InMemoryMetricsRepository):
        super().__init__()
        self._repository = repository

    def _all_results(self) -> List[AnalysisResult]:
        return self._repository._snapshot()
