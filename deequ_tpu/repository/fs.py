"""File-backed metrics repository: the whole history lives in ONE json file;
save = read-all, replace-key, rewrite — simple and atomic enough for metric
histories, exactly the reference's strategy
(reference `repository/fs/FileSystemMetricsRepository.scala:41-57`). The
path may be local or any URI scheme `deequ_tpu.io` supports (``s3://``,
``gs://``, ``memory://``, ...) — the reference reads/writes the same file
through Hadoop `FileSystem` (`io/DfsUtils.scala:24-85`)."""

from __future__ import annotations

from typing import List, Optional

from .. import io as dio
from ..runners.context import AnalyzerContext
from . import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from .serde import deserialize_results, serialize_results


class FileSystemMetricsRepository(MetricsRepository):
    def __init__(self, path: str):
        self.path = path

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        successful = AnalyzerContext(
            {a: m for a, m in analyzer_context.metric_map.items() if m.value.is_success}
        )
        existing = [r for r in self._read_all() if r.result_key != result_key]
        existing.append(AnalysisResult(result_key, successful))
        payload = serialize_results(existing)
        # local: write-rename so a crash mid-write never corrupts the
        # history; object stores: one atomic put
        dio.write_text_atomic(self.path, payload)

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        for result in self._read_all():
            if result.result_key == result_key:
                return result.analyzer_context
        return None

    def load(self) -> "FileSystemMetricsRepositoryMultipleResultsLoader":
        return FileSystemMetricsRepositoryMultipleResultsLoader(self)

    def _read_all(self) -> List[AnalysisResult]:
        if not dio.exists(self.path):
            return []
        with dio.open_file(self.path, "r") as f:
            payload = f.read()
        if not payload.strip():
            return []
        return deserialize_results(payload)


class FileSystemMetricsRepositoryMultipleResultsLoader(MetricsRepositoryMultipleResultsLoader):
    def __init__(self, repository: FileSystemMetricsRepository):
        super().__init__()
        self._repository = repository

    def _all_results(self) -> List[AnalysisResult]:
        return self._repository._read_all()
