"""File-backed metrics repository: the whole history lives in ONE json file;
save = read-all, replace-key, rewrite — simple and atomic enough for metric
histories, exactly the reference's strategy
(reference `repository/fs/FileSystemMetricsRepository.scala:41-57`). The
path may be local or any URI scheme `deequ_tpu.io` supports (``s3://``,
``gs://``, ``memory://``, ...) — the reference reads/writes the same file
through Hadoop `FileSystem` (`io/DfsUtils.scala:24-85`).

Integrity: every entry carries an xxhash64 content checksum
(`serde.serialize_result`); a corrupt entry — flipped byte, torn write,
concurrent-writer shear — is QUARANTINED to a ``<path>.quarantine/``
sidecar and counted, instead of poisoning every query loader over the
history. Corruption never crashes a reader: the remaining entries keep
serving (the same partial-results-are-a-feature stance the analyzer
taxonomy takes)."""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, List, Optional

from .. import io as dio
from ..exceptions import CorruptStateError
from ..runners.context import AnalyzerContext
from . import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from .serde import deserialize_result, serialize_results

_logger = logging.getLogger(__name__)

#: process-wide count of quarantined repository payloads (entries or whole
#: files), for tests and the chaos soak; per-run attribution goes through
#: the repository's optional RunMonitor
_QUARANTINE_LOCK = threading.Lock()
_QUARANTINED_TOTAL = 0


def quarantined_total() -> int:
    with _QUARANTINE_LOCK:
        return _QUARANTINED_TOTAL


def _count_quarantine(n: int = 1) -> None:
    global _QUARANTINED_TOTAL
    with _QUARANTINE_LOCK:
        _QUARANTINED_TOTAL += n


class FileSystemMetricsRepository(MetricsRepository):
    """``monitor`` (a ``RunMonitor``), when given, records quarantines on
    its ``corrupt_quarantined`` counter so a run's artifact shows the
    corruption it survived."""

    def __init__(self, path: str, monitor: Optional[Any] = None):
        self.path = path
        self.monitor = monitor
        #: entries fully deserialized (checksum-verified + metric map
        #: materialized) by this repository's reads — the windowed-load
        #: regression pin: a bounded query must never deserialize entries
        #: outside its [after, before] window, even on this legacy
        #: one-file layout
        self.entries_deserialized = 0
        #: quarantines THIS repository performed (per-instance corruption
        #: attribution — the fleet watch reads this, never the
        #: process-global counter)
        self.quarantines = 0

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        successful = AnalyzerContext(
            {a: m for a, m in analyzer_context.metric_map.items() if m.value.is_success}
        )
        # raise_on_torn_file: QUERIES over a structurally-torn history may
        # serve the empty set (quarantine-and-continue), but a SAVE must
        # not follow by rewriting the file with only the new entry — that
        # would silently erase every entry the torn file still holds.
        # Saving raises typed instead; the operator restores/clears the
        # file (the quarantine sidecar preserves its bytes) and retries.
        existing = [
            r
            # count=False: entries_deserialized is the READ-path windowed
            # pin; the rewrite's own full read must not pollute it
            for r in self._read_all(raise_on_torn_file=True, count=False)
            if r.result_key != result_key
        ]
        existing.append(AnalysisResult(result_key, successful))
        payload = serialize_results(existing)
        # local: write-rename so a crash mid-write never corrupts the
        # history; object stores: one atomic put
        dio.write_text_atomic(self.path, payload)

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        for result in self._read_all():
            if result.result_key == result_key:
                return result.analyzer_context
        return None

    def load(self) -> "FileSystemMetricsRepositoryMultipleResultsLoader":
        return FileSystemMetricsRepositoryMultipleResultsLoader(self)

    # -- quarantine ----------------------------------------------------------

    def _quarantine(self, payload: str, reason: str, kind: str) -> None:
        """Copy a corrupt payload into the ``<path>.quarantine/`` sidecar
        and count it. Sidecar names are CONTENT-ADDRESSED (the payload's
        checksum), so re-reading the same unrepaired corruption for weeks
        rewrites one idempotent file instead of accumulating a timestamped
        copy per read — and concurrent quarantines of one payload land on
        one name. Quarantine is best-effort: failing to WRITE the sidecar
        (read-only store) must not turn a survivable corruption into a
        crash — the payload is still skipped, just not preserved."""
        from ..integrity import checksum_bytes

        side_dir = self.path + ".quarantine"
        name = f"{kind}-{checksum_bytes(payload.encode('utf-8'))}.json"
        try:
            dio.makedirs(side_dir)
            dio.write_text_atomic(dio.join(side_dir, name), payload)
            where = dio.join(side_dir, name)
        except Exception:  # noqa: BLE001 - best-effort preservation
            where = "<unwritable quarantine dir>"
        _count_quarantine()
        self.quarantines += 1
        if self.monitor is not None:
            try:
                self.monitor.bump("corrupt_quarantined")
            except Exception:  # noqa: BLE001 - observability only
                pass
        from ..observability import trace as _trace

        _trace.add_event(
            "repository_quarantined", kind=kind, where=where,
            reason=str(reason)[:200],
        )
        _logger.warning(
            "quarantined corrupt repository %s from %s to %s: %s",
            kind, self.path, where, reason,
        )

    def _read_all(
        self,
        raise_on_torn_file: bool = False,
        after: Optional[int] = None,
        before: Optional[int] = None,
        count: bool = True,
    ) -> List[AnalysisResult]:
        """All entries — or, with ``after``/``before`` bounds, only the
        entries inside the window. Even on this one-file layout a bounded
        query must not pay O(all history) deserialization: the structural
        JSON parse is unavoidable (one file), but each entry's result-key
        date is PEEKED from the raw dict first and out-of-window entries
        are skipped before their checksums verify or their metric maps
        materialize (``entries_deserialized`` pins it). An entry whose key
        cannot even be peeked still deserializes, so the quarantine path
        sees it."""
        from ..reliability.faults import fault_point

        if not dio.exists(self.path):
            return []
        with dio.open_file(self.path, "r") as f:
            payload = f.read()
        if not payload.strip():
            return []
        try:
            # chaos site: an injected "corrupt" fault here stands in for a
            # history file whose bytes rotted between writes — it takes the
            # SAME whole-file quarantine path a torn JSON payload takes
            fault_point("repository_load", tag=self.path)
            entries = json.loads(payload)
        except (ValueError, CorruptStateError) as exc:
            # the file itself is torn (a flip landed on JSON structure):
            # quarantine the whole payload; queries serve an empty history,
            # saves refuse (see ``save``) so valid entries are never
            # rewritten away
            self._quarantine(payload, str(exc), "file")
            if raise_on_torn_file:
                raise CorruptStateError(
                    "metrics-repository file", self.path, str(exc)
                ) from exc
            return []
        results: List[AnalysisResult] = []
        for entry in entries:
            if entry_outside_window(entry, after, before):
                continue
            try:
                if count:
                    self.entries_deserialized += 1
                results.append(deserialize_result(entry, source=self.path))
            except CorruptStateError as exc:
                self._quarantine(
                    json.dumps(entry), str(exc), "entry"
                )
        return results


def entry_outside_window(
    entry: Any, after: Optional[int], before: Optional[int]
) -> bool:
    """Whether a RAW serialized entry's result-key date provably falls
    outside [after, before] (both inclusive, matching the loader's
    filter). Unpeekable entries answer False so they still flow through
    full deserialization — and its quarantine path."""
    if after is None and before is None:
        return False
    try:
        date = int(entry["resultKey"]["dataSetDate"])
    except (KeyError, TypeError, ValueError):
        return False
    if after is not None and date < after:
        return True
    return before is not None and date > before


class FileSystemMetricsRepositoryMultipleResultsLoader(MetricsRepositoryMultipleResultsLoader):
    def __init__(self, repository: FileSystemMetricsRepository):
        super().__init__()
        self._repository = repository

    def _all_results(self) -> List[AnalysisResult]:
        # push the time window down: entries outside [after, before] are
        # skipped BEFORE deserialization (get() re-applies the same filter
        # on the survivors, which is then a no-op)
        return self._repository._read_all(
            after=self._after, before=self._before
        )
