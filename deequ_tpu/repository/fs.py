"""File-backed metrics repository: the whole history lives in ONE json file;
save = read-all, replace-key, rewrite — simple and atomic enough for metric
histories, exactly the reference's strategy
(reference `repository/fs/FileSystemMetricsRepository.scala:41-57`)."""

from __future__ import annotations

import os
import tempfile
from typing import List, Optional

from ..runners.context import AnalyzerContext
from . import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from .serde import deserialize_results, serialize_results


class FileSystemMetricsRepository(MetricsRepository):
    def __init__(self, path: str):
        self.path = path

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        successful = AnalyzerContext(
            {a: m for a, m in analyzer_context.metric_map.items() if m.value.is_success}
        )
        existing = [r for r in self._read_all() if r.result_key != result_key]
        existing.append(AnalysisResult(result_key, successful))
        payload = serialize_results(existing)
        # write-rename so a crash mid-write never corrupts the history
        directory = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        for result in self._read_all():
            if result.result_key == result_key:
                return result.analyzer_context
        return None

    def load(self) -> "FileSystemMetricsRepositoryMultipleResultsLoader":
        return FileSystemMetricsRepositoryMultipleResultsLoader(self)

    def _read_all(self) -> List[AnalysisResult]:
        if not os.path.exists(self.path):
            return []
        with open(self.path) as f:
            payload = f.read()
        if not payload.strip():
            return []
        return deserialize_results(payload)


class FileSystemMetricsRepositoryMultipleResultsLoader(MetricsRepositoryMultipleResultsLoader):
    def __init__(self, repository: FileSystemMetricsRepository):
        super().__init__()
        self._repository = repository

    def _all_results(self) -> List[AnalysisResult]:
        return self._repository._read_all()
