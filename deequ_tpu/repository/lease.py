"""Lease/fence file: single-compactor election for cross-process writers.

`PartitionedMetricsRepository.compact` is safe against concurrent saves
in ONE process (append-first commits + the in-process ``_compact_lock``),
but its docstring has always carried the caveat that cross-PROCESS writers
of one store root need external coordination: two processes compacting one
bucket can each rewrite ``compacted.json`` wholesale, and the loser's
rewrite silently drops entries the winner merged (whose loose files the
winner already removed). This module is that coordination — a filesystem
lease with fencing:

- the lease is ONE JSON file beside the store root (``<root>.lease``)
  holding ``{owner, epoch, acquiredAt, expiresAt}``;
- a FRESH acquire is an atomic create (write-to-temp + ``os.link``, which
  fails if the file exists — the POSIX test-and-set);
- a STALE lease (expiresAt in the past: the holder crashed mid-compaction)
  is taken over by atomic rename (``os.replace``) with ``epoch + 1``,
  then CONFIRMED by re-read — when two takeovers race, the last rename
  wins and the loser sees a foreign (owner, epoch) and backs off;
- the epoch is the FENCE: a compactor re-verifies (and renews) its
  (owner, epoch) immediately before the destructive rewrite, so a holder
  that stalled past its TTL and lost the lease aborts with the bucket's
  loose entries intact instead of clobbering the new holder's merge.

A crash while holding the lease costs at most one TTL of deferred
compaction — saves stay append-only and reads merge loose entries
throughout, so no history is ever unavailable behind the lease.

Leases only exist for LOCAL store roots (the link/rename primitives are
POSIX); remote roots (s3://, gs://, memory://) keep the documented
in-process-only guarantee.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import time
from typing import Optional

_logger = logging.getLogger(__name__)

#: env knob: seconds a compaction lease lives before any other process may
#: take it over (stale-holder recovery bound). Warn-once parser; documented
#: in config.py with the other DEEQU_TPU_CLUSTER_* knobs.
LEASE_TTL_ENV = "DEEQU_TPU_CLUSTER_LEASE_TTL_S"
DEFAULT_LEASE_TTL_S = 30.0


def lease_ttl_s() -> float:
    from ..utils import env_number

    return float(
        env_number(LEASE_TTL_ENV, DEFAULT_LEASE_TTL_S, float, minimum=0.1)
    )


def default_owner_id() -> str:
    """host:pid — unique per live process, stable within it (the lease
    survives re-acquire by the same process across repository objects)."""
    return f"{socket.gethostname()}:{os.getpid()}"


class FileLease:
    """One named lease over a shared directory tree (see module
    docstring). Not thread-safe by itself — callers serialize in-process
    (the repository's ``_compact_lock`` does)."""

    def __init__(
        self,
        path: str,
        owner: Optional[str] = None,
        ttl_s: Optional[float] = None,
    ):
        self.path = str(path)
        self.owner = owner or default_owner_id()
        self.ttl_s = float(ttl_s) if ttl_s is not None else lease_ttl_s()
        #: the epoch of OUR current hold (0 = not holding)
        self.epoch = 0
        #: protocol observability, asserted by the cluster drills
        self.refusals = 0
        self.takeovers = 0

    # -- protocol ------------------------------------------------------------

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path, "r") as fh:
                d = json.load(fh)
            if not isinstance(d, dict) or "owner" not in d:
                return None
            return d
        except (OSError, ValueError):
            # missing file = no holder; a torn lease file reads as stale
            # (it cannot prove a live holder) and is replaced by takeover
            return None

    def _record(self, epoch: int, now: float) -> dict:
        return {
            "owner": self.owner,
            "epoch": int(epoch),
            "acquiredAt": now,
            "expiresAt": now + self.ttl_s,
        }

    def _write_temp(self, record: dict) -> str:
        tmp = f"{self.path}.tmp-{self.owner.replace('/', '_')}-{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(record, fh)
            fh.flush()
            os.fsync(fh.fileno())
        return tmp

    def acquire(self) -> bool:
        """Try to take the lease; True iff WE hold it on return. Never
        blocks: a live foreign holder is a refusal (the caller skips its
        compaction — the entries stay loose and readable)."""
        from ..reliability.faults import fault_point

        # chaos site: an injected fault here stands in for the lease file
        # being unreachable/contended at election time
        fault_point("lease_acquire", tag=self.path)
        now = time.time()
        current = self._read()
        if current is not None:
            if (
                current.get("owner") == self.owner
                and int(current.get("epoch", 0)) == self.epoch
                and self.epoch > 0
            ):
                return self.renew()
            if float(current.get("expiresAt", 0)) > now:
                self.refusals += 1
                return False
        proposed = int(current.get("epoch", 0)) + 1 if current else 1
        tmp = self._write_temp(self._record(proposed, now))
        try:
            if current is None:
                try:
                    os.link(tmp, self.path)  # atomic create: loser raises
                except FileExistsError:
                    self.refusals += 1
                    return False
            else:
                # stale takeover: last rename wins; the confirm below
                # detects a lost race
                os.replace(tmp, self.path)
                tmp = None
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        after = self._read()
        if (
            after is not None
            and after.get("owner") == self.owner
            and int(after.get("epoch", 0)) == proposed
        ):
            self.epoch = proposed
            if current is not None:
                self.takeovers += 1
                _logger.warning(
                    "took over stale compaction lease %s from %s "
                    "(epoch %d -> %d)", self.path,
                    current.get("owner"), proposed - 1, proposed,
                )
            return True
        self.refusals += 1
        self.epoch = 0
        return False

    def held(self) -> bool:
        """Re-read the file: are WE still the live holder at OUR epoch?
        The fence check — run immediately before any destructive step."""
        if self.epoch <= 0:
            return False
        current = self._read()
        return (
            current is not None
            and current.get("owner") == self.owner
            and int(current.get("epoch", 0)) == self.epoch
            and float(current.get("expiresAt", 0)) > time.time()
        )

    def renew(self) -> bool:
        """Extend our hold's TTL (same epoch) iff we still hold it; the
        pre-rewrite fence uses this so the destructive window always
        starts with a fresh TTL."""
        if not self.held():
            self.epoch = 0
            return False
        tmp = self._write_temp(self._record(self.epoch, time.time()))
        os.replace(tmp, self.path)
        return True

    def release(self) -> None:
        """Drop the lease if we hold it (best-effort: a crash without
        release is exactly the stale case takeover recovers)."""
        if self.epoch > 0 and self.held():
            try:
                os.unlink(self.path)
            except OSError:
                pass
        self.epoch = 0
