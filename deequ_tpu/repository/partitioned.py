"""Time-partitioned, compacting metrics repository: the fleet-scale
history store behind the anomaly plane (ROADMAP item 5).

The legacy :class:`~deequ_tpu.repository.fs.FileSystemMetricsRepository`
keeps the WHOLE history in one JSON file — every save rewrites it, every
query parses it, so a year of per-run metrics costs O(all history) per
touch. This layout slots into the :class:`PartitionStateStore` conventions
instead (checksummed entries, quarantine-on-corruption, ``YYYY-MM``
buckets):

- each entry lands in the MONTH BUCKET directory its result key's
  ``data_set_date`` names, so a windowed query walks only the buckets
  intersecting ``[after, before]`` — a year of dailies loads in
  O(queried window), never O(365);
- a save APPENDS one small ``e-<date>-<checksum>.json`` file (no
  whole-history rewrite; 10k tenants saving per harvest stay O(1) each);
  once a bucket accumulates ``compact_threshold`` loose entries they
  COMPACT into the bucket's single ``compacted.json`` array, so steady
  state reads one file + a handful of recent appends per month;
- every entry carries the serde layer's xxhash64 content checksum; a
  corrupt entry/file quarantines content-addressed to
  ``<root>.quarantine/`` and the rest of the history keeps serving (the
  FS repository's stance, kept bucket-local);
- the reference's Gson/JVM metrics-history dialect stays readable as
  input via :meth:`PartitionedMetricsRepository.import_jvm_history`.

The public API is exactly :class:`MetricsRepository` — callers,
``VerificationSuite.use_repository`` and the anomaly wiring see no
difference. ``path`` may be local or any ``deequ_tpu.io`` URI scheme
(``s3://``, ``gs://``, ``memory://``).
"""

from __future__ import annotations

import json
import logging
import threading
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Tuple

from .. import io as dio
from ..exceptions import CorruptStateError
from ..runners.context import AnalyzerContext
from . import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from .fs import _count_quarantine, entry_outside_window
from .serde import deserialize_result, serialize_result

_logger = logging.getLogger(__name__)

_COMPACTED = "compacted.json"

#: loose entry files a bucket may hold before a save compacts it into the
#: bucket's single array file (the append-vs-rewrite crossover: appends
#: keep saves O(1), compaction keeps reads O(files-in-window) bounded)
DEFAULT_COMPACT_THRESHOLD = 64


def month_bucket(date_ms: int) -> str:
    """The ``YYYY-MM`` bucket a result-key date (epoch millis, UTC) lands
    in — the partition-store convention applied to metric history."""
    return datetime.fromtimestamp(
        int(date_ms) / 1000.0, tz=timezone.utc
    ).strftime("%Y-%m")


class PartitionedMetricsRepository(MetricsRepository):
    """See module docstring. ``monitor`` (a ``RunMonitor``), when given,
    records quarantines on its ``corrupt_quarantined`` counter."""

    def __init__(
        self,
        path: str,
        monitor: Optional[Any] = None,
        compact_threshold: int = DEFAULT_COMPACT_THRESHOLD,
    ):
        self.path = str(path)
        self.monitor = monitor
        self.compact_threshold = int(compact_threshold)
        #: entries fully deserialized by reads (the O(window) pin — same
        #: meaning as the FS repository's counter) and buckets walked
        self.entries_deserialized = 0
        self.buckets_walked = 0
        #: quarantines THIS repository performed (the fleet watch keys
        #: per-tenant corruption attribution on this, never the
        #: process-global counter — concurrent quarantines elsewhere must
        #: not read as this history rotting)
        self.quarantines = 0
        #: serializes compactions: two concurrent compact() merges of one
        #: bucket could otherwise each rewrite compacted.json wholesale
        #: and the loser's rewrite would drop entries the winner merged
        #: (and whose loose files the winner already removed). In-process
        #: half of the story; the CROSS-process half is the lease below.
        self._compact_lock = threading.Lock()
        #: cross-process single-compactor election (repository.lease): a
        #: filesystem lease/fence file beside the root. Only local roots
        #: get one (the link/rename primitives are POSIX); remote roots
        #: keep the documented in-process-only guarantee. Reads and
        #: append-only saves never touch the lease — they are safe against
        #: concurrent compactors by the append-first commit protocol.
        self.lease = None
        if dio.is_local(self.path):
            from .lease import FileLease

            self.lease = FileLease(self.path + ".lease")
        dio.makedirs(self.path)

    # -- layout --------------------------------------------------------------

    def _bucket_dir(self, bucket: str) -> str:
        return dio.join(self.path, bucket)

    @staticmethod
    def _entry_name(entry: Dict[str, Any]) -> str:
        import time as _time

        # the zero-padded nanosecond component makes loose filenames sort
        # by RECENCY within a date, so when a replaced entry's removal
        # fails (best-effort path) the NEWER entry still wins the
        # last-wins merge in _read_all/compact
        date = int(entry["resultKey"]["dataSetDate"])
        return (
            f"e-{date}-{_time.time_ns():020d}-"
            f"{entry.get('checksum', '0')}.json"
        )

    # -- MetricsRepository API -----------------------------------------------

    def save(self, result_key: ResultKey, analyzer_context: AnalyzerContext) -> None:
        """APPEND-FIRST save: the single atomic write of the new loose
        entry IS the commit point — a crash at any moment leaves either
        the old history or old + new, never a missing key (replace-key is
        a READ-side rule: queries and compaction merge last-wins per key
        by recency, so the newest entry serves the moment it lands).
        After the commit, older same-key loose entries prune best-effort
        (same-DATE candidates only — a result key includes its date and
        the date is embedded in the filename, so a save reads O(same-date
        entries), never the bucket). The compacted file is not touched;
        stale same-key entries inside it lose the recency merge and drop
        at the next compaction."""
        successful = AnalyzerContext(
            {a: m for a, m in analyzer_context.metric_map.items() if m.value.is_success}
        )
        entry = serialize_result(AnalysisResult(result_key, successful))
        bucket = month_bucket(result_key.data_set_date)
        bucket_dir = self._bucket_dir(bucket)
        dio.makedirs(bucket_dir)
        name = self._entry_name(entry)
        dio.write_text_atomic(
            dio.join(bucket_dir, name), json.dumps(entry)
        )
        key = entry["resultKey"]
        date_prefix = f"e-{int(key['dataSetDate'])}-"
        n_loose = 0
        for other in dio.list_files(bucket_dir):
            if other == _COMPACTED or not other.startswith("e-"):
                continue
            if other != name and other.startswith(date_prefix):
                raw = self._read_loose(bucket, other)
                if raw is not None and raw.get("resultKey") == key:
                    try:
                        dio.remove_file(dio.join(bucket_dir, other))
                        continue
                    except Exception:  # noqa: BLE001 - the new entry
                        # still wins at read time: merges are last-wins
                        # by the recency sequence in the filename
                        _logger.warning(
                            "could not drop replaced entry %s/%s",
                            bucket, other, exc_info=True,
                        )
            n_loose += 1  # includes the entry just written
        if n_loose >= self.compact_threshold:
            try:
                self.compact(bucket)
            except CorruptStateError:
                # the entry above already committed durably; a TORN
                # compacted file refuses ITS rewrite (quarantined, typed
                # on explicit compact()) but must not make an append-only
                # save read as failed — appends stay safe until the
                # operator restores/clears the torn file
                _logger.warning(
                    "bucket %s/%s is torn; save committed loose, "
                    "compaction deferred", self.path, bucket,
                )

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalyzerContext]:
        date = int(result_key.data_set_date)
        for result in self._read_all(after=date, before=date):
            if result.result_key == result_key:
                return result.analyzer_context
        return None

    def load(self) -> "PartitionedMetricsRepositoryLoader":
        return PartitionedMetricsRepositoryLoader(self)

    # -- compaction ----------------------------------------------------------

    @staticmethod
    def _loose_seq(name: str) -> int:
        """The recency sequence embedded in a loose filename; unparseable
        names read as newest (a foreign file should win over a possibly
        stale compacted entry, never silently lose)."""
        import re

        m = re.match(r"^e--?\d+-(\d{20})-", name)
        return int(m.group(1)) if m else 2 ** 63 - 1

    def _merged_bucket_entries(
        self,
        bucket: str,
        raise_on_torn: bool = False,
        consumed_names: Optional[List[str]] = None,
    ) -> List[Tuple[Dict[str, Any], Optional[str], int]]:
        """One bucket's raw entries, deduplicated LAST-WINS per result key
        by RECENCY: compacted entries carry the bucket's ``compactedAtNs``
        stamp, loose entries the sequence in their filename — so a loose
        file that predates the compaction (a merged file whose removal
        failed) can never shadow the newer compacted entry, and vice
        versa. Returns ``(entry, loose filename or None, seq)`` tuples;
        the filename lets readers self-heal corrupt loose entries."""
        bucket_dir = self._bucket_dir(bucket)
        compacted, compacted_at = self._read_compacted(
            bucket, raise_on_torn=raise_on_torn
        )
        items: List[Tuple[Dict[str, Any], Optional[str], int]] = [
            (e, None, compacted_at) for e in compacted
        ]
        for name in dio.list_files(bucket_dir):
            if name == _COMPACTED or not name.startswith("e-"):
                continue
            raw = self._read_loose(bucket, name)
            if raw is not None:
                # consumed == successfully READ and merged: a transient
                # read failure (remote timeout) must leave the file for
                # the next pass, never let compaction delete an unmerged
                # committed entry
                if consumed_names is not None:
                    consumed_names.append(name)
                items.append((raw, name, self._loose_seq(name)))
        out: List[Tuple[Dict[str, Any], Optional[str], int]] = []
        by_key: Dict[str, int] = {}
        for item in items:
            k = json.dumps(item[0].get("resultKey"), sort_keys=True)
            at = by_key.get(k)
            if at is None:
                by_key[k] = len(out)
                out.append(item)
            elif item[2] >= out[at][2]:
                out[at] = item
        return out

    def compact(self, bucket: str) -> int:
        """Merge a bucket's loose entry files into its single
        ``compacted.json`` (recency-stamped wrapper; last-wins per key);
        returns the compacted entry count, or ``-1`` when another
        process's compactor holds the lease (the entries stay loose and
        readable — refusal is never data loss). Checksum-corrupt entries
        quarantine and DROP here — compaction is where standing bit rot
        self-heals instead of re-quarantining on every read. Torn loose
        files quarantine and drop (bytes preserved in the sidecar); a
        torn compacted file refuses the rewrite typed (rewriting would
        erase whatever it still holds)."""
        with self._compact_lock:
            if self.lease is None:
                return self._compact_locked(bucket)
            if not self.lease.acquire():
                _logger.info(
                    "another compactor holds %s; leaving bucket %s loose",
                    self.lease.path, bucket,
                )
                return -1
            try:
                return self._compact_locked(bucket)
            finally:
                self.lease.release()

    def _compact_locked(self, bucket: str) -> int:
        import time as _time

        from ..integrity import checksum_json

        bucket_dir = self._bucket_dir(bucket)
        # remove EXACTLY the loose files the merge consumed: a save
        # landing concurrently must never be deleted unmerged
        removed: List[str] = []
        merged = self._merged_bucket_entries(
            bucket, raise_on_torn=True, consumed_names=removed
        )
        kept: List[Dict[str, Any]] = []
        for entry, name, _ in merged:
            stored = entry.get("checksum")
            if stored is not None and checksum_json(
                {k: v for k, v in entry.items() if k != "checksum"}
            ) != stored:
                if not self._quarantine(
                    dio.join(bucket_dir, name or _COMPACTED),
                    json.dumps(entry), "entry",
                ):
                    # unwritable sidecar: keep the corrupt entry in the
                    # rewrite rather than destroy its only copy; it drops
                    # at the next compaction once quarantine can preserve
                    kept.append(entry)
            else:
                kept.append(entry)
        if self.lease is not None and not self.lease.renew():
            # the FENCE: we stalled past the lease TTL mid-merge and a
            # takeover happened — rewriting compacted.json now could drop
            # entries the new holder merged. Abort with the bucket's loose
            # files untouched (they stay readable; the live holder or a
            # later compaction consumes them).
            _logger.warning(
                "compaction lease lost mid-merge; leaving bucket %s/%s "
                "loose", self.path, bucket,
            )
            return -1
        stamp = _time.time_ns()
        dio.write_text_atomic(
            dio.join(bucket_dir, _COMPACTED),
            json.dumps({"compactedAtNs": stamp, "entries": kept}),
        )
        for name in removed:
            try:
                dio.remove_file(dio.join(bucket_dir, name))
            except Exception:  # noqa: BLE001 - a surviving loose file's
                # seq PREDATES compactedAtNs, so it loses every future
                # merge and drops at the next compaction
                _logger.warning(
                    "could not remove compacted entry %s/%s", bucket, name,
                    exc_info=True,
                )
        return len(kept)

    def compaction_lag(self) -> Dict[str, Any]:
        """How far behind the compactor is: loose (uncompacted) entry
        counts per bucket. ``max_loose`` against ``threshold`` is the ops
        signal — a bucket sitting well past the threshold means the
        compactor cannot win the lease or keeps hitting a torn file
        (the /statusz partition-store section surfaces this)."""
        per_bucket: Dict[str, int] = {}
        for bucket in self.buckets():
            n_loose = sum(
                1 for name in dio.list_files(self._bucket_dir(bucket))
                if name != _COMPACTED and name.startswith("e-")
            )
            per_bucket[bucket] = n_loose
        return {
            "buckets": per_bucket,
            "max_loose": max(per_bucket.values(), default=0),
            "threshold": self.compact_threshold,
        }

    # -- reads ---------------------------------------------------------------

    def buckets(self) -> List[str]:
        return dio.list_dirs(self.path)

    def _window_buckets(
        self, after: Optional[int], before: Optional[int]
    ) -> List[str]:
        lo = month_bucket(after) if after is not None else None
        hi = month_bucket(before) if before is not None else None
        out = []
        for bucket in self.buckets():
            if lo is not None and bucket < lo:
                continue
            if hi is not None and bucket > hi:
                continue
            out.append(bucket)
        return out

    def _read_compacted(
        self, bucket: str, raise_on_torn: bool = False
    ) -> Tuple[List[Dict[str, Any]], int]:
        """``(entries, compactedAtNs)`` of a bucket's compacted file (0
        when never compacted). The payload is a recency-stamped wrapper —
        the stamp is what lets the merge order compacted entries against
        loose files correctly."""
        from ..reliability.faults import fault_point

        path = dio.join(self._bucket_dir(bucket), _COMPACTED)
        payload = None
        if dio.exists(path):
            with dio.open_file(path, "r") as fh:
                payload = fh.read()
        try:
            # chaos site: an injected "corrupt" fault stands in for a
            # bucket whose bytes rotted — the poisoned-history drill's
            # target (same site name as the FS repository: one knob
            # poisons either layout). Probed per BUCKET read, whether or
            # not the bucket has compacted yet.
            fault_point("repository_load", tag=path)
            if payload is None or not payload.strip():
                return [], 0
            doc = json.loads(payload)
            if not (
                isinstance(doc, dict) and isinstance(doc.get("entries"), list)
            ):
                raise ValueError("compacted payload is not a stamped wrapper")
            return doc["entries"], int(doc.get("compactedAtNs", 0))
        except (ValueError, CorruptStateError) as exc:
            self._quarantine(path, payload or "", "bucket")
            if raise_on_torn:
                raise CorruptStateError(
                    "metrics-repository bucket", path, str(exc)
                ) from exc
            return [], 0

    def _read_loose(self, bucket: str, name: str) -> Optional[Dict[str, Any]]:
        path = dio.join(self._bucket_dir(bucket), name)
        try:
            with dio.open_file(path, "r") as fh:
                payload = fh.read()
        except (OSError, FileNotFoundError):
            return None  # racing save/compact removed it
        try:
            entry = json.loads(payload)
            if not isinstance(entry, dict):
                raise ValueError("entry payload is not a JSON object")
            return entry
        except ValueError:
            if self._quarantine(path, payload, "entry-file"):
                # self-heal only once the bytes are safe in the sidecar —
                # an unwritable quarantine dir must not destroy the only
                # forensic copy
                try:
                    dio.remove_file(path)
                except Exception:  # noqa: BLE001 - re-quarantines next read
                    pass
            return None

    def _read_all(
        self, after: Optional[int] = None, before: Optional[int] = None
    ) -> List[AnalysisResult]:
        """Entries inside [after, before] (inclusive, the loader filter),
        walking ONLY the month buckets intersecting the window and
        deserializing only in-window entries — the O(queried window)
        contract. Per-entry checksum failures quarantine that entry and
        the rest keeps serving."""
        results: List[AnalysisResult] = []
        for bucket in self._window_buckets(after, before):
            self.buckets_walked += 1
            bucket_dir = self._bucket_dir(bucket)
            for entry, loose_name, _ in self._merged_bucket_entries(bucket):
                if entry_outside_window(entry, after, before):
                    continue
                # provenance for errors/quarantine names the file that
                # actually held the entry — the rotten loose file's path,
                # not the (possibly intact) compacted.json
                source = dio.join(bucket_dir, loose_name or _COMPACTED)
                try:
                    self.entries_deserialized += 1
                    results.append(deserialize_result(entry, source=source))
                except CorruptStateError as exc:
                    preserved = self._quarantine(
                        source, json.dumps(entry), "entry"
                    )
                    _logger.warning(
                        "skipped corrupt entry in %s: %s", source, exc
                    )
                    if loose_name is not None and preserved:
                        # self-heal: the rotten LOOSE entry's bytes are
                        # safe in the sidecar; dropping the file stops
                        # every later read from re-quarantining it
                        # (compaction does the same for compacted
                        # entries). An unwritable sidecar keeps the file
                        # — never destroy the only forensic copy.
                        try:
                            dio.remove_file(
                                dio.join(bucket_dir, loose_name)
                            )
                        except Exception:  # noqa: BLE001 - re-heals on
                            # a later read or at compaction
                            pass
        return results

    # -- JVM interop ---------------------------------------------------------

    def import_jvm_history(self, payload: str, source: str = "<jvm>") -> int:
        """Read a reference-written (Gson dialect) metrics-history JSON
        payload and save every entry into the partitioned layout; returns
        the entry count. The JVM dialect stays an INPUT format — storage
        is always the checksummed native layout."""
        from ..interop import read_jvm_metrics_history_json

        results = read_jvm_metrics_history_json(payload, source=source)
        for result in results:
            self.save(result.result_key, result.analyzer_context)
        return len(results)

    # -- quarantine ----------------------------------------------------------

    def _quarantine(self, source: str, payload: str, kind: str) -> bool:
        """Content-addressed sidecar copy under ``<root>.quarantine/``
        (idempotent re-quarantine — the FS repository convention);
        best-effort, and counted on the shared process-wide repository
        quarantine counter. Returns whether the bytes were actually
        PRESERVED — self-heal paths must not delete the only copy of a
        corrupt payload when the sidecar is unwritable."""
        from ..integrity import checksum_bytes

        side_dir = self.path + ".quarantine"
        data = payload.encode("utf-8")
        name = f"{kind}-{checksum_bytes(data)}.json"
        preserved = True
        try:
            dio.makedirs(side_dir)
            dio.write_text_atomic(dio.join(side_dir, name), payload)
            where = dio.join(side_dir, name)
        except Exception:  # noqa: BLE001 - best-effort preservation
            where = "<unwritable quarantine dir>"
            preserved = False
        _count_quarantine()
        self.quarantines += 1
        if self.monitor is not None:
            try:
                self.monitor.bump("corrupt_quarantined")
            except Exception:  # noqa: BLE001 - observability only
                pass
        from ..observability import trace as _trace

        _trace.add_event(
            "repository_quarantined", kind=kind, where=where, source=source,
        )
        _logger.warning(
            "quarantined corrupt repository %s from %s to %s",
            kind, source, where,
        )
        return preserved

    def __repr__(self) -> str:
        return f"PartitionedMetricsRepository({self.path!r})"


class PartitionedMetricsRepositoryLoader(MetricsRepositoryMultipleResultsLoader):
    def __init__(self, repository: PartitionedMetricsRepository):
        super().__init__()
        self._repository = repository

    def _all_results(self) -> List[AnalysisResult]:
        # the window pushes down to the bucket walk: out-of-window months
        # are never listed, out-of-window entries never deserialized
        return self._repository._read_all(
            after=self._after, before=self._before
        )
