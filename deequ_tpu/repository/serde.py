"""JSON serde for analysis results — the analyzer <-> JSON name mapping IS
the persistence schema (reference `repository/AnalysisResultSerde.scala`,
whose Gson serializers define the same contract for the JVM).

Only string predicates serialize; callable predicates/binning functions are
rejected (the reference's predicates are always SQL strings).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..analyzers import (
    Analyzer,
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    KLLParameters,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from ..metrics import (
    BucketDistribution,
    BucketValue,
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    HistogramMetric,
    KeyedDoubleMetric,
    KLLMetric,
    Metric,
    Success,
)
from ..runners.context import AnalyzerContext


class SerializationError(ValueError):
    pass


#: Version of the metrics-history JSON layout. Bump on ANY change to the
#: analyzer<->JSON mapping or metric payload shapes; the loader refuses
#: newer versions instead of misreading them. v1 layout is frozen by
#: tests/test_state_serde.py::TestFormatVersioning::test_v1_json_layout_pinned.
SERDE_FORMAT_VERSION = 1


def _ser_where(where) -> Optional[str]:
    if where is None:
        return None
    if isinstance(where, str):
        return where
    raise SerializationError("callable predicates are not serializable")


def serialize_analyzer(analyzer: Analyzer) -> Dict[str, Any]:
    t = type(analyzer).__name__
    d: Dict[str, Any] = {"analyzerName": t}
    if isinstance(analyzer, Size):
        d["where"] = _ser_where(analyzer.where)
    elif isinstance(analyzer, (Completeness, Minimum, Maximum, Mean, Sum,
                               StandardDeviation, MinLength, MaxLength,
                               ApproxCountDistinct, DataType)):
        d["column"] = analyzer.column
        d["where"] = _ser_where(analyzer.where)
    elif isinstance(analyzer, Compliance):
        d["instance"] = analyzer.instance_name
        d["predicate"] = _ser_where(analyzer.predicate)
        d["where"] = _ser_where(analyzer.where)
    elif isinstance(analyzer, PatternMatch):
        d["column"] = analyzer.column
        d["pattern"] = analyzer.pattern
        d["where"] = _ser_where(analyzer.where)
    elif isinstance(analyzer, Correlation):
        d["firstColumn"] = analyzer.first_column
        d["secondColumn"] = analyzer.second_column
        d["where"] = _ser_where(analyzer.where)
    elif isinstance(analyzer, ApproxQuantile):
        d["column"] = analyzer.column
        d["quantile"] = analyzer.quantile
        d["relativeError"] = analyzer.relative_error
        d["where"] = _ser_where(analyzer.where)
    elif isinstance(analyzer, ApproxQuantiles):
        d["column"] = analyzer.column
        d["quantiles"] = list(analyzer.quantiles)
        d["relativeError"] = analyzer.relative_error
        d["where"] = _ser_where(analyzer.where)
    elif isinstance(analyzer, KLLSketch):
        d["column"] = analyzer.column
        d["where"] = _ser_where(analyzer.where)
        p = analyzer.kll_parameters
        d["kllParameters"] = (
            None
            if p is None
            else {
                "sketchSize": p.sketch_size,
                "shrinkingFactor": p.shrinking_factor,
                "numberOfBuckets": p.number_of_buckets,
            }
        )
    elif isinstance(analyzer, (Uniqueness, Distinctness, UniqueValueRatio,
                               CountDistinct, MutualInformation, Entropy)):
        d["columns"] = list(analyzer.columns)
    elif isinstance(analyzer, Histogram):
        if analyzer.binning_func is not None:
            raise SerializationError("Histogram with binning function is not serializable")
        d["column"] = analyzer.column
        d["maxDetailBins"] = analyzer.max_detail_bins
    else:
        raise SerializationError(f"Unable to serialize analyzer {analyzer}")
    return d


def deserialize_analyzer(d: Dict[str, Any]) -> Analyzer:
    name = d["analyzerName"]
    where = d.get("where")
    if name == "Size":
        return Size(where=where)
    if name in ("Completeness", "Minimum", "Maximum", "Mean", "Sum",
                "StandardDeviation", "MinLength", "MaxLength",
                "ApproxCountDistinct", "DataType"):
        cls = {
            "Completeness": Completeness, "Minimum": Minimum, "Maximum": Maximum,
            "Mean": Mean, "Sum": Sum, "StandardDeviation": StandardDeviation,
            "MinLength": MinLength, "MaxLength": MaxLength,
            "ApproxCountDistinct": ApproxCountDistinct, "DataType": DataType,
        }[name]
        return cls(d["column"], where)
    if name == "Compliance":
        return Compliance(d["instance"], d["predicate"], where)
    if name == "PatternMatch":
        return PatternMatch(d["column"], d["pattern"], where)
    if name == "Correlation":
        return Correlation(d["firstColumn"], d["secondColumn"], where)
    if name == "ApproxQuantile":
        return ApproxQuantile(d["column"], d["quantile"], d["relativeError"], where)
    if name == "ApproxQuantiles":
        return ApproxQuantiles(d["column"], tuple(d["quantiles"]), d["relativeError"], where=where)
    if name == "KLLSketch":
        p = d.get("kllParameters")
        params = (
            None
            if p is None
            else KLLParameters(p["sketchSize"], p["shrinkingFactor"], p["numberOfBuckets"])
        )
        return KLLSketch(d["column"], params, where)
    if name in ("Uniqueness", "Distinctness", "UniqueValueRatio", "CountDistinct",
                "MutualInformation", "Entropy"):
        cls = {
            "Uniqueness": Uniqueness, "Distinctness": Distinctness,
            "UniqueValueRatio": UniqueValueRatio, "CountDistinct": CountDistinct,
            "MutualInformation": MutualInformation, "Entropy": Entropy,
        }[name]
        return cls(tuple(d["columns"]))
    if name == "Histogram":
        return Histogram(d["column"], None, d["maxDetailBins"])
    raise SerializationError(f"Unable to deserialize analyzer {name}")


def serialize_metric(metric: Metric) -> Dict[str, Any]:
    base = {
        "entity": metric.entity.value,
        "instance": metric.instance,
        "name": metric.name,
    }
    if metric.value.is_failure:
        # failed metrics round-trip as failures (the reference persists only
        # successful runs in practice; we keep the error string)
        base["metricName"] = "DoubleMetric"
        base["error"] = str(metric.value.exception)
        return base
    value = metric.value.get()
    if isinstance(metric, HistogramMetric):
        base["metricName"] = "HistogramMetric"
        base["column"] = metric.column
        base["numberOfBins"] = value.number_of_bins
        base["values"] = {
            k: {"absolute": v.absolute, "ratio": v.ratio} for k, v in value.values.items()
        }
    elif isinstance(metric, KLLMetric):
        base["metricName"] = "KLLMetric"
        base["buckets"] = [
            {"lowValue": b.low_value, "highValue": b.high_value, "count": b.count}
            for b in value.buckets
        ]
        base["parameters"] = list(value.parameters)
        base["data"] = [list(level) for level in value.data]
    elif isinstance(metric, KeyedDoubleMetric):
        base["metricName"] = "KeyedDoubleMetric"
        base["value"] = dict(value)
    else:
        base["metricName"] = "DoubleMetric"
        base["value"] = float(value)
    return base


def deserialize_metric(d: Dict[str, Any]) -> Metric:
    entity = Entity(d["entity"])
    instance = d["instance"]
    name = d["name"]
    if "error" in d:
        from ..exceptions import MetricCalculationRuntimeException
        from ..metrics import Failure

        return DoubleMetric(
            entity, name, instance, Failure(MetricCalculationRuntimeException(d["error"]))
        )
    kind = d["metricName"]
    if kind == "HistogramMetric":
        dist = Distribution(
            {
                k: DistributionValue(int(v["absolute"]), float(v["ratio"]))
                for k, v in d["values"].items()
            },
            number_of_bins=d["numberOfBins"],
        )
        return HistogramMetric(entity, name, instance, Success(dist), d.get("column", instance))
    if kind == "KLLMetric":
        dist = BucketDistribution(
            [BucketValue(b["lowValue"], b["highValue"], int(b["count"])) for b in d["buckets"]],
            list(d["parameters"]),
            [list(level) for level in d["data"]],
        )
        return KLLMetric(entity, name, instance, Success(dist))
    if kind == "KeyedDoubleMetric":
        return KeyedDoubleMetric(entity, name, instance, Success(dict(d["value"])))
    return DoubleMetric(entity, name, instance, Success(float(d["value"])))


def serialize_result(result) -> Dict[str, Any]:
    from . import AnalysisResult

    assert isinstance(result, AnalysisResult)
    pairs = []
    for analyzer, metric in result.analyzer_context.metric_map.items():
        try:
            pairs.append(
                {"analyzer": serialize_analyzer(analyzer), "metric": serialize_metric(metric)}
            )
        except SerializationError:
            continue  # skip non-serializable analyzers, keep the rest
    payload = {
        "formatVersion": SERDE_FORMAT_VERSION,
        "resultKey": {
            "dataSetDate": result.result_key.data_set_date,
            "tags": result.result_key.tags_dict,
        },
        "analyzerContext": {"metricMap": pairs},
    }
    # per-ENTRY content checksum over the canonical JSON of everything
    # above: one flipped byte in one entry fails exactly that entry's
    # verification, so the loader can quarantine it and keep serving the
    # rest of the history (a whole-file checksum would poison every query)
    from ..integrity import checksum_json

    payload["checksum"] = checksum_json(
        {k: v for k, v in payload.items() if k != "checksum"}
    )
    return payload


def deserialize_result(d: Dict[str, Any], *, source: str = "<memory>"):
    from . import AnalysisResult, ResultKey
    from ..exceptions import CorruptStateError

    # payloads from before versioning (round <=3) carry no marker and ARE
    # the v1 layout; anything newer than this build understands is refused
    version = int(d.get("formatVersion", 1))
    if version > SERDE_FORMAT_VERSION or version < 1:
        from ..exceptions import UnsupportedFormatVersionError

        raise UnsupportedFormatVersionError(
            "metrics-history JSON", version, SERDE_FORMAT_VERSION
        )
    if "checksum" in d:
        from ..integrity import verify_json_checksum

        verify_json_checksum(
            {k: v for k, v in d.items() if k != "checksum"},
            d["checksum"], "metrics-repository entry", source,
        )
    else:
        from ..integrity import warn_once_unchecksummed

        warn_once_unchecksummed("metrics-repository entry", source)
    try:
        key = ResultKey(d["resultKey"]["dataSetDate"], d["resultKey"].get("tags", {}))
        metric_map = {}
        for pair in d["analyzerContext"]["metricMap"]:
            analyzer = deserialize_analyzer(pair["analyzer"])
            metric_map[analyzer] = deserialize_metric(pair["metric"])
    except (KeyError, TypeError, ValueError) as exc:
        # a structurally-torn entry that somehow kept a valid checksum (or
        # never had one) still surfaces as the one typed error the
        # quarantine path keys on, not a shape-dependent crash
        raise CorruptStateError(
            "metrics-repository entry", source, str(exc)
        ) from exc
    return AnalysisResult(key, AnalyzerContext(metric_map))


def serialize_results(results: List) -> str:
    return json.dumps([serialize_result(r) for r in results])


def deserialize_results(payload: str) -> List:
    return [deserialize_result(d) for d in json.loads(payload)]
