"""Partition-keyed algebraic state store: the durable half of incremental
verification (ROADMAP item 4; reference ``StateProvider.scala`` +
``AnalysisRunner.runOnAggregatedStates`` — SURVEY L3/L4).

A :class:`PartitionStateStore` holds, per ``(dataset, partition)``, the
per-analyzer algebraic states one scan of that partition produced, plus a
checksummed manifest recording what those states are states OF:

- the **schema-contract fingerprint** the battery ran under (column
  names + kinds): states folded under a different schema must never merge
  with these, so a fingerprint mismatch invalidates the partition;
- the partition's **content checksum** (a caller-supplied version token —
  file etag, snapshot id — or a digest computed from the materialized
  payload): a mismatch means the partition's bytes changed and its stored
  states are stale;
- the **analyzer keys** covered: a battery that grew since the partition
  was scanned cannot be served from a store that lacks the new analyzer's
  state (a silent ``None`` would undercount the merge), so coverage is
  checked per query;
- the partition's **row count** and schema (so a fully-reused plan knows
  its totals and schema with zero data touched).

State blobs ride the EXISTING checksummed v2 ``.npz`` / parquet path
(:class:`~deequ_tpu.analyzers.state_provider.FileSystemStateProvider` per
partition directory), so integrity semantics — verified checksums, typed
:class:`~deequ_tpu.exceptions.CorruptStateError`, no pickle — are
inherited, not re-implemented. A corrupt manifest or blob QUARANTINES to a
content-addressed ``<dir>.quarantine/`` sidecar (the FS repository's
convention) and surfaces typed; the delta planner answers by re-scanning
exactly that partition.

Directory layout is TIME-PARTITIONED: partitions whose names start with a
``YYYY-MM`` date land under a month bucket directory, everything else
under a stable hash bucket — so listing a queried window over a year of
daily partitions walks O(months in window) directories, not O(365)
(the compacting-layout direction of ROADMAP item 5, applied here first).

``path`` may be local or any URI scheme `deequ_tpu.io` supports
(``s3://``, ``gs://``, ``memory://``), exactly like the state provider.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import io as dio
from ..analyzers.state_provider import (
    FileSystemStateProvider,
    StateLoader,
    _sanitize_namespace_part,
)
from ..exceptions import CorruptStateError

_logger = logging.getLogger(__name__)

#: manifest layout version; the loader refuses newer versions instead of
#: misreading them (the state-serde convention)
PARTITION_MANIFEST_VERSION = 1

#: env var: root path (local or URI) of the service's default partition
#: store. Unset = the service has no partition store (sessions don't
#: flush, verify_partitioned requires an explicit store).
PARTITION_STORE_ENV = "DEEQU_TPU_PARTITION_STORE"

#: env var: default listing window in MONTH BUCKETS for
#: ``list_partitions`` calls with no explicit window (0 = unlimited).
#: Date-named partitions outside the most recent N month buckets are not
#: walked — a year of daily partitions lists in O(window), the
#: time-partitioned layout's whole point. Non-date (hash-bucket)
#: partitions are always listed. Warn-and-fallback convention: an
#: unparseable value warns once and keeps the default.
PARTITION_WINDOW_ENV = "DEEQU_TPU_PARTITION_WINDOW_MONTHS"


def partition_window_months() -> int:
    from ..utils import env_number

    return env_number(PARTITION_WINDOW_ENV, 0, int, minimum=0)


def default_partition_store(monitor: Optional[Any] = None):
    """The process-default store from ``DEEQU_TPU_PARTITION_STORE``, or
    None when the env var is unset."""
    from ..utils import env_str

    path = env_str(PARTITION_STORE_ENV)
    if not path:
        return None
    return PartitionStateStore(path, monitor=monitor)

_MANIFEST = "partition-manifest.json"

#: partition names starting with a YYYY-MM(-DD...) date bucket by month
_DATE_BUCKET_RE = re.compile(r"^(\d{4})-(\d{2})(?:\b|[-T_])")

#: process-wide count of quarantined partition payloads, for tests and the
#: chaos soak (the FS repository keeps the analogous counter for entries)
_QUARANTINE_LOCK = threading.Lock()
_QUARANTINED_TOTAL = 0


def partition_quarantined_total() -> int:
    with _QUARANTINE_LOCK:
        return _QUARANTINED_TOTAL


def _count_quarantine(n: int = 1) -> None:
    global _QUARANTINED_TOTAL
    with _QUARANTINE_LOCK:
        _QUARANTINED_TOTAL += n


def partition_bucket(partition: str) -> str:
    """The time bucket a partition lists under: ``YYYY-MM`` for
    date-named partitions (a year of dailies lists in O(queried months)),
    else a stable 2-hex-char hash bucket (bounded fanout for arbitrary
    names)."""
    m = _DATE_BUCKET_RE.match(partition)
    if m:
        return f"{m.group(1)}-{m.group(2)}"
    from ..integrity import checksum_bytes

    return "x" + checksum_bytes(partition.encode("utf-8"))[:2]


@dataclass(frozen=True)
class PartitionManifest:
    """One committed partition's verified manifest."""

    dataset: str
    partition: str
    fingerprint: str
    content_checksum: Optional[str]
    num_rows: int
    analyzer_keys: Tuple[str, ...]
    schema: Tuple[Tuple[str, str], ...]  # ((name, kind), ...)
    created_at_ms: int

    def covers(self, analyzer_keys: Sequence[str]) -> bool:
        """Whether this partition's stored states cover every analyzer in
        ``analyzer_keys`` (a battery that grew needs a re-scan; one that
        shrank reuses the superset)."""
        have = set(self.analyzer_keys)
        return all(k in have for k in analyzer_keys)


@dataclass(frozen=True)
class RollupManifest:
    """What the persisted rollup states fold (see the rollup section of
    :class:`PartitionStateStore`)."""

    dataset: str
    fingerprint: str
    analyzer_keys: Tuple[str, ...]
    #: ordered (partition, content-checksum) pairs the rollup folds
    folded: Tuple[Tuple[str, Optional[str]], ...]
    num_rows: int

    def covers(self, analyzer_keys: Sequence[str]) -> bool:
        have = set(self.analyzer_keys)
        return all(k in have for k in analyzer_keys)


class PartitionStateStore:
    """Per-(dataset, partition) algebraic state store; see module
    docstring. ``monitor`` (a ``RunMonitor``), when given, records
    quarantines on its ``corrupt_quarantined`` counter."""

    def __init__(self, path: str, monitor: Optional[Any] = None):
        self.path = str(path)
        self.monitor = monitor
        dio.makedirs(self.path)

    # -- paths ---------------------------------------------------------------

    def _partition_dir(self, dataset: str, partition: str) -> str:
        return dio.join(
            self.path,
            "ds-" + _sanitize_namespace_part(str(dataset)),
            partition_bucket(str(partition)),
            "p-" + _sanitize_namespace_part(str(partition)),
        )

    def provider(self, dataset: str, partition: str) -> FileSystemStateProvider:
        """The partition's state provider (the checksummed v2 .npz /
        parquet path): scans persist through it, merges load through it."""
        return FileSystemStateProvider(self._partition_dir(dataset, partition))

    def loader(self, dataset: str, partition: str) -> StateLoader:
        """Read-side alias of :meth:`provider` (the delta planner hands
        these to the aggregated-states merge)."""
        return self.provider(dataset, partition)

    # -- manifest lifecycle --------------------------------------------------

    def commit(
        self,
        dataset: str,
        partition: str,
        *,
        fingerprint: str,
        content_checksum: Optional[str],
        num_rows: int,
        analyzer_keys: Sequence[str],
        schema: Optional[Sequence[Tuple[str, str]]] = None,
        created_at_ms: Optional[int] = None,
    ) -> PartitionManifest:
        """Write the partition's manifest — called AFTER its state blobs
        persisted, so a crash mid-scan leaves no manifest and the next
        plan simply re-scans (the invalidate-first checkpoint
        convention)."""
        manifest = PartitionManifest(
            dataset=str(dataset),
            partition=str(partition),
            fingerprint=str(fingerprint),
            content_checksum=(
                None if content_checksum is None else str(content_checksum)
            ),
            num_rows=int(num_rows),
            analyzer_keys=tuple(str(k) for k in analyzer_keys),
            schema=tuple(
                (str(n), str(k)) for n, k in (schema or ())
            ),
            created_at_ms=(
                int(created_at_ms)
                if created_at_ms is not None
                else int(time.time() * 1000)
            ),
        )
        d: Dict[str, Any] = {
            "formatVersion": PARTITION_MANIFEST_VERSION,
            "dataset": manifest.dataset,
            "partition": manifest.partition,
            "fingerprint": manifest.fingerprint,
            "contentChecksum": manifest.content_checksum,
            "numRows": manifest.num_rows,
            "analyzerKeys": list(manifest.analyzer_keys),
            "schema": [[n, k] for n, k in manifest.schema],
            "createdAtMs": manifest.created_at_ms,
        }
        from ..integrity import checksum_json

        d["checksum"] = checksum_json(d)
        part_dir = self._partition_dir(dataset, partition)
        dio.makedirs(part_dir)
        dio.write_text_atomic(dio.join(part_dir, _MANIFEST), json.dumps(d))
        return manifest

    def invalidate(self, dataset: str, partition: str) -> None:
        """Drop the partition's manifest (its blobs stay until the re-scan
        overwrites them): the invalidate-FIRST half of a changed-partition
        re-scan, so a crash between invalidation and the new commit costs
        a re-scan, never a half-new half-old merge."""
        path = dio.join(self._partition_dir(dataset, partition), _MANIFEST)
        if dio.exists(path):
            try:
                self._remove_file(path)
            except Exception:  # noqa: BLE001 - best effort; a manifest
                # that survives is re-checked (and re-invalidated) by the
                # next plan
                _logger.warning(
                    "could not invalidate partition manifest %s", path,
                    exc_info=True,
                )

    @staticmethod
    def _remove_file(path: str) -> None:
        dio.remove_file(path)

    def get(
        self, dataset: str, partition: str
    ) -> Optional[PartitionManifest]:
        """The partition's verified manifest, or None when it was never
        committed (or was invalidated). A corrupt manifest — torn write,
        flipped byte, unparseable JSON — QUARANTINES and raises the typed
        :class:`CorruptStateError` the recovery layers key on (the delta
        planner answers by re-scanning the partition)."""
        from ..reliability.faults import fault_point

        path = dio.join(self._partition_dir(dataset, partition), _MANIFEST)
        # chaos site: an injected "corrupt" fault here stands in for a
        # manifest whose bytes rotted after it was committed
        fault_point("partition_store_load", tag=f"{dataset}/{partition}")
        if not dio.exists(path):
            return None
        with dio.open_file(path, "r") as fh:
            payload = fh.read()
        try:
            d = json.loads(payload)
            version = int(d.get("formatVersion", 1))
            if version > PARTITION_MANIFEST_VERSION or version < 1:
                from ..exceptions import UnsupportedFormatVersionError

                raise UnsupportedFormatVersionError(
                    "partition manifest", version, PARTITION_MANIFEST_VERSION
                )
            from ..integrity import verify_json_checksum

            verify_json_checksum(
                {k: v for k, v in d.items() if k != "checksum"},
                d.get("checksum", ""), "partition manifest", path,
            )
            return PartitionManifest(
                dataset=str(d["dataset"]),
                partition=str(d["partition"]),
                fingerprint=str(d["fingerprint"]),
                content_checksum=(
                    None if d.get("contentChecksum") is None
                    else str(d["contentChecksum"])
                ),
                num_rows=int(d["numRows"]),
                analyzer_keys=tuple(d["analyzerKeys"]),
                schema=tuple((n, k) for n, k in d.get("schema", [])),
                created_at_ms=int(d.get("createdAtMs", 0)),
            )
        except CorruptStateError:
            self._quarantine(path, payload, "checksum mismatch")
            raise
        except Exception as exc:  # noqa: BLE001 - torn JSON = corrupt
            from ..exceptions import UnsupportedFormatVersionError

            if isinstance(exc, UnsupportedFormatVersionError):
                # a NEWER manifest is refused, not quarantined: treating
                # it as corrupt would re-scan and OVERWRITE a store a
                # newer build owns (the state-serde refusal convention)
                raise
            self._quarantine(path, payload, str(exc))
            raise CorruptStateError(
                "partition manifest", path, str(exc)
            ) from exc

    def quarantine_states(self, dataset: str, partition: str, reason: str) -> None:
        """A stored state BLOB of this partition failed its load (torn
        .npz, checksum trip): preserve the partition's payload files in
        the quarantine sidecar and invalidate the manifest, so the next
        plan re-scans instead of re-tripping (the repository's
        quarantine-and-keep-serving stance applied per partition)."""
        part_dir = self._partition_dir(dataset, partition)
        try:
            import os

            if dio.is_local(part_dir) and os.path.isdir(part_dir):
                for name in sorted(os.listdir(part_dir)):
                    src = os.path.join(part_dir, name)
                    if os.path.isfile(src):
                        with open(src, "rb") as fh:
                            self._quarantine_bytes(src, fh.read(), reason)
        except Exception:  # noqa: BLE001 - preservation is best-effort
            _logger.warning(
                "could not quarantine partition payload %s", part_dir,
                exc_info=True,
            )
        self.invalidate(dataset, partition)
        _count_quarantine()
        if self.monitor is not None:
            try:
                self.monitor.bump("corrupt_quarantined")
            except Exception:  # noqa: BLE001 - observability only
                pass
        from ..observability import trace as _trace

        _trace.add_event(
            "partition_quarantined", dataset=str(dataset),
            partition=str(partition), reason=str(reason)[:200],
        )
        _logger.warning(
            "quarantined corrupt partition %s/%s: %s",
            dataset, partition, reason,
        )

    def _quarantine(self, source: str, payload: str, reason: str) -> None:
        self._quarantine_bytes(source, payload.encode("utf-8"), reason)
        _count_quarantine()
        if self.monitor is not None:
            try:
                self.monitor.bump("corrupt_quarantined")
            except Exception:  # noqa: BLE001 - observability only
                pass
        _logger.warning(
            "quarantined corrupt partition manifest %s: %s", source, reason
        )

    def _quarantine_bytes(self, source: str, payload: bytes, reason: str) -> None:
        """Content-addressed sidecar copy (idempotent re-quarantine, the
        FS repository convention); best-effort — an unwritable store must
        not turn a survivable corruption into a crash."""
        from ..integrity import checksum_bytes

        side_dir = self.path + ".quarantine"
        import os

        name = (
            f"{os.path.basename(source)}-{checksum_bytes(payload)}"
        )
        try:
            dio.makedirs(side_dir)
            with dio.open_file(dio.join(side_dir, name), "wb") as fh:
                fh.write(payload)
        except Exception:  # noqa: BLE001 - best-effort preservation
            pass

    # -- rollup cache --------------------------------------------------------
    #
    # The merged LEFT-FOLD of a dataset's partition sequence, persisted so
    # an append-only growth run folds ``rollup + suffix`` (O(1) state
    # loads) instead of re-loading every partition's states (O(N) — the
    # dominant cost of a fully-reused merge, measured ~1.5ms/blob). The
    # fold is associativity-safe bitwise: ``merge_states_batched`` is a
    # sequential left fold, so ``fold(fold(p1..pk), pk+1..pn)`` equals
    # ``fold(p1..pn)`` exactly. The rollup manifest records the ORDERED
    # (partition, content-checksum) list it folds; any prefix mismatch —
    # changed/dropped/reordered partitions, fingerprint or battery drift —
    # rebuilds from the per-partition states (which remain the source of
    # truth; the rollup is purely a cache).

    def _rollup_dir(self, dataset: str) -> str:
        # lives beside the time buckets; the lister only walks "p-"
        # entries inside buckets, so the rollup never lists as a partition
        return dio.join(
            self.path, "ds-" + _sanitize_namespace_part(str(dataset)),
            "rollup",
        )

    def rollup_provider(self, dataset: str) -> FileSystemStateProvider:
        return FileSystemStateProvider(self._rollup_dir(dataset))

    def rollup_commit(
        self,
        dataset: str,
        *,
        fingerprint: str,
        analyzer_keys: Sequence[str],
        folded: Sequence[Tuple[str, Optional[str]]],
        num_rows: int,
    ) -> None:
        """Record what the persisted rollup states fold — called AFTER
        the merged states persisted (invalidate-first discipline: callers
        `rollup_invalidate` before overwriting the blobs)."""
        d: Dict[str, Any] = {
            "formatVersion": PARTITION_MANIFEST_VERSION,
            "dataset": str(dataset),
            "fingerprint": str(fingerprint),
            "analyzerKeys": [str(k) for k in analyzer_keys],
            "folded": [
                [str(n), None if c is None else str(c)] for n, c in folded
            ],
            "numRows": int(num_rows),
            "createdAtMs": int(time.time() * 1000),
        }
        from ..integrity import checksum_json

        d["checksum"] = checksum_json(d)
        roll_dir = self._rollup_dir(dataset)
        dio.makedirs(roll_dir)
        dio.write_text_atomic(dio.join(roll_dir, _MANIFEST), json.dumps(d))

    def rollup_invalidate(self, dataset: str) -> None:
        path = dio.join(self._rollup_dir(dataset), _MANIFEST)
        if dio.exists(path):
            try:
                self._remove_file(path)
            except Exception:  # noqa: BLE001 - see invalidate()
                _logger.warning(
                    "could not invalidate rollup manifest %s", path,
                    exc_info=True,
                )

    def rollup_get(self, dataset: str) -> Optional["RollupManifest"]:
        """The verified rollup manifest, or None. Corruption quarantines
        and returns None — the rollup is a CACHE; its loss costs a
        re-merge from partition states, never an error."""
        path = dio.join(self._rollup_dir(dataset), _MANIFEST)
        if not dio.exists(path):
            return None
        with dio.open_file(path, "r") as fh:
            payload = fh.read()
        try:
            d = json.loads(payload)
            from ..integrity import verify_json_checksum

            verify_json_checksum(
                {k: v for k, v in d.items() if k != "checksum"},
                d.get("checksum", ""), "rollup manifest", path,
            )
            return RollupManifest(
                dataset=str(d["dataset"]),
                fingerprint=str(d["fingerprint"]),
                analyzer_keys=tuple(d["analyzerKeys"]),
                folded=tuple(
                    (n, None if c is None else str(c))
                    for n, c in d["folded"]
                ),
                num_rows=int(d["numRows"]),
            )
        except Exception as exc:  # noqa: BLE001 - cache loss, not error
            self._quarantine(path, payload, str(exc))
            self.rollup_invalidate(dataset)
            return None

    # -- listing / retention -------------------------------------------------

    def list_partitions(
        self,
        dataset: str,
        after: Optional[str] = None,
        before: Optional[str] = None,
    ) -> List[str]:
        """Committed partition names of ``dataset``, sorted. ``after`` /
        ``before`` (PREFIX-inclusive partition-name bounds — ``"2026-01"``
        includes every ``2026-01-*`` partition) restrict the walk to
        month buckets intersecting the window — the O(queried window)
        listing contract; non-date (hash-bucket) partitions are always
        walked, their names filtered."""
        ds_dir = dio.join(
            self.path, "ds-" + _sanitize_namespace_part(str(dataset))
        )
        out: List[str] = []
        buckets = self._list_dirs(ds_dir)
        if after is None and before is None:
            window = partition_window_months()
            if window > 0:
                # default-window listing: only the most recent N month
                # buckets are walked (hash buckets always are)
                dated = sorted(
                    b for b in buckets if _DATE_BUCKET_RE.match(b + "-")
                )
                keep = set(dated[-window:])
                buckets = [
                    b for b in buckets
                    if b in keep or not _DATE_BUCKET_RE.match(b + "-")
                ]
        for bucket in buckets:
            if _DATE_BUCKET_RE.match(bucket + "-"):
                # a month bucket wholly outside the window cannot contain
                # a partition inside it (bucket == name[:7] for date
                # names): skip the directory walk entirely
                if after is not None and bucket < str(after)[:7]:
                    continue
                if before is not None and bucket > str(before)[:7]:
                    continue
            bucket_dir = dio.join(ds_dir, bucket)
            for entry in self._list_dirs(bucket_dir):
                if not entry.startswith("p-"):
                    continue
                if not dio.exists(
                    dio.join(bucket_dir, entry, _MANIFEST)
                ):
                    continue  # never committed / invalidated
                name = self._unsanitize(entry[2:])
                # prefix-inclusive bounds: compare only the bound's
                # length of the name, so before="2026-05" keeps
                # "2026-05-31"
                if after is not None and name[: len(str(after))] < str(after):
                    continue
                if (
                    before is not None
                    and name[: len(str(before))] > str(before)
                ):
                    continue
                out.append(name)
        return sorted(out)

    @staticmethod
    def _list_dirs(path: str) -> List[str]:
        # an absent prefix lists empty; auth/network failures RAISE (an
        # unreachable store reading as "no partitions" would silently
        # re-scan 100% of the data)
        return dio.list_dirs(path)

    @staticmethod
    def _unsanitize(safe: str) -> str:
        """Invert `_sanitize_namespace_part`'s injective escaping."""
        if safe in ("_.", "_.."):
            return safe[1:]
        out = bytearray()
        i = 0
        while i < len(safe):
            ch = safe[i]
            if ch == "_" and i + 3 <= len(safe):
                try:
                    out.append(int(safe[i + 1:i + 3], 16))
                    i += 3
                    continue
                except ValueError:
                    pass
            out.extend(ch.encode("utf-8"))
            i += 1
        return out.decode("utf-8", errors="replace")

    def delete(self, dataset: str, partition: str) -> bool:
        """Retention: drop one partition's manifest AND state blobs.
        Returns whether anything existed. Metrics stay consistent because
        suite metrics are always a RE-MERGE of the surviving partitions —
        nothing is subtracted from anything."""
        part_dir = self._partition_dir(dataset, partition)
        import os

        if dio.is_local(part_dir) and not os.path.isdir(part_dir):
            return False
        # manifest first: a reader racing the delete sees "never
        # committed", not a manifest whose blobs are vanishing
        try:
            self.invalidate(dataset, partition)
            dio.remove_dir(part_dir)
            return True
        except Exception:  # noqa: BLE001
            return False

    def __repr__(self) -> str:
        return f"PartitionStateStore({self.path!r})"
